"""Mixture-of-Experts feed-forward with expert parallelism.

No reference counterpart (the reference is a dense Llama-style model,
model.py:218-254) — this is a beyond-parity model family completing the
parallelism portfolio (dp/pp/fsdp/sp/tp + **ep**). TPU-native design, the
Switch/GShard dense-dispatch formulation rather than gather/scatter:

- a top-k softmax router (fp32) picks experts per token; weights of the
  kept slots are renormalized to sum to 1;
- routing is *grouped* by batch row (GShard's groups): each row places its
  tokens into per-expert capacity slots ``C = ceil(cf * k * S / E)`` by a
  cumulative count in token order (slot-major priority: all slot-0
  assignments outrank slot-1); overflow tokens are *dropped* for that slot
  (standard Switch semantics — the residual stream still carries them).
  Grouping bounds the dispatch one-hots at (B, S, E, C) instead of a
  global (B*S, E, C) — the difference between ~300 MB and ~10 GB at the
  bench shapes;
- dispatch/combine are one-hot einsums in the compute dtype, so expert
  inputs ``(E, B*C, D)`` and outputs are plain MXU matmuls with static
  shapes; when the ``expert`` mesh axis is >1, XLA inserts the
  token->expert all-to-all from the shardings (the experts' stacked params
  shard over ``expert`` on their leading axis via the path rules,
  parallel/sharding.py);
- the load-balancing auxiliary loss is the Switch formulation
  ``E * sum_e(f_e * P_e)`` (f = fraction of tokens routed to e at slot 0,
  P = mean router probability), sown into the ``losses`` collection and
  added to the training objective by ``model_loss`` with weight
  ``moe_aux_weight`` (a no-op when the collection is not mutable, e.g. in
  eval's forward).

Routing is deterministic given the batch, and the capacity bookkeeping is
computed on the global (jit) view — so expert-parallel runs reproduce the
single-device loss trajectory exactly (tests/test_moe.py).
"""

import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel.mesh import mesh_axis_size
from ..parallel.sharding import constrain
from .configs import TransformerConfig


# lecun_normal with the leading expert dim treated as a batch axis: fan_in
# is the per-expert `in` dim, not E*in (which lecun_normal() would use on an
# (E, in, out) shape, under-scaling the init std by sqrt(E)).
_STACKED_INIT = nn.initializers.variance_scaling(
    1.0, "fan_in", "truncated_normal", batch_axis=(0,))


class _StackedKernel(nn.Module):
    """One (E, in, out) expert-stacked kernel, laid out so the param tree
    path (``experts/w{1,2,3}/kernel``) and init distribution match the
    capacity impl's ``nn.vmap(FeedForward)`` params — the two dispatch
    implementations share checkpoints and sharding rules."""

    shape: Tuple[int, ...]
    param_dtype: Any

    @nn.compact
    def __call__(self):
        return self.param("kernel", _STACKED_INIT, self.shape,
                          self.param_dtype)


class _ExpertKernels(nn.Module):
    """Param holder producing the stacked SwiGLU kernels under
    ``experts/``."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self):
        cfg = self.cfg
        e, d, h = cfg.moe_experts, cfg.dim, cfg.ffn_hidden_dim
        w1 = _StackedKernel((e, d, h), cfg.param_dtype, name="w1")()
        w3 = _StackedKernel((e, d, h), cfg.param_dtype, name="w3")()
        w2 = _StackedKernel((e, h, d), cfg.param_dtype, name="w2")()
        return w1, w3, w2


class MoEFeedForward(nn.Module):
    """Drop-in replacement for the dense SwiGLU FFN (ref: model.py:218-254)
    when ``cfg.moe_experts > 0``; per-expert FFNs keep the reference's
    hidden-dim rounding."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from .llama import FeedForward

        cfg = self.cfg
        E, k = cfg.moe_experts, cfg.moe_top_k
        b, s, d = x.shape

        gates = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="router")(
            x.astype(jnp.float32))
        probs = jax.nn.softmax(gates, axis=-1)  # (B, S, E), fp32
        top_w, top_e = jax.lax.top_k(probs, k)  # (B, S, k)
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)

        # Switch aux loss: E * sum_e f_e * P_e, computed on slot-0 routing
        # over every token in the batch (shared by both dispatch impls)
        f = jnp.mean(jax.nn.one_hot(top_e[..., 0], E, dtype=jnp.float32),
                     axis=(0, 1))
        p = jnp.mean(probs, axis=(0, 1))
        self.sow("losses", "moe_aux", E * jnp.sum(f * p))

        impl = cfg.moe_impl
        if impl == "auto":
            # capacity everywhere: measured on v5e (BASELINE.md), the
            # dropless ragged-dot path runs the expert GEMMs ~50% below
            # dense-GEMM efficiency and loses end-to-end despite doing
            # 2.0x instead of 2.5x FFN FLOPs. "sorted" stays selectable
            # for its semantics (no token dropping).
            impl = "capacity"
        if impl == "sorted":
            if mesh_axis_size("expert") > 1:
                raise ValueError(
                    "moe_impl='sorted' is single-expert-group only; use "
                    "the capacity impl under --ep. Measured rejection "
                    "(BASELINE.md round 3): a dropless exchange needs "
                    "worst-case-padded all-to-all buffers on a static-"
                    "shape compiler, and shard-local ragged GEMM "
                    "throughput collapses with the expert shard size")
            return self._sorted_dispatch(x, top_w, top_e)

        capacity = max(1, math.ceil(cfg.moe_capacity_factor * k * s / E))
        # Per-slot bookkeeping (fp32): position of each token within its
        # expert's capacity if every earlier token (and earlier slot) in
        # its group kept its place; overflow (pos >= capacity) drops.
        count = jnp.zeros((b, E), jnp.float32)  # filled slots per expert
        slot_idx = []
        for slot in range(k):  # k is tiny and static
            oh = jax.nn.one_hot(top_e[..., slot], E, dtype=jnp.float32)
            pos_in_e = (jnp.cumsum(oh, axis=1) - oh) + count[:, None, :]
            pos = jnp.sum(pos_in_e * oh, axis=-1)  # (B, S)
            keep = pos < capacity
            slot_idx.append(jnp.where(
                keep, top_e[..., slot] * capacity + pos.astype(jnp.int32),
                E * capacity))  # dropped -> one index past the last slot
            count = count + jnp.sum(
                oh * keep[..., None].astype(jnp.float32), axis=1)

        # Dispatch one-hot (B, S, E, C) built straight from the flattened
        # slot index in the compute dtype (no fp32 expert-x-position outer
        # products — dropped pairs index one past the end and one_hot
        # zeroes them). The einsum layout stays on ALL meshes: a batched
        # scatter/gather alternative was measured slower on v5e (TPU
        # scatters lose to MXU one-hot matmuls, BASELINE.md), and under
        # --ep this static layout is what the partitioner turns into the
        # token<->expert all-to-all.
        dispatch = jnp.zeros((b, s, E, capacity), cfg.dtype)
        for slot in range(k):
            pos_oh = jax.nn.one_hot(slot_idx[slot], E * capacity,
                                    dtype=cfg.dtype)
            dispatch = dispatch + pos_oh.reshape(b, s, E, capacity)
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x)
        expert_in = constrain(expert_in, "expert_stack", "batch", None,
                              "act_embed")
        experts = nn.vmap(
            FeedForward,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=0, out_axes=0,
            # Experts keep SPLIT w1/w3 matmuls regardless of the dense
            # trunk's fused_w13 default: under the expert vmap the fused
            # form materializes one (E, B, C, 2H) h13 buffer per layer
            # (160 MB at the bench MoE shape) on top of the capacity
            # slots, measured to push the bs-8/50k-vocab MoE config over
            # the 16 GB HBM edge (round 4) — while the fusion's win is a
            # dense-trunk bandwidth effect the slot-dispatched experts
            # don't see.
        )(cfg.replace(fused_w13=False), name="experts")
        expert_out = experts(expert_in)  # (E, B, C, D)
        expert_out = constrain(expert_out, "expert_stack", "batch", None,
                               "act_embed")

        combine = jnp.zeros((b, s, E, capacity), cfg.dtype)
        for slot in range(k):
            pos_oh = jax.nn.one_hot(slot_idx[slot], E * capacity,
                                    dtype=jnp.float32)
            combine = combine + (
                pos_oh * top_w[..., slot][..., None]).astype(
                cfg.dtype).reshape(b, s, E, capacity)
        return jnp.einsum("bsec,ebcd->bsd", combine, expert_out)

    def _sorted_dispatch(self, x, top_w, top_e):
        """Dropless sort-based dispatch over ``jax.lax.ragged_dot``.

        Tokens sort by assigned expert (stable argsort -> deterministic),
        the three SwiGLU matmuls run as ragged grouped GEMMs against the
        (E, in, out) kernel stacks, and a scatter-add combines the k
        weighted expert outputs back per token. No capacity slots and no
        token dropping — every (token, slot) pair computes — and none of
        the (B, S, E, C) dispatch/combine one-hots exist, so the overhead
        beyond the expert GEMMs themselves is one gather, one sort, and
        one scatter of (N*k) rows. Single-expert-group form (the 'expert'
        mesh axis stays with the capacity impl, whose static layout is
        what XLA turns into the token<->expert all-to-all)."""
        cfg = self.cfg
        e_cnt, k = cfg.moe_experts, cfg.moe_top_k
        b, s, d = x.shape
        n = b * s
        w1, w3, w2 = _ExpertKernels(cfg, name="experts")()
        x_flat = x.reshape(n, d)
        eids = top_e.reshape(n * k)      # slot-major per token (t*k + j)
        order = jnp.argsort(eids)        # jnp.argsort is stable
        tok_sorted = jnp.arange(n * k, dtype=jnp.int32)[order] // k
        xs = jnp.take(x_flat, tok_sorted, axis=0)
        group_sizes = jnp.bincount(eids, length=e_cnt).astype(jnp.int32)
        gate = jax.lax.ragged_dot(xs, w1.astype(cfg.dtype), group_sizes)
        up = jax.lax.ragged_dot(xs, w3.astype(cfg.dtype), group_sizes)
        out = jax.lax.ragged_dot(
            (jax.nn.silu(gate) * up).astype(cfg.dtype),
            w2.astype(cfg.dtype), group_sizes)
        w_sorted = top_w.reshape(n * k)[order].astype(jnp.float32)
        y = jnp.zeros((n, d), jnp.float32).at[tok_sorted].add(
            out.astype(jnp.float32) * w_sorted[:, None])
        return y.reshape(b, s, d).astype(x.dtype)
