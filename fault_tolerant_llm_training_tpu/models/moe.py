"""Mixture-of-Experts feed-forward with expert parallelism.

No reference counterpart (the reference is a dense Llama-style model,
model.py:218-254) — this is a beyond-parity model family completing the
parallelism portfolio (dp/pp/fsdp/sp/tp + **ep**). TPU-native design, the
Switch/GShard dense-dispatch formulation rather than gather/scatter:

- a top-k softmax router (fp32) picks experts per token; weights of the
  kept slots are renormalized to sum to 1;
- routing is *grouped* by batch row (GShard's groups): each row places its
  tokens into per-expert capacity slots ``C = ceil(cf * k * S / E)`` by a
  cumulative count in token order (slot-major priority: all slot-0
  assignments outrank slot-1); overflow tokens are *dropped* for that slot
  (standard Switch semantics — the residual stream still carries them).
  Grouping bounds the dispatch one-hots at (B, S, E, C) instead of a
  global (B*S, E, C) — the difference between ~300 MB and ~10 GB at the
  bench shapes;
- dispatch/combine are one-hot einsums in the compute dtype, so expert
  inputs ``(E, B*C, D)`` and outputs are plain MXU matmuls with static
  shapes; when the ``expert`` mesh axis is >1, XLA inserts the
  token->expert all-to-all from the shardings (the experts' stacked params
  shard over ``expert`` on their leading axis via the path rules,
  parallel/sharding.py);
- the load-balancing auxiliary loss is the Switch formulation
  ``E * sum_e(f_e * P_e)`` (f = fraction of tokens routed to e at slot 0,
  P = mean router probability), sown into the ``losses`` collection and
  added to the training objective by ``model_loss`` with weight
  ``moe_aux_weight`` (a no-op when the collection is not mutable, e.g. in
  eval's forward).

Routing is deterministic given the batch, and the capacity bookkeeping is
computed on the global (jit) view — so expert-parallel runs reproduce the
single-device loss trajectory exactly (tests/test_moe.py).
"""

import math

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel.sharding import constrain
from .configs import TransformerConfig


class MoEFeedForward(nn.Module):
    """Drop-in replacement for the dense SwiGLU FFN (ref: model.py:218-254)
    when ``cfg.moe_experts > 0``; per-expert FFNs keep the reference's
    hidden-dim rounding."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from .llama import FeedForward

        cfg = self.cfg
        E, k = cfg.moe_experts, cfg.moe_top_k
        b, s, d = x.shape

        gates = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="router")(
            x.astype(jnp.float32))
        probs = jax.nn.softmax(gates, axis=-1)  # (B, S, E), fp32
        top_w, top_e = jax.lax.top_k(probs, k)  # (B, S, k)
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)

        capacity = max(1, math.ceil(cfg.moe_capacity_factor * k * s / E))
        # dispatch/combine — the two (B, S, E, C) one-hots, by far the
        # largest tensors here — are built directly in the compute dtype:
        # every (token, expert) pair is written by at most one slot (top_k
        # experts are distinct), so no cross-slot add ever rounds. The
        # position/count bookkeeping stays fp32.
        dispatch = jnp.zeros((b, s, E, capacity), cfg.dtype)
        combine = jnp.zeros((b, s, E, capacity), cfg.dtype)
        count = jnp.zeros((b, E), jnp.float32)  # filled slots per expert
        for slot in range(k):  # k is tiny and static
            oh = jax.nn.one_hot(top_e[..., slot], E, dtype=jnp.float32)
            # position of each token within its expert's capacity if every
            # earlier token (and earlier slot) in its group kept its place
            pos_in_e = (jnp.cumsum(oh, axis=1) - oh) + count[:, None, :]
            pos = jnp.sum(pos_in_e * oh, axis=-1)  # (B, S)
            keep = (pos < capacity).astype(jnp.float32)
            pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                    dtype=jnp.float32)
            pair = ((oh * keep[..., None])[..., :, None]
                    * pos_oh[..., None, :])
            dispatch = dispatch + pair.astype(cfg.dtype)
            combine = combine + (
                pair * top_w[..., slot][..., None, None]).astype(cfg.dtype)
            count = count + jnp.sum(oh * keep[..., None], axis=1)

        # Switch aux loss: E * sum_e f_e * P_e, computed on slot-0 routing
        # over every token in the batch
        f = jnp.mean(jax.nn.one_hot(top_e[..., 0], E, dtype=jnp.float32),
                     axis=(0, 1))
        p = jnp.mean(probs, axis=(0, 1))
        self.sow("losses", "moe_aux", E * jnp.sum(f * p))

        # (E, B, C, D): expert axis sharded over 'expert', batch sub-dim
        # over the batch axes — without the batch constraint every
        # data-parallel device would all-gather and compute every group
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x)
        expert_in = constrain(expert_in, "expert_stack", "batch", None,
                              "act_embed")
        experts = nn.vmap(
            FeedForward,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=0, out_axes=0,
        )(cfg, name="experts")
        expert_out = experts(expert_in)  # (E, B, C, D)
        expert_out = constrain(expert_out, "expert_stack", "batch", None,
                               "act_embed")
        return jnp.einsum("bsec,ebcd->bsd", combine, expert_out)
