"""Llama-3-style decoder-only transformer, Flax linen (ref: model.py:9-380).

Architecture parity with the reference:
- RMSNorm with fp32 internal math, cast back, learnable scale (model.py:24-48)
- interleaved-pair RoPE, fp32, precomputed table (model.py:51-126,342-344)
- GQA with separate bias-free wq/wk/wv/wo (model.py:170-177); the reference's
  ``repeat_kv`` copy (model.py:129-138) is replaced by a grouped einsum that
  keeps KV at their native head count (no HBM-bandwidth waste on TPU)
- SwiGLU ``w2(silu(w1 x) * w3 x)`` with the reference's hidden-dim rounding
  (model.py:243-254)
- pre-norm residual blocks, final RMSNorm, untied output head
  (model.py:310-312,350-352,373-380)

TPU-first differences: the RoPE table is a constant folded into the jitted
step (not a buffer); attention dispatches to XLA-einsum / Pallas-flash / ring
(sequence-parallel) kernels; activations carry logical sharding constraints
so the same module traces on 1 CPU device or a v5p pod mesh; optional
``jax.checkpoint`` rematerialization per block.
"""

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.attention import cached_attention, multihead_attention
from ..ops.rope import (
    apply_rope,
    apply_rope_bhsd,
    precompute_rope,
    rope_cos_sin,
)
from ..parallel.mesh import mesh_axis_size
from ..parallel.sharding import constrain
from .configs import TransformerConfig

_DENSE_INIT = nn.initializers.lecun_normal()
_EMBED_INIT = nn.initializers.normal(stddev=0.02)


class RMSNorm(nn.Module):
    """ref: model.py:24-48 — x * rsqrt(mean(x^2) + eps) in fp32, then scale."""

    dim: int
    eps: float = 1e-5
    param_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (self.dim,), self.param_dtype)
        xf = x.astype(jnp.float32)
        normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return normed.astype(x.dtype) * scale.astype(x.dtype)


class TokenEmbed(nn.Module):
    """Token embedding (ref: model.py:340 ``nn.Embedding``).

    Two lookups behind ``cfg.embed_impl``: a plain gather, or an iota
    one-hot matmul. The matmul form matters under tensor parallelism where
    the (vocab, embed) table is vocab-sharded: contracting the vocab axis is
    a clean MXU matmul + psum, whereas a gather from a vocab-sharded table
    forces the SPMD partitioner into an involuntary full rematerialization
    (observed on the dp/fsdp/sp/tp dryrun mesh)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        emb = self.param("embedding", _EMBED_INIT,
                         (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        impl = cfg.embed_impl
        if impl == "auto":
            # one_hot only when the VOCAB dim actually shards ('tensor' /
            # 'pipe' after the divisibility degrade, parallel/sharding.py):
            # there a gather would force the partitioner into involuntary
            # full rematerialization, while contracting vocab is a clean
            # MXU matmul + psum. With the vocab dim replicated (fsdp-only
            # meshes shard the table's FEATURE dim; dp-only meshes nothing)
            # gather stays the impl: the one_hot form was measured to
            # deadlock XLA's in-process CPU collectives on an fsdp-sharded
            # table under sustained multi-step load (2/3 runs on the
            # 8-virtual-device mesh), and gather is cheapest anyway.
            from ..parallel.sharding import shard_size
            impl = ("one_hot" if shard_size(cfg.vocab_size, "vocab") > 1
                    else "gather")
        if impl == "one_hot":
            one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
            # Pin the one-hot to the table's vocab sharding: the iota
            # compare generates each device's slice for free, so no
            # full-V (B, S, V) tensor exists per device.
            one_hot = constrain(one_hot, "batch", "seq", "vocab")
            return one_hot @ emb.astype(cfg.dtype)
        # Gather from a feature-sharded table computes a feature-sharded
        # output the partitioner cannot reshard to the batch-sharded
        # activation layout directly; its last resort is replicate-then-
        # partition plus an involuntary-full-rematerialization warning
        # (fsdp/ep meshes). When the (B, S, D) output is genuinely small,
        # stage that same reshard explicitly (replicate, then the
        # activation constraint re-slices) — identical data movement,
        # voluntary and warning-free. For large global shapes (long
        # context, big batch) forcing full replication would defeat the
        # batch/sequence sharding budget, so the partitioner keeps the
        # choice. (A feature-replicated TABLE constraint was tried
        # instead and deadlocks the in-process CPU collectives — see
        # ROUND_NOTES.md.)
        out = jnp.take(emb, tokens, axis=0).astype(cfg.dtype)
        if out.size * out.dtype.itemsize <= 64 * 2**20:
            out = constrain(out, None, None, None)
        return constrain(out, "batch", "seq", "act_embed")


class _Kernel(nn.Module):
    """Declares a Dense-compatible kernel param (``<name>/kernel``) and
    returns it raw — the fused projection paths (``cfg.fused_qkv`` /
    ``cfg.fused_w13``) contract several projections' kernels in ONE
    matmul while keeping the param tree byte-identical to the separate
    ``nn.Dense`` modules (checkpoints, shardings and the torch converter
    see no difference; init RNG folds over the same module path, so
    initial values match too)."""

    shape: tuple
    param_dtype: Any

    @nn.compact
    def __call__(self):
        return self.param("kernel", _DENSE_INIT, self.shape, self.param_dtype)


class Attention(nn.Module):
    """GQA causal self-attention (ref: model.py:129-215)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions=None, cache=None, adapter=None):
        cfg = self.cfg
        dh = cfg.head_dim
        nq, nkv = cfg.n_heads * dh, cfg.kv_heads * dh
        dense = dict(use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_DENSE_INIT)
        b, s = x.shape[0], x.shape[1]
        # Attention-path resolution BEFORE the projections: the head-major
        # einsum form only pays where a head-major consumer follows (the
        # fused-rope / bhsd kernel branches). On the streaming/ring/XLA
        # paths the canonical transpose-back costs more than the Dense it
        # replaced (S=8192: 39.7k vs 40.3k tokens/s, −1.4% — BASELINE.md
        # round 5), so those keep the Dense projections.
        impl = cfg.attention_impl
        ring = impl in ("auto", "ring") and mesh_axis_size("sequence") > 1
        resolved = impl
        if impl in ("auto", "ring"):
            resolved = "pallas" if jax.default_backend() == "tpu" else "xla"
        from ..ops.flash_attention import rope_fused_profitable
        fused_rope_branch = (not ring and resolved == "pallas"
                             and positions is None and cache is None
                             and cfg.rope_impl == "fused"
                             and rope_fused_profitable(s, dh))
        bhsd_branch = (not fused_rope_branch and not ring
                       and resolved == "pallas" and positions is None
                       and cache is None
                       and cfg.qkv_layout == "bhsd")
        head_major = None  # (qt, kt, vt) in (B, H, S, D) when qkv_einsum
        if cfg.qkv_einsum and (fused_rope_branch or bhsd_branch):
            # Head-major projections: contract x against the (D, H, dh)
            # views so q/k/v land directly in the flash kernels'
            # (B, H, S, D) layout — no activation-side transpose between
            # projection and kernel (pairs with fused_wo on the output
            # side). Only taken when the selected branch consumes
            # head_major natively (see the gate above).
            def proj(name, heads):
                w = _Kernel((cfg.dim, heads * dh), cfg.param_dtype,
                            name=name)()
                return jnp.einsum(
                    "bsd,dhe->bhse", x,
                    w.reshape(cfg.dim, heads, dh).astype(cfg.dtype))
            head_major = (proj("wq", cfg.n_heads), proj("wk", cfg.kv_heads),
                          proj("wv", cfg.kv_heads))
            # Canonical (B, S, H, D) views are built lazily in the branches
            # that consume them (ADVICE r4: materializing them here made
            # the fused branch's correctness depend on XLA DCE, and a
            # later accidental use would silently double-compute).
            q = k = v = None
        elif cfg.fused_qkv:
            # One (D, (H+2K)*dh) matmul over the concatenated kernels:
            # x is read once instead of three times, and the backward's
            # dx / dW each collapse to one dot (autodiff of the concat is
            # a slice). Weight-side concat cost: ~3 MB/layer, negligible.
            wq = _Kernel((cfg.dim, nq), cfg.param_dtype, name="wq")()
            wk = _Kernel((cfg.dim, nkv), cfg.param_dtype, name="wk")()
            wv = _Kernel((cfg.dim, nkv), cfg.param_dtype, name="wv")()
            qkv = x @ jnp.concatenate([wq, wk, wv], axis=1).astype(cfg.dtype)
            q, k, v = (qkv[..., :nq].reshape(b, s, cfg.n_heads, dh),
                       qkv[..., nq:nq + nkv].reshape(b, s, cfg.kv_heads, dh),
                       qkv[..., nq + nkv:].reshape(b, s, cfg.kv_heads, dh))
        else:
            q = nn.Dense(nq, name="wq", **dense)(x).reshape(
                b, s, cfg.n_heads, dh)
            k = nn.Dense(nkv, name="wk", **dense)(x).reshape(
                b, s, cfg.kv_heads, dh)
            v = nn.Dense(nkv, name="wv", **dense)(x).reshape(
                b, s, cfg.kv_heads, dh)

        if cache is not None:
            # Prefill/decode against a KV cache: q/k/v come from the SAME
            # projection impl the training forward selects (fused_qkv or
            # Dense — the fused_rope/bhsd branches are gated off above, so
            # canonical q/k/v always exist here), RoPE gathers from the same
            # precomputed table at absolute positions, and the einsum
            # attention mirrors xla_attention's numerics — cached decode
            # logits bit-match the uncached forward (tests/test_inference.py,
            # tests/test_paged_kv.py). Two cache layouts, dispatched on the
            # tuple arity (inference/kv_cache.py):
            #   (k, v, offsets)                 per-slot ring buffers
            #   (k, v, tables, offsets, valid)  paged block pool
            #   (k, v, tables, offsets, valid, positions, anc)
            #       paged TREE-verify: per-row rope positions + ancestor
            #       visibility over the speculative window
            from ..inference.kv_cache import write_paged_kv, write_slot_kv
            if adapter is not None:
                # Per-slot LoRA delta on the q/v projections (S-LoRA style
                # multi-tenant serving, inference/adapters.py): each batch
                # row carries ITS OWN low-rank factors — gathered from the
                # paged adapter pool by the caller — so one dispatch serves
                # slots bound to different adapters. The batch dim is a
                # PARALLEL dim of both einsums (each row's contraction is
                # independent of its neighbours), and a row whose scale is
                # 0 (the null adapter) selects the base activations through
                # jnp.where BITWISE — adapter-0 streams are bit-identical
                # to a no-adapter engine, and concurrent heterogeneous
                # streams bit-match sequential single-adapter runs.
                # Applied BEFORE RoPE/cache writes: the delta is part of
                # the projection, y = Wx + B(Ax) * (alpha/r).
                a_q, b_q, a_v, b_v, a_scale = adapter
                xf = x.astype(jnp.float32)
                gate = (a_scale > 0.0)[:, None, None, None]
                dq = jnp.einsum("bsd,bdr->bsr", xf, a_q)
                dq = (jnp.einsum("bsr,brn->bsn", dq, b_q)
                      * a_scale[:, None, None])
                q = jnp.where(gate, q + dq.reshape(q.shape).astype(q.dtype),
                              q)
                dv = jnp.einsum("bsd,bdr->bsr", xf, a_v)
                dv = (jnp.einsum("bsr,brn->bsn", dv, b_v)
                      * a_scale[:, None, None])
                v = jnp.where(gate, v + dv.reshape(v.shape).astype(v.dtype),
                              v)
            if len(cache) == 7:
                # Tree-verify: the S rows are one flattened token tree.
                # Node i's KV lands at cache position ``offsets[b] + i``
                # (contiguous — write_paged_kv unchanged) but its ROPE
                # position is ``offsets[b] + depth(i)``: rope encodes the
                # node's distance down its root path, not its row index,
                # so an accepted path's keys are rotated exactly as the
                # sequential decode would have rotated them. Attention
                # swaps the causal rule for the (S, S) ancestor mask.
                (k_pool, v_pool, block_tables, offsets, write_valid,
                 tree_positions, anc_mask) = cache
                t = block_tables.shape[1] * k_pool.shape[2]
                cos, sin = precompute_rope(dh, t, cfg.rope_theta)
                q = apply_rope(q, cos, sin, positions=tree_positions)
                k = apply_rope(k, cos, sin, positions=tree_positions)
                k_pool = write_paged_kv(
                    k_pool, jnp.transpose(k, (0, 2, 1, 3)), block_tables,
                    offsets, write_valid)
                v_pool = write_paged_kv(
                    v_pool, jnp.transpose(v, (0, 2, 1, 3)), block_tables,
                    offsets, write_valid)
                from ..ops.attention import paged_tree_attention
                out = paged_tree_attention(q, k_pool, v_pool, block_tables,
                                           offsets, anc_mask,
                                           impl=cfg.paged_kernel)
                out = out.reshape(b, s, cfg.n_heads * dh)
                return (nn.Dense(cfg.dim, name="wo", **dense)(out),
                        (k_pool, v_pool))
            if len(cache) == 5:
                k_pool, v_pool, block_tables, offsets, write_valid = cache
                # Table rows cover ceil(max_len/bs) blocks; rope rows are
                # per-position, so the (possibly longer) gathered T only
                # adds masked tail rows — values at shared positions are
                # identical to the ring path's table.
                t = block_tables.shape[1] * k_pool.shape[2]
                cos, sin = precompute_rope(dh, t, cfg.rope_theta)
                pos = (offsets[:, None]
                       + jnp.arange(s, dtype=jnp.int32)[None, :])
                q = apply_rope(q, cos, sin, positions=pos)
                k = apply_rope(k, cos, sin, positions=pos)
                # Scatter ONLY the new tokens through the block table
                # BEFORE attending (so they attend to themselves); invalid
                # positions (pad/inactive) divert to null block 0.
                k_pool = write_paged_kv(
                    k_pool, jnp.transpose(k, (0, 2, 1, 3)), block_tables,
                    offsets, write_valid)
                v_pool = write_paged_kv(
                    v_pool, jnp.transpose(v, (0, 2, 1, 3)), block_tables,
                    offsets, write_valid)
                # paged_attention dispatches on (impl, S): under "pallas"
                # both the S=1 decode read and S>1 chunk reads (chunked /
                # packed prefill, chunk-mode spec-verify) stay in place —
                # this batch-general path is also what the packed
                # multi-request prefill program runs at B > 1.
                from ..ops.attention import paged_attention
                out = paged_attention(q, k_pool, v_pool, block_tables,
                                      offsets, impl=cfg.paged_kernel)
                out = out.reshape(b, s, cfg.n_heads * dh)
                return (nn.Dense(cfg.dim, name="wo", **dense)(out),
                        (k_pool, v_pool))
            k_cache, v_cache, offsets = cache
            t = k_cache.shape[2]
            cos, sin = precompute_rope(dh, t, cfg.rope_theta)
            pos = offsets[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
            q = apply_rope(q, cos, sin, positions=pos)
            k = apply_rope(k, cos, sin, positions=pos)
            # Write the rotated keys/values head-major at each slot's next
            # position (mod T: the ring wraps per slot) BEFORE attending, so
            # the new tokens attend to themselves through the cache.
            k_cache = write_slot_kv(k_cache, jnp.transpose(k, (0, 2, 1, 3)),
                                    offsets % t)
            v_cache = write_slot_kv(v_cache, jnp.transpose(v, (0, 2, 1, 3)),
                                    offsets % t)
            out = cached_attention(q, k_cache, v_cache, offsets)
            out = out.reshape(b, s, cfg.n_heads * dh)
            return (nn.Dense(cfg.dim, name="wo", **dense)(out),
                    (k_cache, v_cache))

        if fused_rope_branch:
            # RoPE inside the kernels (ops/flash_attention.py
            # flash_attention_rope): raw head-major q/k/v plus the
            # interleave-duplicated (S, D) tables. No rotated q/k or rope
            # backward exists at the XLA level. Long-context shapes fall
            # through to XLA rope (see rope_fused_profitable).
            from ..ops.flash_attention import flash_attention_rope
            cos, sin = precompute_rope(dh, cfg.seq_len, cfg.rope_theta)
            cos2 = jnp.repeat(cos[:s], 2, axis=-1)
            sin2 = jnp.repeat(sin[:s], 2, axis=-1)
            if head_major is not None:  # qkv_einsum: already (B, H, S, D)
                qt_in, kt_in, vt_in = head_major
            else:
                qt_in = jnp.transpose(q, (0, 2, 1, 3))
                kt_in = jnp.transpose(k, (0, 2, 1, 3))
                vt_in = jnp.transpose(v, (0, 2, 1, 3))
            out_t = flash_attention_rope(qt_in, kt_in, vt_in,
                                         cos2, sin2, True)
            if cfg.fused_wo:
                # Contract the kernel's head-major output against the
                # (H, dh, D) view of wo directly — the explicit
                # (B,H,S,D) -> (B,S,H*dh) relayout disappears into the
                # matmul's own layout handling.
                wo = _Kernel((nq, cfg.dim), cfg.param_dtype, name="wo")()
                return jnp.einsum(
                    "bhsd,hde->bse", out_t,
                    wo.reshape(cfg.n_heads, dh, cfg.dim).astype(cfg.dtype))
            out = jnp.transpose(out_t, (0, 2, 1, 3))
        elif bhsd_branch:
            # Kernel-native layout path: transpose BEFORE rope so the rope
            # fusion computes in (and emits) exactly the (B, H, S, D)
            # layout the Pallas custom call consumes — the bshd path below
            # pays fp32 relayout copies at the boundary instead (the
            # 11.5 ms/step copy family in the BASELINE.md profile).
            from ..ops.flash_attention import flash_attention_bhsd
            cos, sin = precompute_rope(dh, cfg.seq_len, cfg.rope_theta)
            if head_major is not None:  # qkv_einsum: already (B, H, S, D)
                qh, kh, vh = head_major
            else:
                qh = jnp.transpose(q, (0, 2, 1, 3))
                kh = jnp.transpose(k, (0, 2, 1, 3))
                vh = jnp.transpose(v, (0, 2, 1, 3))
            qt = apply_rope_bhsd(qh, cos, sin)
            kt = apply_rope_bhsd(kh, cos, sin)
            vt = vh
            out = jnp.transpose(flash_attention_bhsd(qt, kt, vt, True),
                                (0, 2, 1, 3))
        else:
            # With sequence parallelism each shard holds a non-prefix
            # slice of the sequence; cos/sin come from a positions x freqs
            # outer product (sharded with the activations) rather than a
            # table gather, which the SPMD partitioner can only reshard by
            # full rematerialization.
            # head_major cannot reach here: the einsum projections are
            # gated on (fused_rope_branch or bhsd_branch) above, so this
            # path always has canonical Dense q/k/v.
            if positions is None:
                cos, sin = precompute_rope(dh, cfg.seq_len, cfg.rope_theta)
            else:
                cos, sin = rope_cos_sin(dh, cfg.rope_theta, positions)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            if ring:
                from ..ops.ring_attention import ring_attention
                out = ring_attention(q, k, v, axis_name="sequence",
                                     zigzag=(cfg.sp_layout == "zigzag"))
            else:
                if impl == "ring":  # ring requested but no sequence axis
                    impl = "auto"
                out = multihead_attention(q, k, v, impl=impl, causal=True)
        out = out.reshape(b, s, cfg.n_heads * dh)
        return nn.Dense(cfg.dim, name="wo", **dense)(out)


class FeedForward(nn.Module):
    """SwiGLU FFN (ref: model.py:218-254): w2(silu(w1 x) * w3 x)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        hidden = cfg.ffn_hidden_dim
        dense = dict(use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_DENSE_INIT)
        if cfg.fused_w13:
            # Gate and up in ONE (D, 2*hidden) matmul (see _Kernel): x is
            # read once, and the backward's dx is one dot instead of two
            # accumulated ones.
            w1 = _Kernel((cfg.dim, hidden), cfg.param_dtype, name="w1")()
            w3 = _Kernel((cfg.dim, hidden), cfg.param_dtype, name="w3")()
            h13 = x @ jnp.concatenate([w1, w3], axis=1).astype(cfg.dtype)
            gate, up = h13[..., :hidden], h13[..., hidden:]
        else:
            gate = nn.Dense(hidden, name="w1", **dense)(x)
            up = nn.Dense(hidden, name="w3", **dense)(x)
        return nn.Dense(cfg.dim, name="w2", **dense)(jax.nn.silu(gate) * up)


class TransformerBlock(nn.Module):
    """Pre-norm residual block (ref: model.py:257-312)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions=None, cache=None, adapter=None):
        cfg = self.cfg
        normed = RMSNorm(cfg.dim, cfg.norm_eps, cfg.param_dtype,
                         name="attention_norm")(x)
        attn = Attention(cfg, name="attention")
        new_cache = None
        if cache is not None:
            attn_out, new_cache = attn(normed, positions, cache, adapter)
        else:
            attn_out = attn(normed, positions)
        h = x + attn_out
        h = constrain(h, "batch", "seq", "act_embed")
        if cfg.moe_experts:
            from .moe import MoEFeedForward
            ffn = MoEFeedForward(cfg, name="feed_forward")
        else:
            ffn = FeedForward(cfg, name="feed_forward")
        out = h + ffn(
            RMSNorm(cfg.dim, cfg.norm_eps, cfg.param_dtype, name="ffn_norm")(h))
        out = constrain(out, "batch", "seq", "act_embed")
        return out if cache is None else (out, new_cache)


class _ScanBlock(nn.Module):
    """Scan adapter: gives TransformerBlock the (carry, x) -> (carry, y)
    shape ``nn.scan`` requires; params nest one level deeper
    (``layers/block/...`` with a leading n_layers axis)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        block = TransformerBlock
        if self.cfg.remat:
            # prevent_cse=False: the scan's while loop already prevents
            # cross-iteration CSE, so the extra optimization barriers the
            # default inserts would only block in-body fusion
            block = nn.remat(TransformerBlock, prevent_cse=False,
                             static_argnums=())
        return block(self.cfg, name="block")(x, positions), None


class Transformer(nn.Module):
    """Trunk: embed -> n_layers blocks -> final norm -> untied head
    (ref: model.py:315-380).

    The reference's 32 distinct ``ModuleDict`` blocks (model.py:346-348)
    map to ``layer_impl="loop"``; ``"scan"`` is the TPU-idiomatic form —
    one block body compiled once by XLA and scanned over layer-stacked
    params, so compile time stops growing with depth.

    Setup-style (not compact) so the pipeline-parallel step can drive the
    pieces separately via ``apply(..., method="embed"/"head")`` while
    ``__call__`` stays the single-call path; attribute names keep the param
    tree byte-compatible with the compact form (``tok_embeddings``,
    ``layers_{i}`` / ``layers/block``, ``norm``, ``output``)."""

    cfg: TransformerConfig

    def setup(self):
        cfg = self.cfg
        self.tok_embeddings = TokenEmbed(cfg)
        if cfg.layer_impl == "scan":
            self.layers = nn.scan(
                _ScanBlock,
                # 'losses': per-layer MoE router aux (models/moe.py sow)
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                in_axes=nn.broadcast,
                # NOTE: nn.scan(unroll=N) was measured and rejected: 62.6k
                # tokens/s at unroll 2 or 4 vs 80.6k at 1 on the headline
                # bench (v5e) — the unrolled bodies' param-stack slices
                # cost more than the recovered cross-layer fusion.
            )(cfg)
        else:
            block = TransformerBlock
            if cfg.remat:
                block = nn.remat(TransformerBlock, static_argnums=())
            # a module list attribute named ``layers`` yields param keys
            # layers_0..layers_{N-1}, matching the reference's ModuleDict
            self.layers = [block(cfg) for _ in range(cfg.n_layers)]
        self.norm = RMSNorm(cfg.dim, cfg.norm_eps, cfg.param_dtype)
        self.output = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_DENSE_INIT)

    def embed(self, tokens):
        x = self.tok_embeddings(tokens)
        return constrain(x, "batch", "seq", "act_embed")

    def head(self, x):
        x = self.norm(x)
        logits = self.output(x)
        return constrain(logits, "batch", "seq", "vocab")

    def default_positions(self, seq_len: int):
        """(1, S) prefix positions — same cos/sin values as the
        precomputed-table path in Attention, broadcasting over batch."""
        return jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def hidden_states(self, tokens, positions=None):
        """embed -> trunk -> final norm, WITHOUT the output projection —
        the fused head+CE loss (ops/fused_ce.py) consumes these and blocks
        the head matmul into the loss so logits never materialize."""
        cfg = self.cfg
        x = self.embed(tokens)
        if cfg.layer_impl == "scan":
            if positions is None:
                # scan broadcasts positions to the body; materialize them
                positions = self.default_positions(tokens.shape[1])
            x, _ = self.layers(x, positions)
        else:
            for layer in self.layers:
                x = layer(x, positions)
        return self.norm(x)

    def __call__(self, tokens, positions=None):
        logits = self.output(self.hidden_states(tokens, positions))
        return constrain(logits, "batch", "seq", "vocab")

    def forward_with_cache(self, tokens, cache_k, cache_v, offsets,
                           block_tables=None, write_valid=None,
                           adapter=None):
        """Prefill/decode forward through per-layer KV caches.

        ``tokens`` (B, S) occupy absolute positions ``offsets[b] + [0, S)``;
        each layer attends against (and appends to) its buffers from
        ``cache_k``/``cache_v`` (length-n_layers sequences). With
        ``block_tables`` None the buffers are per-slot (B, K, T, D) ring
        buffers; with ``block_tables`` (B, NB) they are paged (N, K, bs, D)
        block pools, writes route through the table, and ``write_valid``
        (B, S) masks which new positions are real (padding/inactive writes
        divert to null block 0; default: all valid). Loop trunk only — the
        inference engine converts scan-form checkpoints with
        :func:`unstack_layer_params`. ``adapter`` is an optional
        length-n_layers sequence of per-layer LoRA operand tuples
        ``(A_q, B_q, A_v, B_v, scale)`` — each factor with a leading batch
        dim, sliced by the engine from its paged adapter pool
        (inference/adapters.py); None means base-only everywhere. Returns
        ``(logits, (new_cache_k, new_cache_v))``.
        """
        if self.cfg.layer_impl != "loop":
            raise ValueError(
                "forward_with_cache requires layer_impl='loop'; convert "
                "scan-form checkpoints with unstack_layer_params")
        if block_tables is not None and write_valid is None:
            write_valid = jnp.ones(tokens.shape, jnp.bool_)
        x = self.embed(tokens)
        new_k, new_v = [], []
        for i, layer in enumerate(self.layers):
            c = ((cache_k[i], cache_v[i], offsets) if block_tables is None
                 else (cache_k[i], cache_v[i], block_tables, offsets,
                       write_valid))
            x, (k_i, v_i) = layer(x, None, c,
                                  None if adapter is None else adapter[i])
            new_k.append(k_i)
            new_v.append(v_i)
        return self.head(x), (tuple(new_k), tuple(new_v))

    def verify_with_cache(self, tokens, cache_k, cache_v, offsets,
                          block_tables, write_valid=None):
        """Speculative-decoding verify entry: score k+1 candidate positions
        per slot in one forward through the paged caches.

        ``tokens`` (B, k+1) is ``[last_committed, d_1 .. d_k]`` at absolute
        positions ``offsets[b] + [0, k]``; each row's logits are the
        target's next-token scores AFTER that prefix — the same masked
        attention the j-th sequential single-token decode computes
        (ops/attention.py ``paged_attention`` documents the masking
        argument), though only equal to it up to shape-dependent bf16 GEMM
        accumulation order: a one-ulp logit near-tie can flip an argmax
        between the chunked and single-step programs, which is why the
        engine's AOT verify program micro-steps S=1 forwards when bitwise
        greedy equivalence is required (inference/engine.py
        ``_verify_fn``). Paged layout only — the verify semantics
        depend on masked writes diverting to the null block so a rejected
        suffix can be abandoned without device-side rollback. This is a thin
        named delegation to :meth:`forward_with_cache`: the multi-token path
        there IS the verify math; the entry pins the contract (and gives the
        engine's AOT verify program a stable method name).
        """
        if block_tables is None:
            raise ValueError("verify_with_cache requires the paged layout "
                             "(block_tables)")
        return self.forward_with_cache(tokens, cache_k, cache_v, offsets,
                                       block_tables=block_tables,
                                       write_valid=write_valid)

    def tree_verify_with_cache(self, tokens, cache_k, cache_v, offsets,
                               block_tables, tree_positions, anc_mask,
                               write_valid=None):
        """Tree-speculative verify: score one flattened S-node token TREE
        per slot in a single forward through the paged caches.

        ``tokens`` (B, S) is ``[last_committed, node_1 .. node_{S-1}]`` in
        topological order; node i's KV is written at cache position
        ``offsets[b] + i`` while its rope position is ``tree_positions[b,
        i] = offsets[b] + depth(i)``, and attention inside the speculative
        window follows ``anc_mask`` (S, S) — ancestors ∪ self ∪ root —
        instead of the causal rule (ops/attention.py
        ``paged_tree_attention``). Row i's logits are therefore the
        target's next-token law after node i's root path, for EVERY branch
        of the tree in one dispatch. When the tree degenerates to a chain
        the mask equals the causal one and this reproduces
        :meth:`verify_with_cache` bit-for-bit on the gather impl (the
        chunk-mode caveat there about bf16 shape-dependent accumulation
        vs S=1 micro-steps applies unchanged — hence the engine's
        ``exact`` escape hatch scores only the primary chain).
        """
        if block_tables is None:
            raise ValueError("tree_verify_with_cache requires the paged "
                             "layout (block_tables)")
        if self.cfg.layer_impl != "loop":
            raise ValueError(
                "tree_verify_with_cache requires layer_impl='loop'; convert "
                "scan-form checkpoints with unstack_layer_params")
        if write_valid is None:
            write_valid = jnp.ones(tokens.shape, jnp.bool_)
        x = self.embed(tokens)
        new_k, new_v = [], []
        for i, layer in enumerate(self.layers):
            c = (cache_k[i], cache_v[i], block_tables, offsets, write_valid,
                 tree_positions, anc_mask)
            x, (k_i, v_i) = layer(x, None, c)
            new_k.append(k_i)
            new_v.append(v_i)
        return self.head(x), (tuple(new_k), tuple(new_v))


def stack_layer_params(params: dict, n_layers: int) -> dict:
    """Convert a loop-form param tree (``layers_{i}/...``) to the scan form
    (``layers/block/...`` leaves with a leading n_layers axis)."""
    layers = [params[f"layers_{i}"] for i in range(n_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    out = {k: v for k, v in params.items() if not k.startswith("layers_")}
    out["layers"] = {"block": stacked}
    return out


def unstack_layer_params(params: dict, n_layers: int) -> dict:
    """Inverse of :func:`stack_layer_params`."""
    stacked = params["layers"]["block"]
    out = {k: v for k, v in params.items() if k != "layers"}
    for i in range(n_layers):
        out[f"layers_{i}"] = jax.tree_util.tree_map(lambda a: a[i], stacked)
    return out
