"""Model configuration (ref: model.py:9-21 ``TransformerModelArgs``).

The reference's dataclass defaults (dim 4096 / 32 layers / rope_theta 10000 /
multiple_of 256) are *overridden* by the trainer to the Llama-3-8B shape
(ref: train.py:43-53: n_kv_heads=8, ffn_dim_multiplier=1.3, multiple_of=1024,
rope_theta=500000, vocab from tokenizer). Both shapes are exposed here as
named presets; the headline benchmark preset is the GPT-2-125M-class config
from BASELINE.json.
"""

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    # --- architecture (ref: model.py:9-21) ---
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: Optional[int] = None
    multiple_of: int = 256  # SwiGLU hidden rounded up to a multiple of this
    ffn_dim_multiplier: Optional[float] = None
    norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    seq_len: int = 2048
    vocab_size: int = -1
    # --- TPU compute options (reference: global default dtype, train.py:54) ---
    dtype: jnp.dtype = jnp.bfloat16  # activations / compute
    param_dtype: jnp.dtype = jnp.bfloat16  # weights (and hence AdamW moments)
    attention_impl: str = "auto"
    # Paged-KV attention kernel (every serving read through block tables:
    # S=1 decode AND S>1 chunked prefill / chunk-mode spec-verify):
    # "gather" assembles each slot's blocks into a contiguous view and
    # runs the ring kernel on it (bit-exact reference), "pallas" reads
    # pool blocks in place through the block table — the decode kernel
    # for S=1, the chunk kernel for S>1 (ops/paged_attention.py; no
    # gathered copy either way; equal to gather within fp32 accumulation
    # tolerance). Training never reads this field.
    paged_kernel: str = "gather"
    # Sequence layout under sequence parallelism: "zigzag" (each shard holds
    # one early + one mirrored late chunk — balances causal work around the
    # ring at ~2x fewer FLOPs; ops/ring_attention.py) or "contiguous".
    sp_layout: str = "zigzag"
    # Token-embedding lookup: "gather" (jnp.take), "one_hot" (iota one-hot
    # matmul — contracts the vocab axis on the MXU with a psum, which is how
    # a vocab-sharded table must be read under tensor parallelism), or
    # "auto" (one_hot iff the mesh's tensor axis is >1).
    embed_impl: str = "auto"
    # Trunk form: "loop" unrolls n_layers distinct blocks (params
    # layers_{i}/...); "scan" runs one block body under lax.scan over
    # layer-stacked params (params layers/block/... with a leading
    # n_layers axis) — XLA compiles the body once, so compile time is
    # O(1) in depth instead of O(n_layers) (measured on CPU: 53 s vs 9 s
    # at depth 64), at ~19% step-time cost on TPU from lost cross-layer
    # fusion (98.3k -> 80.0k tokens/s on the headline bench). Both compute
    # identical functions; models/llama.py has the param-layout converters.
    layer_impl: str = "loop"
    # Merge the three attention projections into ONE matmul against the
    # concatenated (D, (H+2K)*dh) kernel (params stay the separate
    # wq/wk/wv trees — the concat is a per-step weight-side reshape that
    # XLA folds). Measured REJECTION on v5e (BASELINE.md round 4:
    # 110.3k vs 113.8k base, and still -2% on top of the other round-4
    # wins) — kept as an option for other generations.
    fused_qkv: bool = False
    # Contract wo against the flash kernel's head-major output via einsum
    # instead of transpose+reshape+Dense (rope_impl="fused" path only).
    # Param tree unchanged (_Kernel). Default ON: +2.1% headline, +0.8%
    # at bs 16 (BASELINE.md round 4).
    fused_wo: bool = True
    # Project q/k/v via 'bsd,dhe->bhse' einsums so they land head-major
    # (the input-side mirror of fused_wo). Measured neutral in round 4;
    # under round 5's blocked lse layout it WINS both regimes — +0.9%
    # headline (124.2k vs 123.1k) and +3.6% at bs 16 (118.6k vs 114.5k),
    # the reduced allocator pressure evidently freeing the input-side
    # transpose elision to pay off (BASELINE.md round 5). Default ON.
    qkv_einsum: bool = True
    # SwiGLU gate+up in one (D, 2*hidden) matmul, split after. Default ON:
    # +2.2% on the headline bench stacked on the in-kernel rope
    # (BASELINE.md round 4); parity with separate matmuls is reduction-
    # order-only (tested).
    fused_w13: bool = True
    # Where RoPE is computed: "xla" = elementwise fp32 rope on (B,S,H,D)
    # activations (ops/rope.py apply_rope — reference-parity math);
    # "fused" = inside the Pallas flash kernels via the J-matrix rotation
    # (ops/flash_attention.py flash_attention_rope) — no rotated q/k or
    # fp32 rope intermediate ever materializes at the XLA level, which
    # removes the rope-adjacent relayout-copy family at the custom-call
    # boundary. "fused" engages only on the single-chip pallas path with
    # prefix positions AND within the fused-backward S*D budget (the
    # streaming kernels re-rope K per tile fetch, measured net-negative
    # past S=4096/D=64 — ops/flash_attention.py rope_fused_profitable);
    # other shapes/paths fall back to "xla" automatically. Default "fused": +3.7% headline and the
    # fp32 relayout-copy family at the custom-call boundary disappears
    # from the profile (BASELINE.md round 4); parity with the xla path is
    # pinned to fp32 noise in tests/test_flash_attention.py.
    rope_impl: str = "fused"
    # Layout of the rope+flash-attention chain: "bshd" reshapes to
    # (B, S, H, D), applies rope, and lets the kernel wrapper transpose to
    # the (B, H, S, D) the TPU tiles need — XLA inserts fp32 layout copies
    # at the custom-call boundary (the 11.5 ms/step "copy" family in the
    # BASELINE.md profile). "bhsd" transposes FIRST and applies rope in
    # the kernel-native layout so the rope fusion emits exactly what the
    # custom call consumes. Only the single-chip pallas path honors
    # "bhsd"; ring/xla paths keep bshd — and rope_impl="fused" (the
    # default) SUPERSEDES it entirely: the fused-rope branch feeds the
    # kernel raw head-major operands itself, so "bhsd" only changes
    # anything under rope_impl="xla" (measured +0.5% there, round 4 —
    # kept as the layout experiment knob for the non-fused path).
    qkv_layout: str = "bshd"
    # Pipeline-parallel schedule (parallel/pipeline.py; only read when the
    # mesh's pipe axis is >1): "1f1b" interleaves each microbatch's
    # backward as soon as its loss gradient exists — activation memory
    # O(pp) with the head+CE fused into the tick loop; "gpipe" is the
    # store-everything forward scan whose autodiff replays the reverse
    # pipeline — memory O(microbatches), kept as a fallback/baseline.
    pp_schedule: str = "1f1b"
    # Unroll each pipeline STAGE's layer loop (a static Python loop over
    # the stage's slice of the layer stack) instead of lax.scan-ing it.
    # Params stay scan-form/stacked (the 'pipe' sharding needs the
    # leading layer axis); only the stage body's control flow changes —
    # this is the PP analogue of layer_impl="loop", recovering the
    # cross-layer fusion whose loss costs the scan trunk ~19-28% on TPU
    # (BASELINE.md rounds 2/4). Default ON, on two measurements of the
    # exact compute pattern: the static unroll over stacked params is
    # 22.5% faster than the lax.scan form ON THE CHIP
    # (scripts/stage_unroll_bench.py: 148.4 vs 191.5 ms fwd+bwd at the
    # bench shape — distinct from the REJECTED nn.scan(unroll=N), whose
    # in-scan dynamic param slicing regressed 22%) and 20% faster on the
    # CPU mesh through the full 1F1B step (scripts/pp_bench.py), with
    # bit-identical losses. The price is compile time proportional to
    # layers-per-stage (L/P — already P-fold smaller than the loop
    # trunk's); --no-pp-stage-unroll restores O(1)-compile scanning for
    # very deep stages.
    pp_stage_unroll: bool = True
    remat: bool = False
    # --- Mixture of Experts (models/moe.py; 0 experts = dense reference
    # FFN). Experts shard over the mesh's 'expert' axis (--ep). ---
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Dispatch implementation: "capacity" = GShard static capacity slots
    # (drops overflow; its static (E, B, C, D) layout is what XLA turns
    # into the expert all-to-all under --ep), "sorted" = dropless
    # sort + ragged-dot grouped GEMMs (single expert group only). "auto"
    # resolves to capacity everywhere — measured faster on v5e than the
    # ragged-dot path (models/moe.py) — sorted is an explicit opt-in for
    # its no-token-dropping semantics.
    moe_impl: str = "auto"

    def __post_init__(self):
        # Unknown values would otherwise silently select a default branch
        # (e.g. a layer_impl typo benchmarking the wrong trunk form).
        for field, allowed in (("layer_impl", ("loop", "scan")),
                               ("pp_schedule", ("1f1b", "gpipe")),
                               ("sp_layout", ("zigzag", "contiguous")),
                               ("qkv_layout", ("bshd", "bhsd")),
                               ("rope_impl", ("xla", "fused")),
                               ("attention_impl",
                                ("auto", "xla", "pallas", "ring")),
                               ("paged_kernel", ("gather", "pallas")),
                               ("embed_impl", ("auto", "gather", "one_hot")),
                               ("moe_impl",
                                ("auto", "capacity", "sorted"))):
            if getattr(self, field) not in allowed:
                raise ValueError(
                    f"{field}={getattr(self, field)!r} not in {allowed}")
        if self.moe_experts:
            if not 1 <= self.moe_top_k <= self.moe_experts:
                raise ValueError(
                    f"moe_top_k={self.moe_top_k} must be in "
                    f"[1, moe_experts={self.moe_experts}]")
            if self.moe_capacity_factor <= 0:
                raise ValueError("moe_capacity_factor must be positive")

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_hidden_dim(self) -> int:
        """SwiGLU hidden size with the reference's exact rounding
        (ref: model.py:243-247): int(2/3 * 4d), scaled by the multiplier,
        rounded *up* to a multiple of ``multiple_of``.
        8B preset: 4*4096=16384 -> 10922 -> *1.3 -> 14198 -> 14336."""
        hidden = int(2 * (4 * self.dim) / 3)
        if self.ffn_dim_multiplier is not None:
            hidden = int(self.ffn_dim_multiplier * hidden)
        return self.multiple_of * ((hidden + self.multiple_of - 1) // self.multiple_of)

    def param_count(self) -> int:
        """Exact parameter count (untied output head, ref: model.py:350-352).
        With MoE: E expert FFNs plus the router matrix per block."""
        d, v, h = self.dim, self.vocab_size, self.ffn_hidden_dim
        qkv = d * (self.n_heads * self.head_dim) + 2 * d * (self.kv_heads * self.head_dim)
        attn = qkv + (self.n_heads * self.head_dim) * d
        ffn = 3 * d * h
        if self.moe_experts:
            ffn = self.moe_experts * ffn + d * self.moe_experts  # + router
        per_layer = attn + ffn + 2 * d  # two RMSNorm scales per block
        return v * d + self.n_layers * per_layer + d + d * v  # embed + blocks + final norm + head

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)


PRESETS = {
    # Exact reference trainer shape (ref: train.py:43-53); ~8.05B params at
    # the Mistral-Nemo vocab of 131072.
    "llama3-8b": TransformerConfig(
        dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        ffn_dim_multiplier=1.3, multiple_of=1024, rope_theta=500000.0,
        vocab_size=131072, seq_len=2048,
    ),
    # BASELINE.json headline config: GPT-2-125M-class decoder in the same
    # Llama-style architecture family (SwiGLU/RoPE/RMSNorm).
    "gpt2-125m": TransformerConfig(
        dim=768, n_layers=12, n_heads=12, n_kv_heads=12,
        multiple_of=256, rope_theta=10000.0, vocab_size=50257, seq_len=2048,
    ),
    # Hermetic-test shape.
    "tiny": TransformerConfig(
        dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, rope_theta=10000.0, vocab_size=512, seq_len=128,
    ),
    # Hermetic 4-layer shape: the speculative-decoding bench/test target
    # (scripts/decode_bench.py spec_decode — "tiny" is its natural draft).
    "tiny-4l": TransformerConfig(
        dim=64, n_layers=4, n_heads=4, n_kv_heads=2,
        multiple_of=32, rope_theta=10000.0, vocab_size=512, seq_len=128,
    ),
    # Hermetic MoE shape (models/moe.py): 4 experts, top-2 routing.
    "tiny-moe": TransformerConfig(
        dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, rope_theta=10000.0, vocab_size=512, seq_len=128,
        moe_experts=4, moe_top_k=2,
    ),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown model preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name].replace(**overrides)
