"""Pallas paged-attention kernels: block-indexed KV reads in place.

The XLA-level paged path (ops/attention.py ``paged_cached_attention``)
gathers each slot's pool blocks into a transient contiguous (B, K, T, D)
copy and runs the ring kernel's einsum on it — correct by construction,
but the gather is an extra full-cache pass per layer per dispatch. This
module is the serving-side member of the repo's Pallas kernel family
(flash_attention.py prefill, ring_flash.py sequence-parallel): the block
table rides in as a scalar-prefetch operand, the grid's innermost axis
walks a slot's logical blocks, and each step's BlockSpec index map sends
the DMA straight at ``pool[tables[b, j]]`` — the pool is read THROUGH
the table with no gathered intermediate, vLLM's PagedAttention fused
with flash-decoding's split-KV online softmax.

Two kernels share that structure, dispatched by ``ops/attention.py
paged_attention(impl=)`` on the query length:

- :func:`paged_decode_attention` — S = 1 (the decode step; the query
  row is the slot's GQA group, (G, D)).
- :func:`paged_chunk_attention` — S > 1 (chunked prefill, chunk-mode
  spec-verify): the same grid, the q block widened to the chunk's
  S*G rows, the causal boundary applied per row.
- :func:`paged_tree_chunk_attention` — S > 1 TREE-verify (tree
  speculative decoding): the chunk kernel with the speculative window's
  causal rule replaced by a per-row ancestor mask, dispatched by
  ``ops/attention.py paged_tree_attention(impl=)``.

MASKING (the single statement of the rationale, for both kernels and
for the gather reference that ops/attention.py keeps selectable):
everything is positional. A query at absolute position ``p`` attends
keys at ``k_pos <= p`` — decode has one position per slot
(``offsets[b]``), a chunk has ``offsets[b] + s`` for its s-th row.
Everything the gather path neutralizes with its additive ``finfo.min``
mask — null-block-0 garbage behind unallocated table entries, stale KV
in freed-and-reused blocks, the written-ahead tail of a COW'd final
block, the unwritten pad tail of a partial prefill chunk — sits past
that per-row boundary, so the same comparison excludes it here: masked
lanes get ``exp2(NEG_INF - m) == 0`` probability exactly, and blocks
that start past the LAST row's boundary are skipped wholesale
(``@pl.when``), never touching the accumulator. The output is therefore
bitwise invariant to the bytes in masked positions (asserted,
tests/test_paged_kernel.py). Shared prefix blocks need no handling at
all: a block referenced by several rows is simply DMA'd for each, same
bytes.

Numerics follow the house flash-decoding scheme (flash_attention.py):
base-2 online softmax with ``log2(e)`` folded into the q prescale, fp32
(m, l, acc) carried in VMEM scratch across the block axis, one rescale +
normalize at the last block. Accumulation order therefore differs from
the gather path's full-row softmax — equality holds to fp32 accumulation
tolerance, not bitwise, which is why the engine keeps the gather program
selectable as the bit-exact reference (``--paged-kernel gather``).

QUANTIZED POOLS (``--kv-dtype int8``): when the pools arrive as
``kv_cache.QuantPool`` (int8 data + per-(block, kv-head) fp32 scales),
the scale pools ride along as two extra scalar-prefetch operands —
(N, K) fp32 in SMEM, looked up with the same dynamic scalar indexing as
the block table — and each kernel dequantizes the block right after its
DMA lands in VMEM, with exactly ``ops/attention.py dequant_kv``'s rule
(fp32 multiply, cast to q dtype). The gather reference dequantizes
after gather with the same rule, so the two paths still differ only by
online-softmax accumulation order; scripts/kernel_checks.py
``check_quantized_decode_parity`` pins the int8-vs-fp32 bound at D=64
and D=128 over the same adversarial pool matrix.

Runs under ``interpret=True`` off-TPU like every kernel here, so tier-1
asserts the equivalence on CPU (tests/test_paged_kernel.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LOG2E, NEG_INF, _interpret

# m/l scratch rides full lanes: TPU VMEM tiles pad the trailing dim to
# 128 anyway, and a (G, 128) broadcast store beats a strided (G, 1) one.
_STAT_LANES = 128

# Mosaic tile knobs (ROADMAP D=128 tile-tuning follow-up): how many kv
# heads one grid step processes. A tile of T fuses T heads' (bs, D) KV
# DMAs and dots into one step — fewer grid steps, larger VMEM tiles —
# at T× the scratch. scripts/d128_tile_sweep.py sweeps these under
# interpret mode; 1 is the recorded CPU-interpret-safe default (the
# sweep found no CPU win above it, and 1 keeps each step's numerics and
# scratch identical to the pre-knob kernels). A tile that does not
# divide the pool's kv-head count falls back to 1.
DECODE_HEAD_TILE = 1
CHUNK_HEAD_TILE = 1


def _split_quant_pools(k_pool, v_pool):
    """Unpack possibly-quantized pools for the pallas_call plumbing.

    Returns ``(k_data, v_data, scale_ops)``: the raw (N, K, bs, D) data
    arrays plus the extra scalar-prefetch operands — ``(k_scale,
    v_scale)`` (each (N, K) fp32, ridden to SMEM like the block table)
    when the pools are int8 :class:`QuantPool`s, else ``()``. Mixed
    quantization of K vs V is rejected: the write path quantizes both
    or neither.
    """
    from ..inference.kv_cache import QuantPool  # lazy: avoid import cycle
    kq, vq = isinstance(k_pool, QuantPool), isinstance(v_pool, QuantPool)
    if kq != vq:
        raise TypeError(f"k/v pools must be quantized together, got "
                        f"k={type(k_pool).__name__} "
                        f"v={type(v_pool).__name__}")
    if not kq:
        return k_pool, v_pool, ()
    return k_pool.q, v_pool.q, (k_pool.scale, v_pool.scale)


def _dequant_block(blk, scale_ref, pool_blk, kv_head, out_dtype):
    """Fused dequant at the point the block DMA landed in VMEM.

    ``blk`` is the int8 (bs, D) slice just read through the table;
    ``scale_ref`` the scalar-prefetched (N, K) fp32 scale pool in SMEM,
    looked up at (pool block id, kv head) with the same dynamic scalar
    indexing the table ride-along already uses. MUST match ops/
    attention.py ``dequant_kv`` exactly — fp32 multiply, cast to the
    query dtype — so the gather oracle and the fused kernels disagree
    only by online-softmax accumulation order (the PR 8 tolerance),
    never by dequant rule.
    """
    return (blk.astype(jnp.float32)
            * scale_ref[pool_blk, kv_head]).astype(out_dtype)


def _decode_kernel(tables_ref, offs_ref, *args, block_size: int,
                   scale: float, head_tile: int = 1,
                   quantized: bool = False):
    """One (slot b, kv-head tile h, logical block j) grid step.

    k_ref/v_ref are the (1, head_tile, bs, D) pool slices the index map
    already aimed at ``tables[b, j]`` — the kernel never sees a block id,
    only the block's bytes. Carry (m, l, acc) lives in VMEM scratch
    revisited across the innermost j axis (one (G, ·) band per tiled
    head); j == 0 initializes, the last j emits. The head loop is a
    static Python unroll, so ``head_tile == 1`` is instruction-for-
    instruction the pre-knob kernel.

    ``quantized`` (static) reads int8 pool blocks with two extra
    scalar-prefetch operands — the (N, K) fp32 k/v scale pools — and
    dequantizes each block right after its DMA (:func:`_dequant_block`).
    The positional mask is unchanged, so masked int8 garbage (null
    block, stale tails — including pool rows whose scale[0] entry holds
    junk from diverted null-row writes) still contributes exactly zero
    probability: dequant keeps every lane finite (finite int8 × finite
    fp32 scale), and finite lanes past the boundary underflow to 0.0.
    """
    if quantized:
        (ksc_ref, vsc_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = args
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = args
        ksc_ref = vsc_ref = None
    b = pl.program_id(0)
    ht_i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    offset = offs_ref[b]  # this slot's decode position (committed length)
    g = acc_scr.shape[0] // head_tile

    # Blocks whose first position is already past the query position are
    # fully masked — skip them (freed/stale/null-table tail). The carry
    # is untouched, exactly as an all -inf block contributes nothing.
    @pl.when(j * block_size <= offset)
    def _block():
        for hh in range(head_tile):
            lo, hi = hh * g, (hh + 1) * g
            kb, vb = k_ref[0, hh], v_ref[0, hh]
            if quantized:
                blk = tables_ref[b * pl.num_programs(2) + j]
                kvh = ht_i * head_tile + hh
                kb = _dequant_block(kb, ksc_ref, blk, kvh, q_ref.dtype)
                vb = _dequant_block(vb, vsc_ref, blk, kvh, q_ref.dtype)
            q2 = (q_ref[0, hh].astype(jnp.float32)
                  * (scale * LOG2E)).astype(q_ref.dtype)       # (G, D)
            s = jax.lax.dot_general(                           # (G, bs) fp32
                q2, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            k_pos = j * block_size + jax.lax.broadcasted_iota(
                jnp.int32, (g, block_size), 1)
            s = jnp.where(k_pos <= offset, s, NEG_INF)
            m_prev, l_prev = m_scr[lo:hi, 0], l_scr[lo:hi, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc_scr[lo:hi, :] = (acc_scr[lo:hi, :] * alpha[:, None]
                                 + jax.lax.dot_general(
                                     p.astype(vb.dtype), vb,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32))
            m_scr[lo:hi, :] = jnp.broadcast_to(
                m_new[:, None], (g, m_scr.shape[1]))
            l_scr[lo:hi, :] = jnp.broadcast_to(
                l_new[:, None], (g, l_scr.shape[1]))

    @pl.when(j == pl.num_programs(2) - 1)
    def _emit():
        # l >= exp2(0) always: position ``offset`` itself is in range
        # (the decode writes the query token's KV before attending).
        for hh in range(head_tile):
            lo, hi = hh * g, (hh + 1) * g
            o_ref[0, hh] = (acc_scr[lo:hi, :]
                            / l_scr[lo:hi, :1]).astype(o_ref.dtype)


def paged_decode_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                           offsets: jnp.ndarray,
                           interpret: bool = None) -> jnp.ndarray:
    """S=1 GQA paged attention reading pool blocks in place via the table.

    q:            (B, 1, H, D) decode queries (rope applied, KV written).
    k/v_pool:     (N, K, bs, D) global block pools (kv_cache.py layout).
    block_tables: (B, NB) int32 — slot b's logical block j is pool block
                  ``block_tables[b, j]``; 0 (the null block) for
                  unallocated entries.
    offsets:      (B,) int32 query positions; keys at ``k_pos <=
                  offsets[b]`` attend, everything else is masked (see
                  module docstring for why that alone covers every
                  adversarial pool state).

    Returns (B, 1, H, D), equal to ``paged_cached_attention`` on the same
    operands to fp32 accumulation tolerance.

    k/v_pool may be :class:`~..inference.kv_cache.QuantPool` (int8 data
    + (N, K) fp32 scales): the scales ride as two extra scalar-prefetch
    operands and the kernel dequantizes each block in place — same
    positional masking, same tolerance against the (dequantizing)
    gather oracle.
    """
    b, s_q, h, d = q.shape
    if s_q != 1:
        raise ValueError(f"paged_decode_attention is S=1-specialized, got "
                         f"S={s_q} (multi-token shapes take "
                         f"paged_chunk_attention — ops/attention.py "
                         f"paged_attention routes)")
    k_pool, v_pool, scale_ops = _split_quant_pools(k_pool, v_pool)
    n, kv, bs, _ = k_pool.shape
    g = h // kv
    nb = block_tables.shape[1]
    ht = DECODE_HEAD_TILE if kv % DECODE_HEAD_TILE == 0 else 1
    qg = q.reshape(b, kv, g, d)  # head-major: (B, K, G, D)
    tables = block_tables.reshape(-1).astype(jnp.int32)
    offs = offsets.astype(jnp.int32)
    kernel = functools.partial(_decode_kernel, block_size=bs,
                               scale=1.0 / math.sqrt(d), head_tile=ht,
                               quantized=bool(scale_ops))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2 + len(scale_ops),
            grid=(b, kv // ht, nb),
            in_specs=[
                pl.BlockSpec((1, ht, g, d),
                             lambda bi, hi, j, t, *pref: (bi, hi, 0, 0)),
                pl.BlockSpec((1, ht, bs, d),
                             lambda bi, hi, j, t, *pref: (t[bi * nb + j],
                                                          hi, 0, 0)),
                pl.BlockSpec((1, ht, bs, d),
                             lambda bi, hi, j, t, *pref: (t[bi * nb + j],
                                                          hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, ht, g, d), lambda bi, hi, j, t, *pref: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((ht * g, _STAT_LANES), jnp.float32),  # m
                pltpu.VMEM((ht * g, _STAT_LANES), jnp.float32),  # l
                pltpu.VMEM((ht * g, d), jnp.float32),            # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kv, g, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(tables, offs, *scale_ops, qg, k_pool, v_pool)
    return out.reshape(b, 1, h, d)


def _chunk_kernel(tables_ref, offs_ref, *args, block_size: int, group: int,
                  s_q: int, scale: float, head_tile: int = 1,
                  quantized: bool = False):
    """One (slot b, kv-head tile h, logical block j) grid step, S > 1 rows.

    The q block is the chunk's S*G rows for each tiled kv head, s-major:
    row r is query position ``offsets[b] + r // group``, group member
    ``r % group``. Same online-softmax carry as :func:`_decode_kernel`
    (one rows-band per tiled head, statically unrolled), but the causal
    boundary is applied PER ROW — one iota-derived q_pos column against
    the block's k_pos row — and the wholesale block skip keys off the
    LAST row's boundary (a block any row can see must run; rows that
    can't see it get every lane masked, exp2 underflows to 0.0 exactly,
    their carry is untouched). ``quantized`` fuses the int8 block
    dequant exactly as in :func:`_decode_kernel`.
    """
    if quantized:
        (ksc_ref, vsc_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = args
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = args
        ksc_ref = vsc_ref = None
    b = pl.program_id(0)
    ht_i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    offset = offs_ref[b]  # this slot's chunk start (first row's position)
    rows = s_q * group

    @pl.when(j * block_size <= offset + (s_q - 1))
    def _block():
        for hh in range(head_tile):
            lo, hi = hh * rows, (hh + 1) * rows
            kb, vb = k_ref[0, hh], v_ref[0, hh]
            if quantized:
                blk = tables_ref[b * pl.num_programs(2) + j]
                kvh = ht_i * head_tile + hh
                kb = _dequant_block(kb, ksc_ref, blk, kvh, q_ref.dtype)
                vb = _dequant_block(vb, vsc_ref, blk, kvh, q_ref.dtype)
            q2 = (q_ref[0, hh].astype(jnp.float32)
                  * (scale * LOG2E)).astype(q_ref.dtype)       # (rows, D)
            s = jax.lax.dot_general(                           # (rows, bs)
                q2, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            k_pos = j * block_size + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_size), 1)
            q_pos = offset + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_size), 0) // group
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
            m_prev, l_prev = m_scr[lo:hi, 0], l_scr[lo:hi, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc_scr[lo:hi, :] = (acc_scr[lo:hi, :] * alpha[:, None]
                                 + jax.lax.dot_general(
                                     p.astype(vb.dtype), vb,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32))
            m_scr[lo:hi, :] = jnp.broadcast_to(
                m_new[:, None], (rows, m_scr.shape[1]))
            l_scr[lo:hi, :] = jnp.broadcast_to(
                l_new[:, None], (rows, l_scr.shape[1]))

    @pl.when(j == pl.num_programs(2) - 1)
    def _emit():
        # l >= exp2(0) for every row: k_pos = 0 satisfies the row's own
        # boundary (offset >= 0), and block 0 always runs.
        for hh in range(head_tile):
            lo, hi = hh * rows, (hh + 1) * rows
            o_ref[0, hh] = (acc_scr[lo:hi, :]
                            / l_scr[lo:hi, :1]).astype(o_ref.dtype)


def paged_chunk_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                          v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                          offsets: jnp.ndarray,
                          interpret: bool = None) -> jnp.ndarray:
    """S>1 GQA paged attention reading pool blocks in place via the table.

    The multi-token counterpart of :func:`paged_decode_attention` for
    chunked prefill and chunk-mode spec-verify: same scalar-prefetched
    (B, K, NB) grid, but the q block carries the chunk's S*G rows and the
    causal mask is per row (``k_pos <= offsets[b] + s`` for the chunk's
    s-th query — exactly ``cached_attention``'s additive mask, stated
    positionally; module docstring has the full equivalence argument).

    q:            (B, S, H, D) chunk queries (rope applied, KV written).
    k/v_pool:     (N, K, bs, D) global block pools.
    block_tables: (B, NB) int32 per-slot tables (0 = null block).
    offsets:      (B,) int32 — row s of slot b sits at absolute position
                  ``offsets[b] + s``.

    Returns (B, S, H, D), equal to ``paged_cached_attention`` on the same
    operands to fp32 accumulation tolerance (pad rows past a partial
    chunk's valid length read the same unwritten pool bytes both paths
    read — callers discard those rows).
    """
    b, s_q, h, d = q.shape
    if s_q < 2:
        raise ValueError(f"paged_chunk_attention wants S > 1, got S={s_q} "
                         f"(S=1 is paged_decode_attention's shape)")
    k_pool, v_pool, scale_ops = _split_quant_pools(k_pool, v_pool)
    n, kv, bs, _ = k_pool.shape
    g = h // kv
    nb = block_tables.shape[1]
    rows = s_q * g
    ht = CHUNK_HEAD_TILE if kv % CHUNK_HEAD_TILE == 0 else 1
    # s-major rows per kv head: (B, S, K, G, D) -> (B, K, S*G, D), so row
    # r is (position r // g, group member r % g) — what the kernel's
    # per-row q_pos iota assumes.
    qr = (q.reshape(b, s_q, kv, g, d)
          .transpose(0, 2, 1, 3, 4).reshape(b, kv, rows, d))
    tables = block_tables.reshape(-1).astype(jnp.int32)
    offs = offsets.astype(jnp.int32)
    kernel = functools.partial(_chunk_kernel, block_size=bs, group=g,
                               s_q=s_q, scale=1.0 / math.sqrt(d),
                               head_tile=ht, quantized=bool(scale_ops))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2 + len(scale_ops),
            grid=(b, kv // ht, nb),
            in_specs=[
                pl.BlockSpec((1, ht, rows, d),
                             lambda bi, hi, j, t, *pref: (bi, hi, 0, 0)),
                pl.BlockSpec((1, ht, bs, d),
                             lambda bi, hi, j, t, *pref: (t[bi * nb + j],
                                                          hi, 0, 0)),
                pl.BlockSpec((1, ht, bs, d),
                             lambda bi, hi, j, t, *pref: (t[bi * nb + j],
                                                          hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, ht, rows, d),
                lambda bi, hi, j, t, *pref: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((ht * rows, _STAT_LANES), jnp.float32),  # m
                pltpu.VMEM((ht * rows, _STAT_LANES), jnp.float32),  # l
                pltpu.VMEM((ht * rows, d), jnp.float32),            # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kv, rows, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(tables, offs, *scale_ops, qr, k_pool, v_pool)
    return (out.reshape(b, kv, s_q, g, d)
            .transpose(0, 2, 1, 3, 4).reshape(b, s_q, h, d))


def _tree_kernel(tables_ref, offs_ref, *args, block_size: int, group: int,
                 s_q: int, scale: float, quantized: bool = False):
    """:func:`_chunk_kernel` with the causal rule swapped for the tree's
    ANCESTOR rule (tree-verify: the q rows are one flattened token tree).

    Row r (tree node ``r // group``) attends every committed key
    (``k_pos < offset``) and, inside the speculative window
    ``[offset, offset + s_q)``, exactly the keys of the nodes on its root
    path: ``anc_ref[r // group, j]`` gates window key ``offset + j``.
    The mask is built by a static unroll over the s_q window nodes — an
    equality compare against each node's k_pos AND'd with that node's
    ancestor column — so sibling/cousin keys are NEG_INF'd and underflow
    to exact zero probability like every other masked lane; the block
    skip and the online-softmax carry are the chunk kernel's unchanged.
    Every row sees at least its own key (``anc[r, r]`` is set), so l > 0
    at emit. ``quantized`` fuses the int8 block dequant exactly as in
    :func:`_decode_kernel` (head_tile is 1 here: program_id(1) IS the
    kv head).
    """
    if quantized:
        (ksc_ref, vsc_ref, q_ref, anc_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = args
    else:
        q_ref, anc_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = args
        ksc_ref = vsc_ref = None
    b = pl.program_id(0)
    kvh = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    offset = offs_ref[b]  # committed length: the root row's position
    rows = s_q * group

    @pl.when(j * block_size <= offset + (s_q - 1))
    def _block():
        kb, vb = k_ref[0, 0], v_ref[0, 0]
        if quantized:
            blk = tables_ref[b * pl.num_programs(2) + j]
            kb = _dequant_block(kb, ksc_ref, blk, kvh, q_ref.dtype)
            vb = _dequant_block(vb, vsc_ref, blk, kvh, q_ref.dtype)
        q2 = (q_ref[0, 0].astype(jnp.float32)
              * (scale * LOG2E)).astype(q_ref.dtype)       # (rows, D)
        s = jax.lax.dot_general(                           # (rows, bs) fp32
            q2, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_size), 1)
        vis = k_pos < offset                               # committed keys
        for t_node in range(s_q):
            col = jnp.broadcast_to(anc_ref[:, t_node:t_node + 1],
                                   (s_q, group)).reshape(rows, 1)
            vis = vis | ((k_pos == offset + t_node) & (col > 0))
        s = jnp.where(vis, s, NEG_INF)
        m_prev, l_prev = m_scr[:, 0], l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = (acc_scr[...] * alpha[:, None]
                        + jax.lax.dot_general(
                            p.astype(vb.dtype), vb,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


def paged_tree_chunk_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                               v_pool: jnp.ndarray,
                               block_tables: jnp.ndarray,
                               offsets: jnp.ndarray, anc_mask: jnp.ndarray,
                               interpret: bool = None) -> jnp.ndarray:
    """Tree-verify paged attention reading pool blocks in place.

    The ancestor-masked sibling of :func:`paged_chunk_attention`: same
    scalar-prefetched (B, K, NB) grid and s-major q rows, but the per-row
    causal boundary is replaced by the tree's ancestor rule, carried as a
    dense (S, S) int32 visibility matrix rider (``anc_mask[r, j]`` != 0
    iff tree row j — cache position ``offsets[b] + j`` — is on row r's
    root path; include self and root). Committed keys below ``offsets[b]``
    attend unconditionally, keys past the window never do, so the gather
    reference (ops/attention.py ``tree_cached_attention``) and this
    kernel mask the identical position set — equal to fp32 accumulation
    tolerance, bitwise invariant to masked bytes (scripts/
    kernel_checks.py pins both at D=64 and D=128).

    q:        (B, S, H, D) flattened tree rows (rope at depth positions
              applied, KV written at ``offsets[b] + row``).
    anc_mask: (S, S) bool/int — static per tree shape; the engine bakes
              one per compiled tree program.
    """
    b, s_q, h, d = q.shape
    if s_q < 2:
        raise ValueError(f"paged_tree_chunk_attention wants S > 1, got "
                         f"S={s_q} (a one-node tree is plain decode)")
    if anc_mask.shape != (s_q, s_q):
        raise ValueError(f"anc_mask must be (S, S) = ({s_q}, {s_q}), got "
                         f"{anc_mask.shape}")
    k_pool, v_pool, scale_ops = _split_quant_pools(k_pool, v_pool)
    n, kv, bs, _ = k_pool.shape
    g = h // kv
    nb = block_tables.shape[1]
    rows = s_q * g
    qr = (q.reshape(b, s_q, kv, g, d)
          .transpose(0, 2, 1, 3, 4).reshape(b, kv, rows, d))
    tables = block_tables.reshape(-1).astype(jnp.int32)
    offs = offsets.astype(jnp.int32)
    anc = anc_mask.astype(jnp.int32)
    kernel = functools.partial(_tree_kernel, block_size=bs, group=g,
                               s_q=s_q, scale=1.0 / math.sqrt(d),
                               quantized=bool(scale_ops))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2 + len(scale_ops),
            grid=(b, kv, nb),
            in_specs=[
                pl.BlockSpec((1, 1, rows, d),
                             lambda bi, hi, j, t, *pref: (bi, hi, 0, 0)),
                pl.BlockSpec((s_q, s_q),
                             lambda bi, hi, j, t, *pref: (0, 0)),
                pl.BlockSpec((1, 1, bs, d),
                             lambda bi, hi, j, t, *pref: (t[bi * nb + j],
                                                          hi, 0, 0)),
                pl.BlockSpec((1, 1, bs, d),
                             lambda bi, hi, j, t, *pref: (t[bi * nb + j],
                                                          hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rows, d),
                lambda bi, hi, j, t, *pref: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, _STAT_LANES), jnp.float32),  # m
                pltpu.VMEM((rows, _STAT_LANES), jnp.float32),  # l
                pltpu.VMEM((rows, d), jnp.float32),            # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kv, rows, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(tables, offs, *scale_ops, qr, anc, k_pool, v_pool)
    return (out.reshape(b, kv, s_q, g, d)
            .transpose(0, 2, 1, 3, 4).reshape(b, s_q, h, d))
