from .rope import precompute_rope, apply_rope
from .attention import multihead_attention

__all__ = ["precompute_rope", "apply_rope", "multihead_attention"]
