"""Causal multi-head attention dispatch.

The reference delegates the attention kernel to the container's fused
``F.scaled_dot_product_attention(is_causal=True)`` (ref: model.py:212) after
expanding GQA KV heads with ``repeat_kv`` (ref: model.py:129-138,204-205).
On TPU the equivalents are:

- ``xla``    — einsum attention with fp32 softmax; XLA fuses it well and it is
               the portable (CPU-testable) reference semantics.
- ``pallas`` — the Pallas flash-attention kernel (ops/flash_attention.py),
               tiled for the MXU, O(S) memory.
- ``ring``   — sequence-parallel ring attention (ops/ring_attention.py) for
               long contexts sharded over the mesh's 'sequence' axis.
- ``auto``   — pallas on TPU, xla elsewhere.

GQA is handled *without* materializing repeated KV heads: the einsum reshapes
Q to (B, S, K, G, D) — K kv-groups of G = n_heads // n_kv_heads query heads —
so KV stay at their native head count (the repeat in the reference exists only
because SDPA requires matching head counts; on TPU it would waste HBM
bandwidth).
"""

import jax
import jax.numpy as jnp


def _causal_mask(s_q: int, s_k: int, dtype=jnp.float32) -> jnp.ndarray:
    """Additive causal mask (s_q, s_k); query i attends keys <= i (+ offset)."""
    q_pos = jnp.arange(s_q)[:, None] + (s_k - s_q)
    k_pos = jnp.arange(s_k)[None, :]
    return jnp.where(k_pos <= q_pos, 0.0, jnp.finfo(dtype).min).astype(dtype)


def xla_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  causal: bool = True) -> jnp.ndarray:
    """Grouped-query causal attention, fp32 softmax, einsum formulation.

    q: (B, S, H, D); k, v: (B, S, K, D) with H % K == 0.
    Matches the reference kernel semantics (model.py:204-212) — softmax over
    keys in fp32, scale 1/sqrt(D) — without the repeat_kv copy.
    """
    b, s_q, h, d = q.shape
    _, s_k, kv, _ = k.shape
    g = h // kv
    qg = q.reshape(b, s_q, kv, g, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    # scores: (B, K, G, S_q, S_k)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        scores = scores + _causal_mask(s_q, s_k)[None, None, None, :, :]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s_q, h, d).astype(q.dtype)


def cached_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     offsets: jnp.ndarray) -> jnp.ndarray:
    """Grouped-query attention against a per-slot KV cache (prefill/decode).

    q:                (B, S, H, D) — S new queries per slot at absolute
                      positions ``offsets[b] + [0, S)``.
    k_cache, v_cache: (B, K, T, D) head-major slot buffers; positions
                      ``[0, offsets[b] + S)`` must already hold this slot's
                      rotated keys/values (the caller writes before calling).
    offsets:          (B,) int32 tokens previously in each slot's cache.

    Numerics mirror :func:`xla_attention` exactly — same grouped einsum
    contraction, fp32 scores, additive ``finfo.min`` mask, fp32 softmax cast
    back to q.dtype, fp32 output accumulation — so a cached decode reproduces
    the full-forward logits bit-for-bit: masked positions (the cache tail
    beyond a slot's length) get ``exp(min) == 0`` probability exactly, and
    zero probabilities contribute exact zeros to the fp32 accumulation.
    """
    b, s_q, h, d = q.shape
    _, kv, t, _ = k_cache.shape
    g = h // kv
    qg = q.reshape(b, s_q, kv, g, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    # scores: (B, K, G, S_q, T)
    scores = jnp.einsum("bqkgd,bktd->bkgqt", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    q_pos = offsets[:, None] + jnp.arange(s_q)[None, :]          # (B, S_q)
    k_pos = jnp.arange(t)[None, None, :]                         # (1, 1, T)
    mask = jnp.where(k_pos <= q_pos[:, :, None], 0.0,
                     jnp.finfo(jnp.float32).min)                 # (B, S_q, T)
    scores = scores + mask[:, None, None, :, :]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqt,bktd->bqkgd", probs, v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s_q, h, d).astype(q.dtype)


def tree_cached_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                          v_cache: jnp.ndarray, offsets: jnp.ndarray,
                          anc_mask: jnp.ndarray) -> jnp.ndarray:
    """:func:`cached_attention` with a per-row ANCESTOR mask over the
    speculative window — the tree-verify attention rule.

    q:        (B, S, H, D) — the round's flattened token tree, row 0 the
              committed last token (root), rows 1..S-1 draft proposals in
              topological order; node i's KV sits at cache position
              ``offsets[b] + i`` (written contiguously, like any chunk).
    anc_mask: (S, S) bool — ``anc_mask[r, j]`` iff tree row j is on row
              r's root path (ancestors ∪ self ∪ root), so siblings and
              cousins never see each other's keys.

    The mask replaces the chunk kernel's pure causal rule: row r attends
    every COMMITTED key (``k_pos < offsets[b]``) exactly as before, plus
    the speculative-window keys ``offsets[b] + j`` with ``anc_mask[r, j]``
    set; keys past the window stay masked. Everything else — grouped
    einsum, fp32 softmax, additive ``finfo.min`` mask with exact-zero
    masked probabilities — is :func:`cached_attention` byte for byte, so
    a tree whose mask happens to be the causal chain reproduces the
    linear verify bit-for-bit.
    """
    b, s_q, h, d = q.shape
    _, kv, t, _ = k_cache.shape
    g = h // kv
    qg = q.reshape(b, s_q, kv, g, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bqkgd,bktd->bkgqt", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(t, dtype=jnp.int32)[None, None, :]        # (1, 1, T)
    node = k_pos - offsets[:, None, None]                        # (B, 1, T)
    committed = node < 0
    in_window = (node >= 0) & (node < s_q)
    tree_vis = jnp.transpose(
        anc_mask[:, jnp.clip(node[:, 0, :], 0, s_q - 1)],        # (S, B, T)
        (1, 0, 2))                                               # (B, S, T)
    visible = committed | (in_window & tree_vis)
    mask = jnp.where(visible, 0.0, jnp.finfo(jnp.float32).min)
    scores = scores + mask[:, None, None, :, :]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqt,bktd->bqkgd", probs, v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s_q, h, d).astype(q.dtype)


def dequant_kv(q_vals: jnp.ndarray, scale: jnp.ndarray,
               out_dtype) -> jnp.ndarray:
    """THE int8-KV dequant rule, shared verbatim by the gather reference
    and the Pallas kernels: int8 values times their per-(block, kv_head)
    fp32 scale, in fp32, cast ONCE to the compute dtype. The gather path
    applies it after the gather (:func:`gather_kv_blocks`); the Pallas
    kernels apply it to each block right where its DMA lands in VMEM
    (ops/paged_attention.py) — so quantized gather-vs-pallas parity
    reduces to the same online-softmax fp32-reordering tolerance as the
    bf16 lanes."""
    return (q_vals.astype(jnp.float32) * scale).astype(out_dtype)


def gather_kv_blocks(pool, block_tables: jnp.ndarray,
                     out_dtype=None) -> jnp.ndarray:
    """Assemble per-slot contiguous KV views from a paged block pool.

    pool:         (N, K, bs, D) global block pool (inference/kv_cache.py
                  ``PagedKVCache``); block 0 is the null/scratch block.
                  An int8 ``QuantPool`` is accepted too — see below.
    block_tables: (B, NB) int32 — slot b's logical block n lives in pool
                  block ``block_tables[b, n]``; unallocated entries are 0.

    Returns (B, K, NB*bs, D). One gather per layer: position ``p`` of slot
    ``b`` is ``pool[block_tables[b, p // bs], :, p % bs]`` — exactly the
    ring buffer's content for every written position, and null-block/stale
    content beyond a slot's length, which the caller's length mask zeroes.

    A quantized pool gathers its int8 blocks AND their per-(block, kv_head)
    scales through the same table, then dequantizes the gathered view via
    :func:`dequant_kv` into ``out_dtype`` (the attention compute dtype,
    default bf16) — dequantize-after-gather, the selectable correctness
    oracle the fused-dequant Pallas kernels are checked against.
    ``out_dtype`` is ignored for plain pools: their bytes pass through
    untouched, preserving the bf16 lanes' bit-exactness story.

    The gather is a pure READ of the tables, so the same pool block may
    appear in several slots' rows at once — that is how the prefix cache
    (inference/prefix_cache.py) serves shared prompt prefixes with zero
    kernel changes: hit blocks are simply referenced by more than one row.
    Writes never target a shared block (the scheduler copy-on-writes it
    into a private block first), so concurrent readers always see
    committed, immutable bytes.
    """
    from ..inference.kv_cache import QuantPool
    if isinstance(pool, QuantPool):
        g = pool.q[block_tables]               # (B, NB, K, bs, D) int8
        sc = pool.scale[block_tables]          # (B, NB, K)
        g = dequant_kv(g, sc[..., None, None],
                       jnp.bfloat16 if out_dtype is None else out_dtype)
    else:
        g = pool[block_tables]                 # (B, NB, K, bs, D)
    b, nb, k, bs, d = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(b, k, nb * bs, d)


def paged_cached_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                           offsets: jnp.ndarray) -> jnp.ndarray:
    """:func:`cached_attention` against block-paged KV pools.

    Gathers each slot's blocks into the (B, K, T, D) layout via the block
    table, then runs the EXACT :func:`cached_attention` math on it — same
    grouped einsum, fp32 softmax, additive ``finfo.min`` mask keyed on
    ``offsets`` — so on identical cached contents the two paths bit-match:
    masked gathered positions (null block, stale/freed blocks, positions
    beyond a slot's length) get ``exp(finfo.min + score) == 0`` probability
    exactly and contribute exact zeros to the fp32 accumulation, just like
    the ring buffer's masked tail. This is the portable XLA-level reference
    of vLLM's PagedAttention: the gather materializes a transient per-call
    contiguous view instead of a fused block-indexed kernel — the
    semantics the in-place Pallas kernel (ops/paged_attention.py)
    reproduces to fp32 accumulation tolerance, and the bit-exact
    reference it is tested against (:func:`paged_attention` dispatches
    between the two).

    Prefix sharing needs NO change here: a block referenced by several
    slots' table rows (prefix-cache hit) is gathered into each of their
    views with bit-identical contents, and since shared blocks are
    read-only (copy-on-write precedes any write into one), a cache-hit
    slot's gathered view equals what its own prefill would have produced —
    the root of the cached-stream bit-exactness tests.
    """
    return cached_attention(
        q, gather_kv_blocks(k_pool, block_tables, q.dtype),
        gather_kv_blocks(v_pool, block_tables, q.dtype), offsets)


def paged_tree_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                         v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                         offsets: jnp.ndarray, anc_mask: jnp.ndarray,
                         impl: str = "gather") -> jnp.ndarray:
    """:func:`tree_cached_attention` against block-paged KV pools — the
    tree-verify routing point, mirroring :func:`paged_attention`.

    ``"gather"`` assembles each slot's blocks and runs the bit-exact
    reference above; ``"pallas"`` takes the ancestor-masked chunk kernel
    (ops/paged_attention.py ``paged_tree_chunk_attention``), which reads
    pool blocks in place through the table and carries the (S, S) mask as
    a packed per-row int32 bitmask — equal within fp32 accumulation
    tolerance and bitwise invariant to masked bytes, like every other
    pallas lane.
    """
    if impl == "gather":
        return tree_cached_attention(
            q, gather_kv_blocks(k_pool, block_tables, q.dtype),
            gather_kv_blocks(v_pool, block_tables, q.dtype), offsets,
            anc_mask)
    if impl == "pallas":
        from .paged_attention import paged_tree_chunk_attention
        return paged_tree_chunk_attention(q, k_pool, v_pool, block_tables,
                                          offsets, anc_mask)
    raise ValueError(f"unknown paged attention impl: {impl!r} "
                     f"(want 'gather' or 'pallas')")


def paged_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                    v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                    offsets: jnp.ndarray, impl: str = "gather"
                    ) -> jnp.ndarray:
    """Paged-attention kernel dispatch — THE routing point for every read
    through the block tables (decode, chunked prefill, spec-verify; the
    former ``paged_verify_attention`` alias collapsed into this).

    - ``"gather"`` — :func:`paged_cached_attention`: gather-then-ring,
      the portable bit-exact reference (serving's ``--paged-kernel
      gather``).
    - ``"pallas"`` — the in-place block-indexed kernels
      (ops/paged_attention.py): pool blocks are DMA'd straight through
      the table, no gathered copy. S=1 takes the decode kernel, S>1
      (chunked prefill, chunk-mode spec-verify) the chunk kernel — every
      paged read is in place under this impl, no silent gather. Both are
      equal to gather within fp32 accumulation tolerance (online softmax
      reorders the reduction) and bitwise invariant to masked bytes; the
      single statement of the positional-masking equivalence lives in
      ops/paged_attention.py's module docstring.
    """
    if impl == "gather":
        return paged_cached_attention(q, k_pool, v_pool, block_tables,
                                      offsets)
    if impl == "pallas":
        if q.shape[1] == 1:
            from .paged_attention import paged_decode_attention
            return paged_decode_attention(q, k_pool, v_pool, block_tables,
                                          offsets)
        from .paged_attention import paged_chunk_attention
        return paged_chunk_attention(q, k_pool, v_pool, block_tables,
                                     offsets)
    raise ValueError(f"unknown paged attention impl: {impl!r} "
                     f"(want 'gather' or 'pallas')")


def multihead_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        impl: str = "auto", causal: bool = True) -> jnp.ndarray:
    """Dispatch to the requested attention implementation.

    ``"ring"`` is accepted (models/configs.py admits it as an
    ``attention_impl``) but resolves like ``"auto"``: ring attention is
    the sequence-parallel collective form (ops/ring_attention.py) and
    only exists under a mesh with a >1 'sequence' axis — the model layer
    routes it there itself (models/llama.py). A direct single-device
    call has no axis to ring over, so it gets the equivalent dense
    kernel instead of an opaque raise.
    """
    if impl in ("auto", "ring"):
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal)
    if impl == "pallas":
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    raise ValueError(f"unknown attention impl: {impl!r}")
