"""Fused output-head + cross-entropy: CE without materializing logits.

One step beyond the vocab-blocked CE (ops/cross_entropy.py, which still
reads a materialized (B, S, V) bf16 logits tensor): here the head matmul
itself is blocked over the vocab dim inside a custom VJP, so **no logits
tensor of any dtype ever exists** — at the reference's 131k vocab the
bf16 logits (plus their dlogits cotangent) are the two largest activation
tensors in the step (ref loss semantics: train.py:101-102).

- **Forward**: for each vocab block, compute ``hidden @ W[:, j:j+block]``
  (MXU matmul, fp32 accumulation) and fold it into running rowwise
  (max, shifted-normalizer, picked-logit) stats — the same online
  logsumexp as the blocked CE. Residuals: hidden, W, labels, lse.
- **Backward**: recompute each block's logits from the residuals, form
  ``dS_j = g * (softmax_j - onehot_j)`` for that block only, and
  contract immediately into the weight gradient ``dW_j = h^T dS_j`` and
  the hidden gradient ``dh += dS_j W_j^T``. Peak extra memory is one
  (B, S, block) fp32 slice.

This is the flash-attention recomputation scheme applied to the
classifier head (sometimes called a "fused/linear cross-entropy").
Numerics match head-then-CE to fp32-accumulation tolerance
(tests/test_train_step.py).

Two forms:

- :func:`fused_head_xent` — single vocab group (the vocab axis is
  unsharded on the active mesh);
- :func:`sharded_fused_head_xent` — the vocab axis is sharded (tensor
  and/or pipe meshes). A partial-manual ``shard_map`` over exactly the
  vocab-sharding mesh axes gives each device its *local, contiguous,
  unsharded* (D, V/n) slice — so the same blocked loops run unchanged
  per shard (under pure auto-SPMD their ``dynamic_slice`` over a sharded
  vocab would make the partitioner gather) — and the online (m, l,
  picked) stats fold across shards with one pmax + two (B, S) psums.
  The backward recomputes locally and psums only the (B, S, D) hidden
  cotangent. Without it, tp/pp meshes at the reference's 131k vocab
  materialize a (B, S, V/n) fp32 slice per device inside the dense CE —
  exactly the tensor class the fused form exists to kill (VERDICT r2
  weak #5).
"""

import functools

import jax
import jax.numpy as jnp
from ..utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from .cross_entropy import DEFAULT_BLOCK

# Auto-dispatch point (training/step.py): the fused form pays ~12% step
# time over materialize-then-chunked-CE when the logits fit (measured at
# vocab 131k, bs 4 on v5e: 129.5 vs 115.7 ms/step; re-measured round 4 at
# the 50k bench vocab: -8%), so it engages only when the estimated logits
# + cotangent footprint (B*S*V * ~6 bytes) would not fit — at which point
# it is the difference between training and OOM (vocab 131k, bs 8 on
# v5e: 244.7 ms/step fused vs 'exceeded hbm capacity by 443 MB' unfused).
#
# The threshold is AUTO_MIN_FRACTION of the DEVICE's HBM (v5e 16 GB ->
# 8 GB, the round-2-calibrated point; a 95 GB v5p engages ~6x later —
# VERDICT r3 weak #5). AUTO_MIN_BYTES is an override hook: tests and the
# sweep harness set it to force a dispatch; None = derive from the device.
AUTO_MIN_BYTES = None
AUTO_MIN_FRACTION = 0.5
_CALIBRATED_HBM = 16 * 2**30  # v5e, where the fraction was measured


def auto_min_bytes() -> float:
    """The logits-footprint threshold above which model_loss picks the
    fused head+CE (see module comment)."""
    if AUTO_MIN_BYTES is not None:
        return AUTO_MIN_BYTES
    from ..utils.device import device_hbm_bytes

    return AUTO_MIN_FRACTION * device_hbm_bytes(_CALIBRATED_HBM)


def _block_logits(hidden, w, j, block):
    """fp32 (B, S, block) logits of vocab block ``j`` — the only shape at
    which logits ever exist."""
    wj = jax.lax.dynamic_slice_in_dim(w, j * block, block, axis=1)
    return jnp.dot(hidden, wj, preferred_element_type=jnp.float32)


def _raw_stats(hidden, w, labels, block):
    """Blocked online-softmax stats (m, l, picked), all fp32 (B, S).

    Returned un-merged (no ``m + log l``) so a vocab-sharded caller — the
    1F1B pipeline's in-loop head, parallel/pipeline.py — can fold stats
    from other shards in with pmax/psum before forming the logsumexp.
    ``labels`` may be out of range (e.g. offset into another shard's
    slice); out-of-range rows simply never hit ``picked``."""
    from .cross_entropy import _block_update

    b, s, _ = hidden.shape
    v = w.shape[1]
    m = jnp.full((b, s), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, s), jnp.float32)
    picked = jnp.zeros((b, s), jnp.float32)

    def body(j, carry):
        sl = _block_logits(hidden, w, j, block)
        return _block_update(sl, labels, j * block, *carry)

    m, l, picked = jax.lax.fori_loop(0, v // block, body, (m, l, picked))
    if v % block:
        tail = jnp.dot(hidden, w[:, (v // block) * block:],
                       preferred_element_type=jnp.float32)
        m, l, picked = _block_update(tail, labels, (v // block) * block,
                                     m, l, picked)
    return m, l, picked


def _fwd_stats(hidden, w, labels, block):
    m, l, picked = _raw_stats(hidden, w, labels, block)
    return m + jnp.log(l), picked


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_head_xent(hidden, w, labels, block: int = DEFAULT_BLOCK):
    """Per-token -log_softmax(hidden @ w)[label], fp32 (B, S).

    ``hidden``: (B, S, D) post-final-norm activations; ``w``: (D, V) head
    weight; ``labels`` must already be in-range (callers mask ignore
    positions around this op)."""
    lse, picked = _fwd_stats(hidden, w, labels, block)
    return lse - picked


def _fx_fwd(hidden, w, labels, block):
    lse, picked = _fwd_stats(hidden, w, labels, block)
    return lse - picked, (hidden, w, labels, lse)


def _bwd_accum(hidden, w, labels, lse, gf, block, dw_dtype=None):
    """Blocked backward of the head+CE: recompute each vocab block's logits,
    form ``dS_j = gf * (softmax_j - onehot_j)``, and contract immediately
    into ``(dh, dw)``. ``gf``: fp32 (B, S) per-token cotangent (linear: a
    zero row yields exactly zero grads). ``dh`` returns fp32; ``dw`` in
    ``dw_dtype`` (default ``w.dtype``). Shared by the custom VJP below and
    the 1F1B pipeline's in-loop head (parallel/pipeline.py), whose
    ``labels`` arrive offset into this shard's local-vocab frame."""
    b, s, d = hidden.shape
    v = w.shape[1]
    dw_dtype = w.dtype if dw_dtype is None else dw_dtype

    def block_ds(j0, vb):
        sl = jnp.dot(
            hidden, jax.lax.dynamic_slice_in_dim(w, j0, vb, axis=1),
            preferred_element_type=jnp.float32)
        p = jnp.exp(sl - lse[..., None])
        loc = labels - j0
        hit = (loc >= 0) & (loc < vb)
        onehot = (jax.lax.broadcasted_iota(jnp.int32, sl.shape, 2)
                  == loc[..., None]) & hit[..., None]
        # dS in the compute dtype: both contractions below are MXU matmuls
        return (gf[..., None] * (p - onehot.astype(jnp.float32))
                ).astype(hidden.dtype)

    def body(j, carry):
        dh, dw = carry
        ds = block_ds(j * block, block)
        wj = jax.lax.dynamic_slice_in_dim(w, j * block, block, axis=1)
        dh = dh + jnp.einsum("bsv,dv->bsd", ds, wj,
                             preferred_element_type=jnp.float32)
        dwj = jnp.einsum("bsd,bsv->dv", hidden, ds,
                         preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice_in_dim(
            dw, dwj.astype(dw_dtype), j * block, axis=1)
        return dh, dw

    dh = jnp.zeros((b, s, d), jnp.float32)
    dw = jnp.zeros(w.shape, dw_dtype)
    dh, dw = jax.lax.fori_loop(0, v // block, body, (dh, dw))
    if v % block:
        j0 = (v // block) * block
        ds = block_ds(j0, v - j0)
        wj = w[:, j0:]
        dh = dh + jnp.einsum("bsv,dv->bsd", ds, wj,
                             preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice_in_dim(
            dw, jnp.einsum("bsd,bsv->dv", hidden, ds,
                           preferred_element_type=jnp.float32
                           ).astype(dw_dtype), j0, axis=1)
    return dh, dw


def _fx_bwd(block, res, g):
    hidden, w, labels, lse = res
    dh, dw = _bwd_accum(hidden, w, labels, lse, g.astype(jnp.float32), block)
    return dh.astype(hidden.dtype), dw, None


fused_head_xent.defvjp(_fx_fwd, _fx_bwd)


def _vocab_manual_axes(w_shape, mesh):
    """The mesh axes that actually shard the vocab dim of a (D, V) head
    weight on ``mesh`` (after the divisibility degrade), in sharding-major
    order, plus the per-device slice size and a global-offset function."""
    from ..parallel.sharding import vocab_shard_axes

    axes = vocab_shard_axes(w_shape, mesh)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    vl = w_shape[1] // n

    def v0():
        """Global vocab offset of this device's slice (traced scalar);
        call inside the shard_map body."""
        idx = jnp.zeros((), jnp.int32)
        for a in axes:  # major-to-minor, matching the dim's axis order
            idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
        return idx * vl

    return axes, vl, v0


def sharded_fused_head_xent(hidden, w, labels,
                            block: int = DEFAULT_BLOCK) -> jax.Array:
    """:func:`fused_head_xent` for a mesh-sharded vocab axis: per-token
    -log_softmax(hidden @ w)[label], fp32 (B, S), with w's vocab dim
    sharded over the active mesh's vocab axes (tensor and/or pipe).

    Must be called with a mesh active whose vocab sharding is non-trivial
    (callers dispatch on ``shard_size(v, "vocab")``, training/step.py).
    Differentiable wrt ``hidden`` and ``w`` (custom VJP)."""
    return _sharded_fx(hidden, w, labels, block)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _sharded_fx(hidden, w, labels, block):
    nll, _ = _sfx_fwd_impl(hidden, w, labels, block)
    return nll


def _sfx_fwd_impl(hidden, w, labels, block):
    from ..parallel.mesh import active_mesh

    mesh = active_mesh()
    axes, vl, v0_fn = _vocab_manual_axes(w.shape, mesh)
    blk = min(block, vl)

    def body(h, w_local, lab):
        loc = lab - v0_fn()
        m, l, picked = _raw_stats(h, w_local, loc, blk)
        m_g = jax.lax.pmax(m, axes)
        l_g = jax.lax.psum(l * jnp.exp(m - m_g), axes)
        picked_g = jax.lax.psum(picked, axes)
        lse = m_g + jnp.log(l_g)
        return lse - picked_g, lse

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(), P(None, axes), P()),
                   out_specs=(P(), P()),
                   axis_names=set(axes), check_vma=False)
    return fn(hidden, w, labels)


def _sfx_fwd(hidden, w, labels, block):
    nll, lse = _sfx_fwd_impl(hidden, w, labels, block)
    return nll, (hidden, w, labels, lse)


def _sfx_bwd(block, res, g):
    from ..parallel.mesh import active_mesh

    hidden, w, labels, lse = res
    mesh = active_mesh()
    axes, vl, v0_fn = _vocab_manual_axes(w.shape, mesh)
    blk = min(block, vl)
    gf = g.astype(jnp.float32)

    def body(h, w_local, lab, lse_, gf_):
        dh_l, dw_l = _bwd_accum(h, w_local, lab - v0_fn(), lse_, gf_, blk)
        # fp32 psum of the hidden cotangent: each shard contributes only
        # its vocab slice's backprop. dw stays local (sharded out).
        dh = jax.lax.psum(dh_l, axes)
        return dh.astype(h.dtype), dw_l

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(), P(None, axes), P(), P(), P()),
                   out_specs=(P(), P(None, axes)),
                   axis_names=set(axes), check_vma=False)
    dh, dw = fn(hidden, w, labels, lse, gf)
    return dh, dw, None


_sharded_fx.defvjp(_sfx_fwd, _sfx_bwd)
