"""Pallas carry-state flash kernels for ring attention.

Ring attention (ops/ring_attention.py) shards the sequence over the mesh's
'sequence' axis and rotates KV blocks around the ring. Its per-step local
math — "accumulate online-softmax attention of my queries against one
visiting KV block" — is exactly one k-phase of the flash forward, so these
kernels generalize the streaming flash family (ops/flash_attention.py) in
two ways:

- **Carry in/out.** The online-softmax state (m, l, acc) — and in the
  backward, the dq / traveling (dk, dv) accumulators — enter as inputs and
  leave as outputs, so the state threads *between* pallas calls across ring
  steps. Inside a call the output block is the accumulator (initialized
  from the input tile at the first inner grid step; the index map ignores
  the inner axis so the block stays resident in VMEM until its last visit).
- **Global position offsets.** Causality in a ring step depends on where
  the local q rows and the visiting KV block sit in the *global* sequence.
  The offsets are traced values (they derive from ``lax.axis_index``), so
  they ride in as scalar-prefetch operands: the kernel reads them from SMEM
  for the mask, and the index maps read them to clamp the fetch index of
  blocks that are entirely in the causal future — the pipeline then skips
  the HBM fetch (same elision trick as the streaming kernels' diagonal
  clamp, but data-dependent).

A fully-future visiting block degenerates to a no-op: every tile's
``useful`` predicate is false, compute is skipped by ``pl.when``, fetches
are clamped, and the carry passes through — so the contiguous-layout ring
caller needs no masking logic at all, just the offsets.

Everything numerical (base-2 softmax, q pre-scaling, the tile updates
themselves) is shared with ops/flash_attention.py so the two families can
never diverge: ``_online_softmax_step``, ``_dq_tile``, ``_dkv_tile``
operate on traced ``masked`` predicates already.

Layouts: q/do/o/dq are (B, H, S_q, D); k/v/dk/dv are (B, K, S_k, D);
m/l/lse/delta are (B, H, S_q, 1) fp32. The ring caller transposes once at
the shard_map body boundary, not per step. All accumulators are fp32 and
unscaled; the ring caller applies the final ``* scale`` (dq), ``* ln 2``
(dk) and ``acc / l`` (out) once after the last ring step.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (
    NEG_INF,
    _delta,
    _dkv_tile,
    _dq_tile,
    _fit_block,
    _online_softmax_step,
    _prescale_q,
)

# Ring carry tiles, pinned to the values the S=64k on-chip measurements
# were taken with (BASELINE.md round 2). Deliberately NOT shared with
# flash_attention's constants: those get retuned for the single-chip
# resident regime (round 3 moved FWD to 512x1024 for the fused-backward
# balance), and a silent inheritance would change the ring kernels'
# operating point in a long-sequence regime no such sweep covered.
RING_FWD_BLOCK_Q, RING_FWD_BLOCK_K = 1024, 256
RING_DQ_BLOCK_Q, RING_DQ_BLOCK_K = 512, 512
RING_DKV_BLOCK_Q, RING_DKV_BLOCK_K = 512, 1024

__all__ = [
    "carry_fwd",
    "carry_dq",
    "carry_dkv",
    "fresh_carry",
    "finalize_carry",
]


def fresh_carry(b, h, s, d):
    """Zero-information (m, l, acc) online-softmax state."""
    return (jnp.full((b, h, s, 1), NEG_INF, jnp.float32),
            jnp.zeros((b, h, s, 1), jnp.float32),
            jnp.zeros((b, h, s, d), jnp.float32))


def finalize_carry(m, l, acc, dtype):
    """(out, lse) from a finished carry; lse is base-2 like the flash fwd."""
    out = (acc / l).astype(dtype)
    lse = m + jnp.log2(l)
    return out, lse


def _bounds(q_start, k_start, block_q, block_k, causal):
    """(useful, masked) predicates for a (bq, bk) tile at global offsets.

    A pair (i, j) is causally valid iff q_pos_i >= k_pos_j; the tile
    contributes iff its last q row sees its first key, and needs the mask
    iff its first q row cannot see its last key. ``causal=False`` means the
    caller guarantees the whole block is valid (static elision)."""
    if not causal:
        return True, False
    useful = k_start <= q_start + block_q - 1
    masked = k_start + block_k - 1 > q_start
    return useful, masked


def _maybe(pred, fn):
    """Run ``fn`` under ``pl.when`` only when the predicate is traced."""
    if pred is True:
        fn()
    else:
        pl.when(pred)(fn)


def _carry_fwd_kernel(offs_ref, q_ref, k_ref, v_ref, m_in, l_in, acc_in,
                      m_ref, l_ref, acc_ref, *, block_q, block_k, scale,
                      causal):
    # grid (b, h, qi, ki), ki innermost; out blocks ignore ki (VMEM-resident
    # accumulators). q: (1,1,bq,D); k/v: (1,1,bk,D); m/l: (1,1,bq,1) fp32.
    ki = pl.program_id(3)
    q_start = offs_ref[0] + pl.program_id(2) * block_q
    k_start = offs_ref[1] + ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = m_in[...]
        l_ref[...] = l_in[...]
        acc_ref[...] = acc_in[...]

    useful, masked = _bounds(q_start, k_start, block_q, block_k, causal)

    def _step():
        q2 = _prescale_q(q_ref[0, 0], scale)
        carry = (m_ref[0, 0][:, 0], l_ref[0, 0][:, 0], acc_ref[0, 0])
        m, l, acc = _online_softmax_step(q2, k_ref[0, 0], v_ref[0, 0], carry,
                                         q_start, k_start, masked)
        m_ref[0, 0] = m[:, None]
        l_ref[0, 0] = l[:, None]
        acc_ref[0, 0] = acc

    _maybe(useful, _step)


def _carry_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_in, dq_ref, *, block_q, block_k, scale,
                     causal):
    # grid (b, h, qi, ki), ki innermost; dq accumulates unscaled fp32.
    ki = pl.program_id(3)
    q_start = offs_ref[0] + pl.program_id(2) * block_q
    k_start = offs_ref[1] + ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_ref[...] = dq_in[...]

    useful, masked = _bounds(q_start, k_start, block_q, block_k, causal)

    def _step():
        q2 = _prescale_q(q_ref[0, 0], scale)
        dq_ref[0, 0] = dq_ref[0, 0] + _dq_tile(
            q2, k_ref[0, 0], v_ref[0, 0], do_ref[0, 0], lse_ref[0, 0],
            delta_ref[0, 0], q_start, k_start, masked)

    _maybe(useful, _step)


def _carry_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dk_in, dv_in, dk_ref, dv_ref, *, block_q,
                      block_k, scale, causal):
    # grid (b, kv_head, ki, qi), qi innermost; q/do/lse/delta carry this KV
    # head's G query heads as (1, G, bq, D) blocks; dk/dv accumulate
    # unscaled fp32 in the output blocks (index maps ignore qi).
    qi = pl.program_id(3)
    k_start = offs_ref[1] + pl.program_id(2) * block_k
    q_start = offs_ref[0] + qi * block_q
    group = q_ref.shape[1]
    k = k_ref[0, 0]
    v = v_ref[0, 0]

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = dk_in[...]
        dv_ref[...] = dv_in[...]

    useful, masked = _bounds(q_start, k_start, block_q, block_k, causal)

    def _step():
        dk_acc, dv_acc = dk_ref[0, 0], dv_ref[0, 0]
        for g in range(group):  # static loop: accumulate the GQA group
            q2 = _prescale_q(q_ref[0, g], scale)
            dk_c, dv_c = _dkv_tile(q2, k, v, do_ref[0, g], lse_ref[0, g],
                                   delta_ref[0, g], q_start, k_start, masked)
            dk_acc, dv_acc = dk_acc + dk_c, dv_acc + dv_c
        dk_ref[0, 0], dv_ref[0, 0] = dk_acc, dv_acc

    _maybe(useful, _step)


def _offs(q_off, k_off):
    return jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])


def _q_major_kv_idx(bq, bk, group, causal):
    """(b, h, qi, ki)-grid KV index map, shared by carry_fwd and carry_dq.

    With ``causal``, clamps the fetch index of k-blocks wholly in the
    causal future of the q tile — the pipeline then skips the HBM fetch
    (compute is skipped by the kernel's ``useful`` predicate either way).
    One definition so the fwd and dq kernels can never fetch differently.
    """
    if causal:
        def kv_idx(bi, hi, qi, ki, offs):
            last = (offs[0] + (qi + 1) * bq - 1 - offs[1]) // bk
            return (bi, hi // group, jnp.minimum(ki, jnp.maximum(last, 0)), 0)
    else:
        def kv_idx(bi, hi, qi, ki, offs):
            return (bi, hi // group, ki, 0)
    return kv_idx


def carry_fwd(q, k, v, m, l, acc, q_off, k_off, *, causal=True,
              interpret=False):
    """One ring step of the flash forward: fold KV block (k, v) at global
    offset ``k_off`` into the online-softmax carry of q rows at ``q_off``.

    q: (B,H,Sq,D); k/v: (B,K,Sk,D); m/l: (B,H,Sq,1) fp32; acc (B,H,Sq,D)
    fp32. Returns the updated (m, l, acc). O(block) VMEM — no (Sq, Sk)
    tensor exists at any point (the VERDICT round-1 weak spot #1)."""
    b, h, s_q, d = q.shape
    kv = k.shape[1]
    group = h // kv
    s_k = k.shape[2]
    bq, bk = _fit_block(s_q, RING_FWD_BLOCK_Q), _fit_block(s_k, RING_FWD_BLOCK_K)
    scale = 1.0 / (d ** 0.5)
    grid = (b, h, s_q // bq, s_k // bk)

    def q_idx(bi, hi, qi, ki, offs):
        return (bi, hi, qi, 0)

    kv_idx = _q_major_kv_idx(bq, bk, group, causal)
    row = pl.BlockSpec((1, 1, bq, 1), q_idx)
    mat = pl.BlockSpec((1, 1, bq, d), q_idx)
    kvspec = pl.BlockSpec((1, 1, bk, d), kv_idx)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[mat, kvspec, kvspec, row, row, mat],
        out_specs=[row, row, mat],
    )
    kernel = functools.partial(_carry_fwd_kernel, block_q=bq, block_k=bk,
                               scale=scale, causal=causal)
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=[jax.ShapeDtypeStruct(m.shape, jnp.float32),
                   jax.ShapeDtypeStruct(l.shape, jnp.float32),
                   jax.ShapeDtypeStruct(acc.shape, jnp.float32)],
        interpret=interpret,
    )(_offs(q_off, k_off), q, k, v, m, l, acc)


def carry_dq(q, k, v, do, lse, delta, dq, q_off, k_off, *, causal=True,
             interpret=False):
    """One ring step of the flash dq: accumulate this KV block's (unscaled)
    dq contribution into the fp32 carry ``dq``. Shapes as in carry_fwd;
    do like q; lse/delta (B,H,Sq,1) fp32 (base-2 lse, rowwise dO.O)."""
    b, h, s_q, d = q.shape
    kv = k.shape[1]
    group = h // kv
    s_k = k.shape[2]
    bq, bk = _fit_block(s_q, RING_DQ_BLOCK_Q), _fit_block(s_k, RING_DQ_BLOCK_K)
    scale = 1.0 / (d ** 0.5)
    grid = (b, h, s_q // bq, s_k // bk)

    def q_idx(bi, hi, qi, ki, offs):
        return (bi, hi, qi, 0)

    kv_idx = _q_major_kv_idx(bq, bk, group, causal)
    qmat = pl.BlockSpec((1, 1, bq, d), q_idx)
    qrow = pl.BlockSpec((1, 1, bq, 1), q_idx)
    kmat = pl.BlockSpec((1, 1, bk, d), kv_idx)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[qmat, kmat, kmat, qmat, qrow, qrow, qmat],
        out_specs=[qmat],
    )
    kernel = functools.partial(_carry_dq_kernel, block_q=bq, block_k=bk,
                               scale=scale, causal=causal)
    (out,) = pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=[jax.ShapeDtypeStruct(dq.shape, jnp.float32)],
        interpret=interpret,
    )(_offs(q_off, k_off), q, k, v, do, lse, delta, dq)
    return out


def carry_dkv(q, k, v, do, lse, delta, dk, dv, q_off, k_off, *, causal=True,
              interpret=False):
    """One ring step of the flash dk/dv: accumulate the local q rows'
    (unscaled) contributions into the traveling fp32 (dk, dv) carry of the
    visiting KV block. Grid runs one step per KV head; the GQA query-head
    group is accumulated in-kernel (same scheme as the flash dkv kernels)."""
    b, h, s_q, d = q.shape
    kv = k.shape[1]
    group = h // kv
    s_k = k.shape[2]
    bq, bk = _fit_block(s_q, RING_DKV_BLOCK_Q), _fit_block(s_k, RING_DKV_BLOCK_K)
    scale = 1.0 / (d ** 0.5)
    grid = (b, kv, s_k // bk, s_q // bq)

    if causal:
        def q_idx(bi, hi, ki, qi, offs):
            # Fetch-elide q tiles wholly before this k block can be seen.
            first = (offs[1] + ki * bk - offs[0]) // bq
            n_q = s_q // bq
            return (bi, hi,
                    jnp.clip(jnp.maximum(qi, first), 0, n_q - 1), 0)
    else:
        def q_idx(bi, hi, ki, qi, offs):
            return (bi, hi, qi, 0)

    def kv_idx(bi, hi, ki, qi, offs):
        return (bi, hi, ki, 0)

    qmat = pl.BlockSpec((1, group, bq, d), q_idx)
    qrow = pl.BlockSpec((1, group, bq, 1), q_idx)
    kmat = pl.BlockSpec((1, 1, bk, d), kv_idx)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[qmat, kmat, kmat, qmat, qrow, qrow, kmat, kmat],
        out_specs=[kmat, kmat],
    )
    kernel = functools.partial(_carry_dkv_kernel, block_q=bq, block_k=bk,
                               scale=scale, causal=causal)
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=[jax.ShapeDtypeStruct(dk.shape, jnp.float32),
                   jax.ShapeDtypeStruct(dv.shape, jnp.float32)],
        interpret=interpret,
    )(_offs(q_off, k_off), q, k, v, do, lse, delta, dk, dv)


def delta_rows(do, o):
    """Rowwise dO . O over the head dim, (B,H,S,1) fp32 — computed once per
    backward before the ring loop (both operands are device-local)."""
    return _delta(do, o)
