"""Sequence-parallel causal ring attention (long-context support).

The reference has no long-context machinery (SURVEY.md §5.7) — full causal
SDPA bounded by one GPU's memory. This is the TPU-native scale-out path: the
sequence is sharded over the mesh's 'sequence' axis, each device computes
online-softmax partial attention for its query block while KV blocks rotate
around the ring via ``lax.ppermute`` over ICI, overlapping compute with
neighbor exchange. Memory per device is O(S / sp); no (S, S) score matrix
ever exists.

The local block math runs on the Pallas carry kernels (ops/ring_flash.py):
each ring step is one k-phase of the flash forward/backward with the
online-softmax (fwd) or gradient (bwd) state threaded between pallas calls,
so per-step memory is O(tile) VMEM — never an (S/sp, S/sp) score tensor.
The backward is a second ring pass under a custom VJP: dq accumulates on
the query's device while (dk, dv) travel with their KV block and take one
extra hop home. ``impl="xla"`` keeps the original plain-einsum local math
as an independent oracle for parity tests.

Causality without wasted work: device ``i`` starts with its own KV block
(the diagonal, causal-masked), then receives blocks ``i-1, i-2, ...``; blocks
from the future are fully masked and contribute nothing to the softmax
accumulators.

Two sequence layouts are supported:

- ``contiguous`` — shard ``i`` holds global positions ``[i*S/sp, (i+1)*S/sp)``.
  Simple, but causal work is imbalanced: device 0's queries attend one block
  while device sp-1's attend all of them, and since every ring step is gated
  by the lockstep ``ppermute``, the busiest device sets the pace (a per-step
  ``lax.cond`` skip of fully-masked blocks was tried and reverted — it saves
  FLOPs but zero wall-clock).
- ``zigzag`` — the sequence is split into ``2*sp`` chunks and shard ``i``
  holds chunks ``(i, 2*sp-1-i)``: one early chunk plus its mirrored late
  chunk. Then at every ring step ``t>0`` each device has exactly half a
  block of *unmasked* work — either all its queries against the visiting
  early chunk (KV from an earlier device) or its late queries against both
  visiting chunks (KV from a later device) — two equal-FLOP ``lax.cond``
  branches, so the ring stays in lockstep while doing ~2x fewer FLOPs than
  contiguous, evenly. The layout permutation is applied once to the token
  stream by the train step (training/step.py) — RoPE and the causal mask see
  true global positions; the summed CE loss is permutation-invariant.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import active_mesh
from .flash_attention import LN2, _interpret
from .ring_flash import (
    carry_dkv,
    carry_dq,
    carry_fwd,
    delta_rows,
    finalize_carry,
    fresh_carry,
)

from ..utils.jax_compat import shard_map as _shard_map

NEG_INF = -1e30


def _local_update(qg, k_blk, v_blk, m, l, acc, q_pos, k_pos, scale):
    """One online-softmax accumulation of q against a single KV block.

    ``q_pos``/``k_pos`` of None means the caller guarantees every (q, k)
    pair in the block is causally valid — no mask is applied."""
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if q_pos is not None:
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None, :, :]
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bkgqs,bskd->bkgqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _unmasked_update(qg, k_blk, v_blk, m, l, acc, scale):
    return _local_update(qg, k_blk, v_blk, m, l, acc, None, None, scale)


def zigzag_ok(seq_len: int, sp: int) -> bool:
    """Whether the zigzag layout applies: needs 2*sp even chunks."""
    return sp > 1 and seq_len % (2 * sp) == 0


def zigzag_perm(seq_len: int, sp: int) -> np.ndarray:
    """Global sequence permutation for the zigzag layout.

    ``permuted[j] = original[perm[j]]``: split the sequence into ``2*sp``
    chunks; contiguous shard ``i`` of the permuted sequence holds chunks
    ``(i, 2*sp-1-i)``. Static (trace-time) data."""
    c = seq_len // (2 * sp)
    chunks = np.arange(seq_len, dtype=np.int32).reshape(2 * sp, c)
    order = [x for i in range(sp) for x in (i, 2 * sp - 1 - i)]
    return chunks[order].reshape(-1)


def zigzag_layout_active(cfg, seq_len: int, sp: int) -> bool:
    """The single predicate deciding whether the train step permutes tokens
    into the zigzag layout — must mirror the model's attention dispatch
    (models/llama.py: ring is used iff impl is auto|ring and sp > 1) plus
    the ring op's own ``zigzag_ok`` divisibility fallback, or masking and
    layout would disagree."""
    return (sp > 1 and cfg.attention_impl in ("auto", "ring")
            and cfg.sp_layout == "zigzag" and zigzag_ok(seq_len, sp))


def _zigzag_pos(idx, sp: int, c: int):
    """(2c,) true global positions of the shard holding chunks
    ``(idx, 2*sp-1-idx)``."""
    lo = idx * c + jnp.arange(c)
    hi = (2 * sp - 1 - idx) * c + jnp.arange(c)
    return jnp.concatenate([lo, hi])


def _ring_local_zigzag(q, k, v, *, sp: int, axis_name: str):
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    kv_heads = k.shape[2]
    g = h // kv_heads
    c = s_loc // 2
    scale = 1.0 / (d ** 0.5)
    qg = q.reshape(b, s_loc, kv_heads, g, d)
    q_pos = _zigzag_pos(my, sp, c)

    m = jnp.full((b, kv_heads, g, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv_heads, g, s_loc), jnp.float32)
    acc = jnp.zeros((b, kv_heads, g, s_loc, d), jnp.float32)

    perm = _ring_perm(sp)
    k_blk, v_blk = k, v
    for t in range(sp):
        if t == 0:
            # Diagonal: both chunk pairs are our own — positional causal mask.
            m, l, acc = _local_update(qg, k_blk, v_blk, m, l, acc, q_pos,
                                      q_pos, scale)
        else:
            src = (my - t) % sp

            def from_earlier(ops, kb=k_blk, vb=v_blk):
                # Visiting KV came from an earlier ring slot: chunk src is
                # entirely in our past, chunk 2*sp-1-src entirely in our
                # future — so every query attends exactly the early half.
                m, l, acc = ops
                return _unmasked_update(qg, kb[:, :c], vb[:, :c], m, l, acc,
                                        scale)

            def from_later(ops, kb=k_blk, vb=v_blk):
                # Visiting KV came from a later slot: our early chunk sees
                # nothing, our late chunk (2*sp-1-my) sees both visiting
                # chunks in full. Same FLOPs as the other branch.
                m, l, acc = ops
                m2, l2, acc2 = _unmasked_update(
                    qg[:, c:], kb, vb, m[..., c:], l[..., c:],
                    acc[..., c:, :], scale)
                return (jnp.concatenate([m[..., :c], m2], axis=-1),
                        jnp.concatenate([l[..., :c], l2], axis=-1),
                        jnp.concatenate([acc[..., :c, :], acc2], axis=-2))

            m, l, acc = jax.lax.cond(src < my, from_earlier, from_later,
                                     (m, l, acc))
        if t + 1 < sp:
            k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)

    out = acc / l[..., None]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def _ring_local(q, k, v, *, sp: int, axis_name: str):
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    kv_heads = k.shape[2]
    g = h // kv_heads
    scale = 1.0 / (d ** 0.5)
    qg = q.reshape(b, s_loc, kv_heads, g, d)
    q_pos = my * s_loc + jnp.arange(s_loc)

    m = jnp.full((b, kv_heads, g, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv_heads, g, s_loc), jnp.float32)
    acc = jnp.zeros((b, kv_heads, g, s_loc, d), jnp.float32)

    perm = _ring_perm(sp)
    k_blk, v_blk = k, v
    for t in range(sp):
        src = (my - t) % sp  # which global block this device holds at step t
        k_pos = src * s_loc + jnp.arange(s_loc)
        # Future blocks (src > my) are fully masked and mathematically
        # no-ops. Skipping their compute would save FLOPs but no wall-clock:
        # every ring step is gated by the slowest device through the
        # lockstep ppermute, and some device always computes at every step.
        # The fix is the zigzag layout above, which balances causal work.
        m, l, acc = _local_update(qg, k_blk, v_blk, m, l, acc, q_pos, k_pos,
                                  scale)
        if t + 1 < sp:
            k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)

    out = acc / l[..., None]  # (b, kv, g, s_loc, d)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def _ring_perm(sp):
    return [(i, (i + 1) % sp) for i in range(sp)]


def _flash_fwd_impl(q, k, v, sp, axis_name, zigzag):
    """Ring forward with Pallas carry kernels: O(block) VMEM per step, no
    (S/sp, S/sp) score tensor (the round-1 einsum path materialized one).

    Per-device shards: q (b, s_loc, h, d), k/v (b, s_loc, kv, d). Internally
    (B, H, S, D) — transposed once here, not per ring step. Returns the
    attention output in the input layout plus the base-2 lse residual."""
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    itp = _interpret()
    m, l, acc = fresh_carry(b, h, s_loc, d)
    c = s_loc // 2
    k_blk, v_blk = kt, vt
    for t in range(sp):
        src = (my - t) % sp
        if not zigzag:
            # One causal kernel per step; global offsets make the diagonal
            # mask itself, past blocks run unmasked, and future blocks
            # degenerate to carry pass-through (compute and fetch elided
            # tile-by-tile inside the kernel).
            m, l, acc = carry_fwd(qt, k_blk, v_blk, m, l, acc,
                                  my * s_loc, src * s_loc,
                                  causal=True, interpret=itp)
        elif t == 0:
            # Diagonal in the zigzag layout: our chunks are (my, 2sp-1-my).
            # lo x lo and hi x hi are causal at their true global offsets;
            # hi x lo is fully visible; lo x hi is fully future (skipped).
            lo_off, hi_off = my * c, (2 * sp - 1 - my) * c
            m_lo, l_lo, acc_lo = carry_fwd(
                qt[:, :, :c], k_blk[:, :, :c], v_blk[:, :, :c],
                m[:, :, :c], l[:, :, :c], acc[:, :, :c],
                lo_off, lo_off, causal=True, interpret=itp)
            m_hi, l_hi, acc_hi = carry_fwd(
                qt[:, :, c:], k_blk[:, :, c:], v_blk[:, :, c:],
                m[:, :, c:], l[:, :, c:], acc[:, :, c:],
                hi_off, hi_off, causal=True, interpret=itp)
            m_hi, l_hi, acc_hi = carry_fwd(
                qt[:, :, c:], k_blk[:, :, :c], v_blk[:, :, :c],
                m_hi, l_hi, acc_hi, 0, 0, causal=False, interpret=itp)
            m = jnp.concatenate([m_lo, m_hi], axis=2)
            l = jnp.concatenate([l_lo, l_hi], axis=2)
            acc = jnp.concatenate([acc_lo, acc_hi], axis=2)
        else:
            # Equal-FLOP branches (module doc): earlier visitor -> all our
            # queries see its early chunk; later visitor -> our late chunk
            # sees both its chunks. All updates are unmasked.
            def from_earlier(ops, kb=k_blk, vb=v_blk):
                m, l, acc = ops
                return carry_fwd(qt, kb[:, :, :c], vb[:, :, :c], m, l, acc,
                                 0, 0, causal=False, interpret=itp)

            def from_later(ops, kb=k_blk, vb=v_blk):
                m, l, acc = ops
                m2, l2, acc2 = carry_fwd(
                    qt[:, :, c:], kb, vb, m[:, :, c:], l[:, :, c:],
                    acc[:, :, c:], 0, 0, causal=False, interpret=itp)
                return (jnp.concatenate([m[:, :, :c], m2], axis=2),
                        jnp.concatenate([l[:, :, :c], l2], axis=2),
                        jnp.concatenate([acc[:, :, :c], acc2], axis=2))

            m, l, acc = jax.lax.cond(src < my, from_earlier, from_later,
                                     (m, l, acc))
        if t + 1 < sp:
            k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name,
                                            _ring_perm(sp))
    out, lse = finalize_carry(m, l, acc, q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def _flash_bwd_impl(sp, axis_name, zigzag, res, g):
    """Ring backward: dq accumulates locally; (dk, dv) travel with their KV
    block and take one extra rotation home after the last step. The masking
    geometry mirrors the forward exactly, via the same carry kernels."""
    q, k, v, out, lse = res
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    ot = jnp.transpose(out, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))
    delta = delta_rows(dot, ot)
    itp = _interpret()
    scale = 1.0 / (d ** 0.5)
    c = s_loc // 2
    dq = jnp.zeros((b, h, s_loc, d), jnp.float32)
    k_blk, v_blk = kt, vt
    dk_blk = jnp.zeros(kt.shape, jnp.float32)
    dv_blk = jnp.zeros(vt.shape, jnp.float32)
    for t in range(sp):
        src = (my - t) % sp
        if not zigzag:
            q_off, k_off = my * s_loc, src * s_loc
            dq = carry_dq(qt, k_blk, v_blk, dot, lse, delta, dq,
                          q_off, k_off, causal=True, interpret=itp)
            dk_blk, dv_blk = carry_dkv(qt, k_blk, v_blk, dot, lse, delta,
                                       dk_blk, dv_blk, q_off, k_off,
                                       causal=True, interpret=itp)
        elif t == 0:
            lo_off, hi_off = my * c, (2 * sp - 1 - my) * c
            q_lo, q_hi = qt[:, :, :c], qt[:, :, c:]
            do_lo, do_hi = dot[:, :, :c], dot[:, :, c:]
            lse_lo, lse_hi = lse[:, :, :c], lse[:, :, c:]
            dl_lo, dl_hi = delta[:, :, :c], delta[:, :, c:]
            k_lo, v_lo = k_blk[:, :, :c], v_blk[:, :, :c]
            k_hi, v_hi = k_blk[:, :, c:], v_blk[:, :, c:]
            dq_lo = carry_dq(q_lo, k_lo, v_lo, do_lo, lse_lo, dl_lo,
                             dq[:, :, :c], lo_off, lo_off, causal=True,
                             interpret=itp)
            dq_hi = carry_dq(q_hi, k_hi, v_hi, do_hi, lse_hi, dl_hi,
                             dq[:, :, c:], hi_off, hi_off, causal=True,
                             interpret=itp)
            dq_hi = carry_dq(q_hi, k_lo, v_lo, do_hi, lse_hi, dl_hi,
                             dq_hi, 0, 0, causal=False, interpret=itp)
            dq = jnp.concatenate([dq_lo, dq_hi], axis=2)
            dk_lo, dv_lo = carry_dkv(q_lo, k_lo, v_lo, do_lo, lse_lo, dl_lo,
                                     dk_blk[:, :, :c], dv_blk[:, :, :c],
                                     lo_off, lo_off, causal=True,
                                     interpret=itp)
            dk_lo, dv_lo = carry_dkv(q_hi, k_lo, v_lo, do_hi, lse_hi, dl_hi,
                                     dk_lo, dv_lo, 0, 0, causal=False,
                                     interpret=itp)
            dk_hi, dv_hi = carry_dkv(q_hi, k_hi, v_hi, do_hi, lse_hi, dl_hi,
                                     dk_blk[:, :, c:], dv_blk[:, :, c:],
                                     hi_off, hi_off, causal=True,
                                     interpret=itp)
            dk_blk = jnp.concatenate([dk_lo, dk_hi], axis=2)
            dv_blk = jnp.concatenate([dv_lo, dv_hi], axis=2)
        else:
            def from_earlier(ops, kb=k_blk, vb=v_blk):
                dq, dkb, dvb = ops
                dq = carry_dq(qt, kb[:, :, :c], vb[:, :, :c], dot, lse,
                              delta, dq, 0, 0, causal=False, interpret=itp)
                dk_lo, dv_lo = carry_dkv(qt, kb[:, :, :c], vb[:, :, :c],
                                         dot, lse, delta, dkb[:, :, :c],
                                         dvb[:, :, :c], 0, 0, causal=False,
                                         interpret=itp)
                return (dq,
                        jnp.concatenate([dk_lo, dkb[:, :, c:]], axis=2),
                        jnp.concatenate([dv_lo, dvb[:, :, c:]], axis=2))

            def from_later(ops, kb=k_blk, vb=v_blk):
                dq, dkb, dvb = ops
                dq_hi = carry_dq(qt[:, :, c:], kb, vb, dot[:, :, c:],
                                 lse[:, :, c:], delta[:, :, c:],
                                 dq[:, :, c:], 0, 0, causal=False,
                                 interpret=itp)
                dq = jnp.concatenate([dq[:, :, :c], dq_hi], axis=2)
                dkb, dvb = carry_dkv(qt[:, :, c:], kb, vb, dot[:, :, c:],
                                     lse[:, :, c:], delta[:, :, c:],
                                     dkb, dvb, 0, 0, causal=False,
                                     interpret=itp)
                return dq, dkb, dvb

            dq, dk_blk, dv_blk = jax.lax.cond(
                src < my, from_earlier, from_later, (dq, dk_blk, dv_blk))
        if t + 1 < sp:
            k_blk, v_blk, dk_blk, dv_blk = jax.lax.ppermute(
                (k_blk, v_blk, dk_blk, dv_blk), axis_name, _ring_perm(sp))
    # After sp-1 rotations the traveling gradients sit one hop short of
    # their owner; one more ppermute completes the circle.
    dk_blk, dv_blk = jax.lax.ppermute((dk_blk, dv_blk), axis_name,
                                      _ring_perm(sp))
    dq_out = jnp.transpose(dq * scale, (0, 2, 1, 3)).astype(q.dtype)
    dk_out = jnp.transpose(dk_blk * LN2, (0, 2, 1, 3)).astype(k.dtype)
    dv_out = jnp.transpose(dv_blk, (0, 2, 1, 3)).astype(v.dtype)
    return dq_out, dk_out, dv_out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, sp, axis_name, zigzag):
    out, _ = _flash_fwd_impl(q, k, v, sp, axis_name, zigzag)
    return out


def _ring_flash_fwd(q, k, v, sp, axis_name, zigzag):
    out, lse = _flash_fwd_impl(q, k, v, sp, axis_name, zigzag)
    return out, (q, k, v, out, lse)


_ring_flash.defvjp(_ring_flash_fwd, _flash_bwd_impl)


def _ring_local_flash(q, k, v, *, sp: int, axis_name: str):
    return _ring_flash(q, k, v, sp, axis_name, False)


def _ring_local_flash_zigzag(q, k, v, *, sp: int, axis_name: str):
    return _ring_flash(q, k, v, sp, axis_name, True)


def ring_attention(q, k, v, axis_name: str = "sequence", mesh=None,
                   zigzag: bool = False, impl: str = "flash") -> jax.Array:
    """Causal GQA attention with the sequence dim sharded over ``axis_name``.

    q: (B, S, H, D); k/v: (B, S, K, D) — global (jit) view; internally a
    shard_map over the active mesh rotates KV blocks around the ring.
    With ``zigzag=True`` the inputs must already be in the zigzag sequence
    layout (``zigzag_perm``; the train step applies it) — the op then does
    ~2x fewer, evenly balanced FLOPs per device.
    """
    mesh = mesh or active_mesh()
    if mesh is None or mesh.shape[axis_name] == 1:
        from .attention import xla_attention
        return xla_attention(q, k, v, causal=True)
    sp = mesh.shape[axis_name]
    use_zigzag = zigzag and zigzag_ok(q.shape[1], sp)
    if impl == "flash":
        local = _ring_local_flash_zigzag if use_zigzag else _ring_local_flash
    elif impl == "xla":  # plain-einsum reference path (parity oracle)
        local = _ring_local_zigzag if use_zigzag else _ring_local
    else:
        raise ValueError(f"unknown ring attention impl: {impl!r}")
    # Degrade per-axis when a dim is not divisible by its mesh axes (e.g. the
    # batch-1 dummy used by model.init): shard_map then replicates that dim,
    # which is always semantically valid.
    dp_total = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    tp = mesh.shape.get("tensor", 1)
    batch_axes = ("data", "fsdp") if q.shape[0] % dp_total == 0 else None
    head_axis = ("tensor"
                 if q.shape[2] % tp == 0 and k.shape[2] % tp == 0 else None)
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = _shard_map(
        functools.partial(local, sp=sp, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
