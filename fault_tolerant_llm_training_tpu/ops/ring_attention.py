"""Sequence-parallel causal ring attention (long-context support).

The reference has no long-context machinery (SURVEY.md §5.7) — full causal
SDPA bounded by one GPU's memory. This is the TPU-native scale-out path: the
sequence is sharded over the mesh's 'sequence' axis, each device computes
online-softmax partial attention for its query block while KV blocks rotate
around the ring via ``lax.ppermute`` over ICI, overlapping compute with
neighbor exchange. Memory per device is O(S / sp); no (S, S) score matrix
ever exists.

Causality without wasted work: device ``i`` starts with its own KV block
(the diagonal, causal-masked), then receives blocks ``i-1, i-2, ...``; blocks
from the future are fully masked and contribute nothing to the softmax
accumulators.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import active_mesh

try:  # jax >= 0.6 exposes shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

NEG_INF = -1e30


def _local_update(qg, k_blk, v_blk, m, l, acc, q_pos, k_pos, scale):
    """One online-softmax accumulation of q against a single KV block."""
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_blk,
                   preferred_element_type=jnp.float32) * scale
    mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None, :, :]
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bkgqs,bskd->bkgqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _ring_local(q, k, v, *, sp: int, axis_name: str):
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    kv_heads = k.shape[2]
    g = h // kv_heads
    scale = 1.0 / (d ** 0.5)
    qg = q.reshape(b, s_loc, kv_heads, g, d)
    q_pos = my * s_loc + jnp.arange(s_loc)

    m = jnp.full((b, kv_heads, g, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv_heads, g, s_loc), jnp.float32)
    acc = jnp.zeros((b, kv_heads, g, s_loc, d), jnp.float32)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    k_blk, v_blk = k, v
    for t in range(sp):
        src = (my - t) % sp  # which global block this device holds at step t
        k_pos = src * s_loc + jnp.arange(s_loc)
        # Future blocks (src > my) are fully masked and mathematically
        # no-ops. Skipping their compute would save FLOPs but no wall-clock:
        # every ring step is gated by the slowest device through the
        # lockstep ppermute, and some device always computes at every step.
        # The real fix is zigzag/striped block placement (each device holds
        # one early and one mirrored late chunk, balancing causal work) —
        # a data-layout change tracked in ROUND_NOTES.md.
        m, l, acc = _local_update(qg, k_blk, v_blk, m, l, acc, q_pos, k_pos,
                                  scale)
        if t + 1 < sp:
            k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)

    out = acc / l[..., None]  # (b, kv, g, s_loc, d)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name: str = "sequence", mesh=None
                   ) -> jax.Array:
    """Causal GQA attention with the sequence dim sharded over ``axis_name``.

    q: (B, S, H, D); k/v: (B, S, K, D) — global (jit) view; internally a
    shard_map over the active mesh rotates KV blocks around the ring.
    """
    mesh = mesh or active_mesh()
    if mesh is None or mesh.shape[axis_name] == 1:
        from .attention import xla_attention
        return xla_attention(q, k, v, causal=True)
    sp = mesh.shape[axis_name]
    # Degrade per-axis when a dim is not divisible by its mesh axes (e.g. the
    # batch-1 dummy used by model.init): shard_map then replicates that dim,
    # which is always semantically valid.
    dp_total = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    tp = mesh.shape.get("tensor", 1)
    batch_axes = ("data", "fsdp") if q.shape[0] % dp_total == 0 else None
    head_axis = ("tensor"
                 if q.shape[2] % tp == 0 and k.shape[2] % tp == 0 else None)
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = _shard_map(
        functools.partial(_ring_local, sp=sp, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
