"""Sequence-parallel causal ring attention (long-context support).

The reference has no long-context machinery (SURVEY.md §5.7) — full causal
SDPA bounded by one GPU's memory. This is the TPU-native scale-out path: the
sequence is sharded over the mesh's 'sequence' axis, each device computes
online-softmax partial attention for its query block while KV blocks rotate
around the ring via ``lax.ppermute`` over ICI, overlapping compute with
neighbor exchange. Memory per device is O(S / sp); no (S, S) score matrix
ever exists.

Causality without wasted work: device ``i`` starts with its own KV block
(the diagonal, causal-masked), then receives blocks ``i-1, i-2, ...``; blocks
from the future are fully masked and contribute nothing to the softmax
accumulators.

Two sequence layouts are supported:

- ``contiguous`` — shard ``i`` holds global positions ``[i*S/sp, (i+1)*S/sp)``.
  Simple, but causal work is imbalanced: device 0's queries attend one block
  while device sp-1's attend all of them, and since every ring step is gated
  by the lockstep ``ppermute``, the busiest device sets the pace (a per-step
  ``lax.cond`` skip of fully-masked blocks was tried and reverted — it saves
  FLOPs but zero wall-clock).
- ``zigzag`` — the sequence is split into ``2*sp`` chunks and shard ``i``
  holds chunks ``(i, 2*sp-1-i)``: one early chunk plus its mirrored late
  chunk. Then at every ring step ``t>0`` each device has exactly half a
  block of *unmasked* work — either all its queries against the visiting
  early chunk (KV from an earlier device) or its late queries against both
  visiting chunks (KV from a later device) — two equal-FLOP ``lax.cond``
  branches, so the ring stays in lockstep while doing ~2x fewer FLOPs than
  contiguous, evenly. The layout permutation is applied once to the token
  stream by the train step (training/step.py) — RoPE and the causal mask see
  true global positions; the summed CE loss is permutation-invariant.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import active_mesh

try:  # jax >= 0.6 exposes shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

NEG_INF = -1e30


def _local_update(qg, k_blk, v_blk, m, l, acc, q_pos, k_pos, scale):
    """One online-softmax accumulation of q against a single KV block.

    ``q_pos``/``k_pos`` of None means the caller guarantees every (q, k)
    pair in the block is causally valid — no mask is applied."""
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if q_pos is not None:
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None, :, :]
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bkgqs,bskd->bkgqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _unmasked_update(qg, k_blk, v_blk, m, l, acc, scale):
    return _local_update(qg, k_blk, v_blk, m, l, acc, None, None, scale)


def zigzag_ok(seq_len: int, sp: int) -> bool:
    """Whether the zigzag layout applies: needs 2*sp even chunks."""
    return sp > 1 and seq_len % (2 * sp) == 0


def zigzag_perm(seq_len: int, sp: int) -> np.ndarray:
    """Global sequence permutation for the zigzag layout.

    ``permuted[j] = original[perm[j]]``: split the sequence into ``2*sp``
    chunks; contiguous shard ``i`` of the permuted sequence holds chunks
    ``(i, 2*sp-1-i)``. Static (trace-time) data."""
    c = seq_len // (2 * sp)
    chunks = np.arange(seq_len, dtype=np.int32).reshape(2 * sp, c)
    order = [x for i in range(sp) for x in (i, 2 * sp - 1 - i)]
    return chunks[order].reshape(-1)


def zigzag_layout_active(cfg, seq_len: int, sp: int) -> bool:
    """The single predicate deciding whether the train step permutes tokens
    into the zigzag layout — must mirror the model's attention dispatch
    (models/llama.py: ring is used iff impl is auto|ring and sp > 1) plus
    the ring op's own ``zigzag_ok`` divisibility fallback, or masking and
    layout would disagree."""
    return (sp > 1 and cfg.attention_impl in ("auto", "ring")
            and cfg.sp_layout == "zigzag" and zigzag_ok(seq_len, sp))


def _zigzag_pos(idx, sp: int, c: int):
    """(2c,) true global positions of the shard holding chunks
    ``(idx, 2*sp-1-idx)``."""
    lo = idx * c + jnp.arange(c)
    hi = (2 * sp - 1 - idx) * c + jnp.arange(c)
    return jnp.concatenate([lo, hi])


def _ring_local_zigzag(q, k, v, *, sp: int, axis_name: str):
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    kv_heads = k.shape[2]
    g = h // kv_heads
    c = s_loc // 2
    scale = 1.0 / (d ** 0.5)
    qg = q.reshape(b, s_loc, kv_heads, g, d)
    q_pos = _zigzag_pos(my, sp, c)

    m = jnp.full((b, kv_heads, g, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv_heads, g, s_loc), jnp.float32)
    acc = jnp.zeros((b, kv_heads, g, s_loc, d), jnp.float32)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    k_blk, v_blk = k, v
    for t in range(sp):
        if t == 0:
            # Diagonal: both chunk pairs are our own — positional causal mask.
            m, l, acc = _local_update(qg, k_blk, v_blk, m, l, acc, q_pos,
                                      q_pos, scale)
        else:
            src = (my - t) % sp

            def from_earlier(ops, kb=k_blk, vb=v_blk):
                # Visiting KV came from an earlier ring slot: chunk src is
                # entirely in our past, chunk 2*sp-1-src entirely in our
                # future — so every query attends exactly the early half.
                m, l, acc = ops
                return _unmasked_update(qg, kb[:, :c], vb[:, :c], m, l, acc,
                                        scale)

            def from_later(ops, kb=k_blk, vb=v_blk):
                # Visiting KV came from a later slot: our early chunk sees
                # nothing, our late chunk (2*sp-1-my) sees both visiting
                # chunks in full. Same FLOPs as the other branch.
                m, l, acc = ops
                m2, l2, acc2 = _unmasked_update(
                    qg[:, c:], kb, vb, m[..., c:], l[..., c:],
                    acc[..., c:, :], scale)
                return (jnp.concatenate([m[..., :c], m2], axis=-1),
                        jnp.concatenate([l[..., :c], l2], axis=-1),
                        jnp.concatenate([acc[..., :c, :], acc2], axis=-2))

            m, l, acc = jax.lax.cond(src < my, from_earlier, from_later,
                                     (m, l, acc))
        if t + 1 < sp:
            k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)

    out = acc / l[..., None]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def _ring_local(q, k, v, *, sp: int, axis_name: str):
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    kv_heads = k.shape[2]
    g = h // kv_heads
    scale = 1.0 / (d ** 0.5)
    qg = q.reshape(b, s_loc, kv_heads, g, d)
    q_pos = my * s_loc + jnp.arange(s_loc)

    m = jnp.full((b, kv_heads, g, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv_heads, g, s_loc), jnp.float32)
    acc = jnp.zeros((b, kv_heads, g, s_loc, d), jnp.float32)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    k_blk, v_blk = k, v
    for t in range(sp):
        src = (my - t) % sp  # which global block this device holds at step t
        k_pos = src * s_loc + jnp.arange(s_loc)
        # Future blocks (src > my) are fully masked and mathematically
        # no-ops. Skipping their compute would save FLOPs but no wall-clock:
        # every ring step is gated by the slowest device through the
        # lockstep ppermute, and some device always computes at every step.
        # The fix is the zigzag layout above, which balances causal work.
        m, l, acc = _local_update(qg, k_blk, v_blk, m, l, acc, q_pos, k_pos,
                                  scale)
        if t + 1 < sp:
            k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)

    out = acc / l[..., None]  # (b, kv, g, s_loc, d)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name: str = "sequence", mesh=None,
                   zigzag: bool = False) -> jax.Array:
    """Causal GQA attention with the sequence dim sharded over ``axis_name``.

    q: (B, S, H, D); k/v: (B, S, K, D) — global (jit) view; internally a
    shard_map over the active mesh rotates KV blocks around the ring.
    With ``zigzag=True`` the inputs must already be in the zigzag sequence
    layout (``zigzag_perm``; the train step applies it) — the op then does
    ~2x fewer, evenly balanced FLOPs per device.
    """
    mesh = mesh or active_mesh()
    if mesh is None or mesh.shape[axis_name] == 1:
        from .attention import xla_attention
        return xla_attention(q, k, v, causal=True)
    sp = mesh.shape[axis_name]
    local = _ring_local_zigzag if zigzag and zigzag_ok(q.shape[1], sp) \
        else _ring_local
    # Degrade per-axis when a dim is not divisible by its mesh axes (e.g. the
    # batch-1 dummy used by model.init): shard_map then replicates that dim,
    # which is always semantically valid.
    dp_total = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    tp = mesh.shape.get("tensor", 1)
    batch_axes = ("data", "fsdp") if q.shape[0] % dp_total == 0 else None
    head_axis = ("tensor"
                 if q.shape[2] % tp == 0 and k.shape[2] % tp == 0 else None)
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = _shard_map(
        functools.partial(local, sp=sp, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
