"""Pallas TPU flash attention (causal, GQA-aware), forward + backward.

TPU-native replacement for the reference's fused-kernel dependency
``F.scaled_dot_product_attention(is_causal=True)`` (ref: model.py:212), which
on CUDA comes from the NGC container. Here the kernel is first-party:
an online-softmax tiled forward that never materializes the (S, S) score
matrix — O(S) memory, q-tiles streamed through VMEM, scores computed on the
MXU in fp32 — plus Pallas backward kernels that recompute scores per tile
from the saved logsumexp, so the backward is O(S) memory too (the
flash-attention-2 recomputation scheme; the resident family fuses dq and
dk/dv into one kernel, the streaming family keeps them split).

GQA: the kernels map query head ``h`` to KV head ``h // (H // K)`` in the
BlockSpec index map — KV are never repeated in memory (the reference's
``repeat_kv`` at model.py:129-138 materializes the expansion). dk/dv are
written at native KV-head granularity: the resident fused backward emits
its full-row scratch once per KV-head span, and the streaming dk/dv
kernel runs one grid step per *KV* head, accumulating its query-head
group in-kernel.

VPU economy (attention at head_dim 64 is VPU-bound on TPU, not MXU-bound):

- The causal mask (two iotas + compare + select per (bq, bk) tile) is applied
  only to *diagonal* k-blocks; the k-loop is split into a full-block phase
  with no masking and a masked tail. For bq == bk that is one masked block
  per q-tile instead of all of them.
- Softmax runs in base 2: ``log2(e)`` is folded into the per-tile q scaling
  (one (bq, D) multiply) so the inner loop's only transcendental is a bare
  ``exp2`` — no per-element score scaling at all. The saved logsumexp is
  base-2 as well; it is a kernel-internal residual, consumed only by the
  backward kernels which recompute probabilities as ``exp2(s2 - lse2)``.
  Backward accumulators run unscaled and are rescaled once per tile at the
  final write (exact: the accumulation is linear).

lse is carried padding-free in both families (see _lse_layout): the
streaming family as (B, H, 1, S) — q positions on the LANE dim — and the
resident family as (B, H, S/128, 128) — the lse vector wrapped into full
(8, 128) tiles. The Pallas TPU lowering requires a block's last two dims
to be (8k, 128m)-tileable or full, and the TPU (8, 128) tile pads
whatever lands on the trailing dims: the legacy (B, H, S, 1) residual
(kept for unaligned shapes) pads its singleton lane 128x (measured
95.25 MB per layer at the bench shape, seen in HBM dumps), where (1, S)
pads the singleton sublane only 8x and (S/128, 128) pads nothing.
Kernels read the (1, block_q) row / (block_q/128, 128) block and restore
the (block_q, 1) orientation the tile math uses — once per q tile
(cached in scratch where the k loop is the grid).
delta (rowwise dO . O) is computed inside the backward kernels
from the do/o tiles (see _delta) — an XLA-side delta materializes fp32
casts of the full dO and O with layout-change copies at the custom-call
boundary.

Two kernel families, dispatched on sequence length:

- **Resident** (forward: S <= STREAM_THRESHOLD; backward: S*D within
  RESIDENT_BWD_SD_BUDGET, which reaches past the forward's cutover): the
  non-grid operand (K/V, and the dk/dv gradient accumulators) sits whole
  in VMEM and an in-kernel fori_loop walks it. Fastest at moderate S —
  no per-block pipeline boundaries — but VMEM-bound: the resident rows
  grow linearly with S*D. The backward is ONE fused kernel
  (_bwd_fused_kernel) producing dq, dk and dv from a single pass over
  the causal tile triangle — the split FA2 scheme recomputes the
  VPU-bound softmax core (scores, exp2, dO @ V^T, dS) twice per tile,
  once in dq and once in dk/dv; fusing it measured +10.9% on the
  headline bench (98.2k -> 109.0k tokens/s), +9.4% at bs 16, and −9.6%
  fwd+bwd at S=4096 where it outlives the streamed forward
  (BASELINE.md round 3).
- **Streaming** (S > STREAM_THRESHOLD): the loop moves into the grid's
  innermost dimension; the online-softmax / gradient accumulators live in
  VMEM scratch that persists across grid steps, and every operand is a
  fixed-size tile. O(1) VMEM in S — this is what makes 32k+ contexts
  compile on a single chip (beyond that, ring attention shards S over the
  mesh's 'sequence' axis, ops/ring_attention.py).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes tuned on TPU v5e at S=2048, D=64 (see BASELINE.md); each kernel
# has its own operating point because the blocks play different roles: the
# q-tile is the grid unit in fwd/dq but the loop chunk in dkv, and vice
# versa. FWD retuned again in round 4 after the in-kernel rope shifted the
# balance (512x512: 122.1k vs 120.5k at the round-3 512x1024, and best at
# bs 16 too; round-3's sweep history: the bs-8 peak 256x1024 collapses 26x
# at bs 16 — BASELINE.md).
FWD_BLOCK_Q, FWD_BLOCK_K = 512, 512
DQ_BLOCK_Q, DQ_BLOCK_K = 512, 512
DKV_BLOCK_Q, DKV_BLOCK_K = 512, 1024
# The mid-range STREAMING regime (STREAM_THRESHOLD < S <
# LONG_STREAM_THRESHOLD) keeps the round-3 forward tiles: its A/B there
# (S=8192: -13%, S=16384: -10% vs the older 1024x256) was measured with
# the 1024-wide k-tile, and the round-4 resident retune does not transfer
# (the grid-streamed pipeline amortizes differently).
MID_FWD_BLOCK_Q, MID_FWD_BLOCK_K = 512, 1024
# Very long sequences get their own operating point (tuned at S=32k/64k,
# B1/H12/D64: -6.6% at 32k, -14.5% at 64k vs the resident tiles — the
# grid-streamed pipeline prefers larger k-tiles in fwd/dq and a larger
# q-tile in dkv there). Below LONG_STREAM_THRESHOLD the resident tile
# sizes measured equal (8k) or clearly better (16k: 132 vs 179 ms), so
# the streaming kernels keep them.
LONG_STREAM_THRESHOLD = 32768
STREAM_FWD_BLOCK_Q, STREAM_FWD_BLOCK_K = 1024, 512
STREAM_DQ_BLOCK_Q, STREAM_DQ_BLOCK_K = 512, 1024
STREAM_DKV_BLOCK_Q, STREAM_DKV_BLOCK_K = 1024, 512
# Above this sequence length the resident FORWARD kernel's full-row VMEM
# operands no longer fit the 16M scoped-vmem limit at D=64 (originally
# measured on the split dk/dv kernel at S=4096); switch to the streaming
# kernels.
STREAM_THRESHOLD = 2048
# The fused backward stays viable past the forward's threshold — its
# residency is K/V rows + two (S, D) fp32 dk/dv scratch rows + the
# double-buffered q-side tiles, all linear in S*D: calibrated at D=64,
# S=8192 measured 21.0M > the 16M scoped limit while S=4096 fits, so the
# dispatch bound is S*D <= 4096*64 (a D=128 model hits the same wall at
# half the S). Round-5 on-chip validation (scripts/kernel_checks.py):
# the bound holds WITH in-kernel rope at both boundary shapes — S=4096/
# D=64 and S=2048/D=128 compile and match XLA (the rope path's extra
# (S, D) rotated-K scratch fits; no derate needed, ADVICE r4). The D=64
# tile constants also transfer to D=128 unchanged: a 10-combo resident
# fwd/dq/dkv sweep at S=2048/D=128 (scripts/d128_tile_sweep.py) put the
# defaults first, every variant 8-11% slower. Within the bound but past STREAM_THRESHOLD, the forward
# streams while the backward runs fused (one softmax-core pass instead
# of two).
#
# The 16 MiB figure is XLA's default --xla_tpu_scoped_vmem_limit_kib —
# the compiler's per-kernel scratch budget, NOT the physical VMEM (which
# is 128 MiB on v4/v5p/v6 cores and 64+64 MiB on v5e's paired cores; the
# default limit is the same across current generations, which is why the
# calibrated bound transfers). An operator raising the XLA flag should
# set FTL_SCOPED_VMEM_KIB to match and the S*D bound scales linearly
# with it (the residency is linear in S*D).
SCOPED_VMEM_BYTES = int(os.environ.get("FTL_SCOPED_VMEM_KIB", "16384")) * 1024
RESIDENT_BWD_SD_BUDGET = (4096 * 64) * SCOPED_VMEM_BYTES // (16 * 2**20)


def _fused_bwd_fits(s: int, d: int) -> bool:
    return s * d <= RESIDENT_BWD_SD_BUDGET


def rope_fused_profitable(s: int, d: int) -> bool:
    """Whether in-kernel rope (flash_attention_rope) beats XLA-side rope
    at this shape — the dispatch the model's rope_impl='fused' uses.

    Measured on v5e (BASELINE.md round 4): +3.7% headline at S=2048 and
    −2.6% attention time at S=4096 (resident/fused-backward region, where
    K is roped ONCE per span into scratch), but +2.1% at S=8192 and
    +3.7% at S=16384 — the streaming kernels re-fetch each K tile per
    (q-tile, k-step) grid visit and the rotation rides every fetch, so
    the redundant k-rope grows with S while XLA-side rope stays O(S).
    The boundary is exactly the fused-backward budget."""
    return _fused_bwd_fits(s, d)
NEG_INF = -1e30
LOG2E = math.log2(math.e)
LN2 = math.log(2.0)


def _prescale_q(q_ref_slice, scale):
    """Pre-scale a q tile by scale*log2(e) (base-2 softmax, see module doc).

    Single source of truth for the rounding: the backward's exp2(s - lse) is
    exact only if every kernel scales (and rounds) q identically.
    """
    return (q_ref_slice.astype(jnp.float32) * (scale * LOG2E)).astype(
        q_ref_slice.dtype)


def _rope_j(d: int):
    """The (D, D) pair-rotation matrix J of the interleaved RoPE convention:
    ``(x @ J)[2j] = -x[2j+1]`` and ``(x @ J)[2j+1] = x[2j]``.

    Lets the kernels apply RoPE as ``x*cos2 + (x@J)*sin2`` — one tiny MXU
    matmul instead of even/odd lane shuffles (which Mosaic lowers poorly)
    or an XLA-side rope whose strided-pair reshapes force the fp32
    relayout-copy family at the custom-call boundary (BASELINE.md round-4
    profile). Entries are exactly +-1, so the product is exact in fp32.
    """
    r = jax.lax.broadcasted_iota(jnp.int32, (d, d), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (d, d), 1)
    plus = (c == r + 1) & (r % 2 == 0)
    minus = (c == r - 1) & (r % 2 == 1)
    return (jnp.where(plus, 1.0, 0.0)
            + jnp.where(minus, -1.0, 0.0)).astype(jnp.float32)


def _rope_rot(x, c, s, scale_const=None):
    """Interleaved-pair RoPE rotation of a (rows, D) tile, fp32 internal.

    ``c``/``s`` are (rows, D) fp32 interleave-duplicated tables
    (``c[r, 2j] == c[r, 2j+1] == cos(angle_j(r))``). With ``scale_const``
    the softmax prescale (scale * log2(e), see _prescale_q) is folded in.
    Rounds back to ``x.dtype`` ONCE at the end; the XLA-side chain rounds
    twice on q (``apply_rope`` -> dtype, then ``_prescale_q`` -> dtype),
    so under bf16 the two paths can differ by that one extra rounding —
    fp32 is bit-identical (ADVICE r4). Within THIS path the forward and
    backward recompute the rotation identically, so ``exp2(s - lse)``
    stays exact regardless."""
    xf = x.astype(jnp.float32)
    xj = jax.lax.dot_general(xf, _rope_j(x.shape[-1]), (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    out = xf * c + xj * s
    if scale_const is not None:
        out = out * scale_const
    return out.astype(x.dtype)


def _rope_rot_t(g, c, s):
    """Transpose (= inverse) rotation applied to an fp32 cotangent tile:
    ``rot^T(g) = g*c - (g*s) @ J`` (J^T = -J; the duplicated-halves
    structure of the tables makes s commute with the pair swap). The
    backward kernels emit dq/dk through this — gradients w.r.t. the RAW
    pre-rope q/k, so no XLA-side rope backward exists at all."""
    return g * c - jax.lax.dot_general(
        g * s, _rope_j(g.shape[-1]), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _causal_select(s, q_start, k_start):
    """Apply the causal mask to a (bq, bk) score tile in place."""
    bq, bk = s.shape
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _scores(q2, k, q_start, k_start, masked):
    """q2 @ k^T base-2 scores (fp32); q2 is pre-scaled by scale*log2(e).

    Applies the causal select only when ``masked``: statically elided for
    full blocks when ``masked`` is a Python bool (resident kernels), or a
    runtime lax.cond when it is a traced predicate (streaming kernels,
    where the diagonal/full distinction is a grid position).
    q2: (bq, D), k: (bk, D) -> (bq, bk).
    """
    s = jax.lax.dot_general(
        q2, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if isinstance(masked, bool):
        return _causal_select(s, q_start, k_start) if masked else s
    return jax.lax.cond(
        masked, lambda x: _causal_select(x, q_start, k_start), lambda x: x, s)


def _online_softmax_step(q2, k, v, carry, q_start, k_start, masked):
    """One online-softmax accumulation over a (bq, bk) tile.

    carry = (m, l, acc) running rowwise max (base-2), normalizer, and fp32
    PV accumulator. Shared by the resident and streaming forward kernels so
    their math can never diverge."""
    m_prev, l_prev, acc_prev = carry
    s = _scores(q2, k, q_start, k_start, masked)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.exp2(s - m_new[:, None])
    alpha = jnp.exp2(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    acc_new = acc_prev * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _active_tiles(s: int):
    """The (fwd, dq, dkv) (block_q, block_k) pairs the kernels will use at
    sequence length ``s`` — the single source of truth for the
    tile-set dispatch, shared by _flash_fwd, _flash_bwd and _lse_layout
    (which must validate lane alignment against the SAME q-tiles)."""
    if s >= LONG_STREAM_THRESHOLD:
        return ((STREAM_FWD_BLOCK_Q, STREAM_FWD_BLOCK_K),
                (STREAM_DQ_BLOCK_Q, STREAM_DQ_BLOCK_K),
                (STREAM_DKV_BLOCK_Q, STREAM_DKV_BLOCK_K))
    if s > STREAM_THRESHOLD:
        return ((MID_FWD_BLOCK_Q, MID_FWD_BLOCK_K),
                (DQ_BLOCK_Q, DQ_BLOCK_K),
                (DKV_BLOCK_Q, DKV_BLOCK_K))
    return ((FWD_BLOCK_Q, FWD_BLOCK_K),
            (DQ_BLOCK_Q, DQ_BLOCK_K),
            (DKV_BLOCK_Q, DKV_BLOCK_K))


def _lse_layout(s: int, d: int) -> str:
    """The lse residual's memory layout at sequence length ``s``:

    - ``"packed"`` — (B, H, 1, S), q positions on the lane dim. Streaming
      family (s > STREAM_THRESHOLD), where the legacy layout's padding is
      the point — e.g. 384 MB at S=64k — and every q-tile is 128-aligned
      (odd sequence lengths degrade tiles below 128 rows, making the
      packed blocks illegal). Consumers (via _read_lse): the streaming
      backward kernels, and the FUSED resident backward when it runs past
      the forward's threshold (RESIDENT_BWD_SD_BUDGET) — one entry
      transpose per grid step.
    - ``"blocked"`` — (B, H, S/128, 128): the resident family's packed
      form (VERDICT r4 weak #3, the one variant the r2/r3 rejection
      sweeps never built). The forward's (block_q,) lse vector wraps to
      (block_q/128, 128) — a lane-preserving reshape, unlike the r3
      relayout/transpose variants (−1.4 to −3%) — and the fused backward
      unwraps it once per q-tile. Zero padding: the fp32 (S/128, 128)
      plane tiles natively. Requires s and the resident q-tiles to be
      128-multiples; FTL_LSE_RESIDENT=legacy opts out (A/B knob).
    - ``"legacy"`` — (B, H, S, 1), whose singleton lane pads 128x
      (~1.1 GB at the bs-8 bench shape). Kept for unaligned shapes.
    """
    if (s > STREAM_THRESHOLD
            and all(_fit_block(s, bq) % 128 == 0
                    for bq, _ in _active_tiles(s))):
        return "packed"
    # "blocked" additionally requires the FUSED backward (_fused_bwd_fits
    # needs d): the streaming backward kernels have no blocked row_spec,
    # and a shrunken FTL_SCOPED_VMEM_KIB budget (or d >= 256) can route
    # s <= STREAM_THRESHOLD shapes to them while the forward would have
    # emitted the blocked plane — a trace-time Pallas failure.
    if (s <= STREAM_THRESHOLD and s % 128 == 0
            and _fused_bwd_fits(s, d)
            and os.environ.get("FTL_LSE_RESIDENT", "blocked") != "legacy"
            and all(_fit_block(s, bq) % 128 == 0
                    for bq, _ in _active_tiles(s))):
        return "blocked"
    return "legacy"


def _read_lse(ref, g, layout):
    """(block_q, 1) column lse from a kernel ref; ``g`` is the GQA group
    row (0 for per-head refs). Streaming-family layouts only — the
    resident "blocked" plane is unwrapped inline in _bwd_fused_kernel
    (the read needs the grid's q-tile index)."""
    if layout == "packed":
        return jnp.transpose(ref[0, g])  # (1, bq) -> (bq, 1)
    return ref[0, g]


def _delta(do, o):
    """Rowwise dO . O — the softmax-normalization term, (bq, 1) fp32.

    Computed in-kernel from tiles already resident in VMEM: an XLA-side
    delta materializes fp32 casts of the full (B, H, S, D) dO and O with
    layout-change copies around the custom-call boundary (profiled at
    several ms/step, BASELINE.md breakdown).
    """
    return jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1, keepdims=True)


def _dq_tile(q2, k, v, do, lse, delta, q_start, k_start, masked):
    """Unscaled dq contribution of one (bq, bk) tile (caller scales once)."""
    s = _scores(q2, k, q_start, k_start, masked)
    p = jnp.exp2(s - lse)  # exact probabilities; lse is (bq, 1), base-2
    dp = jax.lax.dot_general(  # dO @ V^T: (bq, bk)
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dkv_tile(q2, k, v, do, lse, delta, q_start, k_start, masked):
    """(dk, dv) contributions of one (bq, bk) tile for one GQA query head.

    dk is unscaled: dk_true = (ds*scale)^T @ q_raw = (ds^T @ q2) * ln(2)
    since q2 = q_raw * scale * log2(e); the caller rescales once."""
    s = _scores(q2, k, q_start, k_start, masked)
    p = jnp.exp2(s - lse)
    dv_c = jax.lax.dot_general(  # P^T @ dO
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(  # dO @ V^T
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk_c = jax.lax.dot_general(  # dS^T @ Q2
        ds.astype(q2.dtype), q2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return dk_c, dv_c


def _k_block_bounds(q_start, block_q, s_k, block_k, causal):
    """(n_full, n_total) k-block counts for a q-tile at ``q_start``.

    Blocks [0, n_full) are fully attended (no mask needed); blocks
    [n_full, n_total) straddle the diagonal and need the causal select.
    A k-block [ks, ks+bk) is full iff ks + bk - 1 <= q_start (its every key
    is visible to the tile's *first* row, hence to all rows).
    """
    n_blocks = s_k // block_k
    if not causal:
        return n_blocks, n_blocks
    n_total = jnp.minimum(
        (q_start + block_q + block_k - 1) // block_k, n_blocks)
    n_full = jnp.minimum(q_start // block_k, n_total)
    return n_full, n_total


def _fwd_kernel(*refs, block_k: int, scale: float, causal: bool,
                rope: bool = False, group: int = 1,
                lse_blocked: bool = False):
    # q_ref/o_ref: (1, 1, block_q, D); k_ref/v_ref: (1, 1, S, D);
    # lse_ref: (1, 1, block_q/128, 128) in the blocked layout (the
    # resident default — the (block_q,) lse vector wraps lane-preserving,
    # see _lse_layout), else (1, 1, block_q, 1) legacy.
    # rope=True adds (cq, sq) q-row and (ck, sk) full-row table refs plus a
    # (S, D) scratch holding this KV head's rotated K (computed once per
    # GQA span — see _rope_rot; q is rotated per tile with the softmax
    # prescale folded into the tables' scalar).
    if rope:
        (q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref,
         o_ref, lse_ref, k2_scr) = refs

        @pl.when((pl.program_id(2) == 0) & (pl.program_id(1) % group == 0))
        def _rope_k():
            k2_scr[...] = _rope_rot(k_ref[0, 0], ck_ref[...], sk_ref[...])

        q2 = _rope_rot(q_ref[0, 0], cq_ref[...], sq_ref[...], scale * LOG2E)

        def k_at(start):
            return k2_scr[pl.ds(start, block_k), :]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        q2 = _prescale_q(q_ref[0, 0], scale)

        def k_at(start):
            return k_ref[0, 0, pl.ds(start, block_k), :]
    block_q, d = q2.shape
    s_k = k_ref.shape[2]
    q_start = pl.program_id(2) * block_q
    n_full, n_total = _k_block_bounds(q_start, block_q, s_k, block_k, causal)

    def body(j, carry, masked):
        k_start = j * block_k
        k = k_at(k_start)
        v = v_ref[0, 0, pl.ds(k_start, block_k), :]
        return _online_softmax_step(q2, k, v, carry, q_start, k_start, masked)

    init = (jnp.full((block_q,), NEG_INF, jnp.float32),
            jnp.zeros((block_q,), jnp.float32),
            jnp.zeros((block_q, d), jnp.float32))
    carry = jax.lax.fori_loop(
        0, n_full, functools.partial(body, masked=False), init)
    m, l, acc = jax.lax.fori_loop(
        n_full, n_total, functools.partial(body, masked=causal), carry)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log2(l)  # base-2, internal only
    if lse_blocked:
        # Full (S/128, 128) plane revisited across q-tiles (Mosaic wants
        # block dims 8/128-divisible or full; block_q/128 rows is neither
        # at the production tiles) — each tile stores its wrapped rows.
        rows = block_q // 128
        lse_ref[0, 0, pl.ds(pl.program_id(2) * rows, rows), :] = (
            lse.reshape(rows, 128))
    else:
        lse_ref[0, 0] = lse[:, None]


def _bwd_fused_kernel(*refs, block_k: int, scale: float, causal: bool,
                      group: int, lse_layout: str, rope: bool = False):
    """Fused resident backward: dq, dk and dv from ONE pass over the score
    tiles.

    The split FA2 kernels each recompute the tile's scores, probabilities
    (exp2) and dP = dO @ V^T — i.e. the whole VPU-bound softmax core runs
    twice per (q, k) tile. Here the grid walks q tiles (like the dq
    kernel); dq accumulates per grid step, while dk/dv accumulate into
    full-row fp32 VMEM scratch that persists across the (GQA group x
    q-tile) span of one KV head and is emitted once at the span's last
    step. Per tile: 5 matmuls + 1 exp pass, vs the split kernels' 7 + 2.
    Resident family only — the scratch is (S, D) fp32, which is exactly
    the full-row VMEM residency that defines the family.

    Grid (b, h, qi), qi innermost. q/do/o/dq: (1, 1, block_q, D) at qi;
    k/v: (1, 1, S, D) and dk/dv out: (1, 1, S, D) at KV head h // group
    (their blocks are revisited across the span, written back on the last
    step); lse: (1, 1, block_q, 1).

    rope=True adds (cq, sq) q-row and (ck, sk) full-row RAW table refs plus
    a (S, D) rotated-K scratch: scores recompute the forward's exact
    rotation; dq/dk are emitted through the transpose rotation
    (_rope_rot_t) so the kernel's outputs are gradients w.r.t. the raw
    pre-rope q/k — no XLA-side rope backward exists.
    """
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref, cq_ref, sq_ref,
         ck_ref, sk_ref, dq_ref, dk_ref, dv_ref,
         dk_scr, dv_scr, k2_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref,
         dq_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
    hi = pl.program_id(1)
    qi = pl.program_id(2)
    n_qi = pl.num_programs(2)

    @pl.when((qi == 0) & (hi % group == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)
        if rope:
            k2_scr[...] = _rope_rot(k_ref[0, 0], ck_ref[...], sk_ref[...])

    if rope:
        q2 = _rope_rot(q_ref[0, 0], cq_ref[...], sq_ref[...], scale * LOG2E)
    else:
        q2 = _prescale_q(q_ref[0, 0], scale)
    do = do_ref[0, 0]
    # lse is read once per grid step, so the non-legacy layouts afford a
    # single restore each: "packed" (1, block_q) row (above
    # STREAM_THRESHOLD, where the forward streamed) transposes; "blocked"
    # (the resident default) unwraps its rows of the full (S/128, 128)
    # plane back to the (block_q, 1) column.
    if lse_layout == "blocked":
        # Mosaic cannot shape-cast (rows, 128) -> (block_q, 1) directly;
        # per-row (1, 128) -> (128, 1) transposes (the op the packed
        # path uses) + a sublane concat restore the column.
        rows = q2.shape[0] // 128
        band = lse_ref[0, 0, pl.ds(qi * rows, rows), :]
        lse = jnp.concatenate(
            [jnp.transpose(band[r:r + 1, :]) for r in range(rows)], axis=0)
    else:
        lse = _read_lse(lse_ref, 0, lse_layout)
    delta = _delta(do, o_ref[0, 0])
    block_q, d = q2.shape
    s_k = k_ref.shape[2]
    q_start = qi * block_q
    n_full, n_total = _k_block_bounds(q_start, block_q, s_k, block_k, causal)

    def body(j, dq_acc, masked):
        k_start = j * block_k
        if rope:
            k = k2_scr[pl.ds(k_start, block_k), :]
        else:
            k = k_ref[0, 0, pl.ds(k_start, block_k), :]
        v = v_ref[0, 0, pl.ds(k_start, block_k), :]
        s = _scores(q2, k, q_start, k_start, masked)
        p = jnp.exp2(s - lse)
        dp = jax.lax.dot_general(  # dO @ V^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dv_scr[pl.ds(k_start, block_k), :] = (
            dv_scr[pl.ds(k_start, block_k), :]
            + jax.lax.dot_general(  # P^T @ dO
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        dk_scr[pl.ds(k_start, block_k), :] = (
            dk_scr[pl.ds(k_start, block_k), :]
            + jax.lax.dot_general(  # dS^T @ Q2
                ds.astype(q2.dtype), q2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        return dq_acc + jax.lax.dot_general(  # dS @ K
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_full, functools.partial(body, masked=False),
                           jnp.zeros((block_q, d), jnp.float32))
    dq = jax.lax.fori_loop(n_full, n_total,
                           functools.partial(body, masked=causal), dq)
    if rope:
        dq = _rope_rot_t(dq, cq_ref[...], sq_ref[...])
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)

    @pl.when((qi == n_qi - 1) & (hi % group == group - 1))
    def _emit():
        dk = dk_scr[...]
        if rope:
            dk = _rope_rot_t(dk, ck_ref[...], sk_ref[...])
        dk_ref[0, 0] = (dk * LN2).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _stream_bounds(ki, q_start, block_q, n_k, block_k, causal):
    """(useful, masked, n_total) for streamed k-step ``ki`` of a q-tile.

    Single source of truth for the causal grid bounds shared by the fwd and
    dq streaming kernels (the dkv kernel streams the transposed geometry and
    has its own bounds).
    """
    if not causal:
        return True, False, n_k
    n_full, n_total = _k_block_bounds(q_start, block_q, n_k * block_k,
                                      block_k, causal)
    return ki < n_total, ki >= n_full, n_total


def _fwd_stream_kernel(*refs, block_q: int, block_k: int,
                       scale: float, causal: bool, lse_layout: str,
                       rope: bool = False):
    # grid (b, h, qi, ki), ki innermost/sequential. q_ref/o_ref:
    # (1, 1, block_q, D) at qi; k_ref/v_ref: (1, 1, block_k, D) at ki;
    # lse_ref: (1, 1, 1, block_q). Scratch (fp32, persists across ki):
    # m/l (block_q, 1), acc (block_q, D).
    # rope=True adds (cq, sq) q-row tables at qi and (ck, sk) k-row
    # tables at ki (same clamped index map as k/v); q and the k tile are
    # rotated per step — the tile is re-fetched per (qi, ki) anyway, so
    # there is no span to cache across.
    if rope:
        (q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)
    q_start = pl.program_id(2) * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    useful, masked, n_total = _stream_bounds(ki, q_start, block_q, n_k,
                                             block_k, causal)

    @pl.when(useful)
    def _step():
        if rope:
            q2 = _rope_rot(q_ref[0, 0], cq_ref[...], sq_ref[...],
                           scale * LOG2E)
            k = _rope_rot(k_ref[0, 0], ck_ref[...], sk_ref[...])
        else:
            q2 = _prescale_q(q_ref[0, 0], scale)
            k = k_ref[0, 0]
        carry = (m_scr[...][:, 0], l_scr[...][:, 0], acc_scr[...])
        m, l, acc = _online_softmax_step(q2, k, v_ref[0, 0], carry,
                                         q_start, k_start, masked)
        m_scr[...] = m[:, None]
        l_scr[...] = l[:, None]
        acc_scr[...] = acc

    @pl.when(ki == n_total - 1)
    def _emit():
        l = l_scr[...][:, 0]
        o_ref[0, 0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)
        lse = m_scr[...][:, 0] + jnp.log2(l)
        lse_ref[0, 0] = (lse[None, :] if lse_layout == "packed"
                         else lse[:, None])


def _dq_stream_kernel(*refs, block_q: int,
                      block_k: int, scale: float, causal: bool,
                      lse_layout: str, rope: bool = False):
    # grid (b, h, qi, ki), ki innermost. Same tiling as _fwd_stream_kernel
    # plus do/o at qi; lse: (1, 1, 1, block_q). Scratch: dq (block_q, D)
    # fp32, delta and column-oriented lse (block_q, 1) fp32, all persisting
    # across ki (delta/lse depend only on the q tile, so they are computed
    # once at ki == 0).
    # rope=True adds (cq, sq) / (ck, sk) table refs plus a rotated-q2
    # scratch (cached at ki == 0 — the rotation depends only on the q
    # tile); k tiles rotate per step; dq emits through _rope_rot_t.
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref, cq_ref, sq_ref,
         ck_ref, sk_ref, dq_ref, dq_scr, delta_scr, lse_scr, q2_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref,
         dq_ref, dq_scr, delta_scr, lse_scr) = refs
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)
    q_start = pl.program_id(2) * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)
        delta_scr[...] = _delta(do_ref[0, 0], o_ref[0, 0])
        lse_scr[...] = _read_lse(lse_ref, 0, lse_layout)
        if rope:
            q2_scr[...] = _rope_rot(q_ref[0, 0], cq_ref[...], sq_ref[...],
                                    scale * LOG2E)

    useful, masked, n_total = _stream_bounds(ki, q_start, block_q, n_k,
                                             block_k, causal)

    @pl.when(useful)
    def _step():
        if rope:
            q2 = q2_scr[...]
            k = _rope_rot(k_ref[0, 0], ck_ref[...], sk_ref[...])
        else:
            q2 = _prescale_q(q_ref[0, 0], scale)
            k = k_ref[0, 0]
        dq_scr[...] = dq_scr[...] + _dq_tile(
            q2, k, v_ref[0, 0], do_ref[0, 0], lse_scr[...],
            delta_scr[...], q_start, k_start, masked)

    @pl.when(ki == n_total - 1)
    def _emit():
        dq = dq_scr[...]
        if rope:
            dq = _rope_rot_t(dq, cq_ref[...], sq_ref[...])
        dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_stream_kernel(*refs, block_q: int,
                       block_k: int, scale: float, causal: bool,
                       lse_layout: str, rope: bool = False):
    # grid (b, kv_head, ki, qi), qi innermost. k/v/dk/dv: (1, 1, block_k, D)
    # at ki; q/do/o: (1, G, block_q, D) at qi; lse: (1, G, 1, block_q).
    # delta is recomputed per (g, qi) step — negligible next to the tile's
    # matmuls, and qi is the INNER grid axis so a single-tile cache cannot
    # hold it across the k rows.
    # Scratch dk/dv (block_k, D) fp32, persists across qi.
    # rope=True adds (cq, sq) q-row tables at qi and (ck, sk) k-row tables
    # at ki, plus a rotated-k scratch cached at qi == 0 (the k tile is
    # this grid row's constant); q rotates per (g, step) — the tables are
    # head-independent; dk emits through _rope_rot_t.
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref, cq_ref, sq_ref,
         ck_ref, sk_ref, dk_ref, dv_ref, dk_scr, dv_scr, k2_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)
    k_start = pl.program_id(2) * block_k
    q_start = qi * block_q
    group = q_ref.shape[1]
    v = v_ref[0, 0]

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)
        if rope:
            k2_scr[...] = _rope_rot(k_ref[0, 0], ck_ref[...], sk_ref[...])

    if causal:
        j_start = k_start // block_q
        j_full = (k_start + block_k - 1 + block_q - 1) // block_q
        useful = qi >= j_start
        masked = qi < j_full
    else:
        useful, masked = True, False

    @pl.when(useful)
    def _step():
        k = k2_scr[...] if rope else k_ref[0, 0]
        dk_acc, dv_acc = dk_scr[...], dv_scr[...]
        for g in range(group):  # static loop: accumulate the GQA group
            if rope:
                q2 = _rope_rot(q_ref[0, g], cq_ref[...], sq_ref[...],
                               scale * LOG2E)
            else:
                q2 = _prescale_q(q_ref[0, g], scale)
            dk_c, dv_c = _dkv_tile(q2, k, v, do_ref[0, g],
                                   _read_lse(lse_ref, g, lse_layout),
                                   _delta(do_ref[0, g], o_ref[0, g]),
                                   q_start, k_start, masked)
            dk_acc, dv_acc = dk_acc + dk_c, dv_acc + dv_c
        dk_scr[...], dv_scr[...] = dk_acc, dv_acc

    @pl.when(qi == n_q - 1)
    def _emit():
        dk = dk_scr[...]
        if rope:
            dk = _rope_rot_t(dk, ck_ref[...], sk_ref[...])
        dk_ref[0, 0] = (dk * LN2).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _fit_block(s, block):
    """Largest usable tile size <= ``block`` for a sequence of length ``s``.

    The tuned defaults are large (up to 1024); a sequence length they don't
    divide (e.g. 1536) degrades to a smaller tile instead of failing. Tiles
    must divide ``s`` and satisfy the TPU tiling rule from the module doc —
    a multiple of 8 sublanes, or the full dim; if no such divisor exists
    (e.g. prime ``s``), the whole sequence becomes one tile."""
    block = min(block, s)
    if s % block == 0:
        return block
    best = s  # "full" is always a legal tile
    for b in range(8, block + 1, 8):
        if s % b == 0:
            best = b
    if best < block // 4 or best > block * 4:
        import logging
        logging.getLogger(__name__).warning(
            "flash attention: seq len %d forces a %d-row tile far from the "
            "tuned %d; expect degraded throughput (pad the sequence length "
            "to a multiple of a large power of two to avoid this)",
            s, best, block)
    return best


def _blocks(s, block_q, block_k):
    return _fit_block(s, block_q), _fit_block(s, block_k)


def _flash_fwd(q, k, v, causal, interpret):
    # (B, S, H, D) -> (B, H, S, D) so heads become a grid axis.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out, lse = _flash_fwd_t(qt, kt, vt, causal, interpret)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def _flash_fwd_t(qt, kt, vt, causal, interpret, rope_tables=None):
    # Head-major (B, H, S, D) operands — heads are a grid axis.
    # rope_tables: optional (cos2, sin2) interleave-duplicated (S, D) fp32
    # tables — the kernels then apply RoPE to q/k tiles in VMEM
    # (flash_attention_rope); q/k arrive RAW.
    b, h, s, d = qt.shape
    kv_heads = kt.shape[1]
    group = h // kv_heads
    block_q, block_k = _blocks(s, *_active_tiles(s)[0])
    scale = 1.0 / (d ** 0.5)
    layout = _lse_layout(s, d)
    if layout == "packed":
        lse_shape = (b, h, 1, s)
        lse_spec = pl.BlockSpec((1, 1, 1, block_q),
                                lambda bi, hi, qi, *_: (bi, hi, 0, qi))
    elif layout == "blocked":
        lse_shape = (b, h, s // 128, 128)
        lse_spec = pl.BlockSpec((1, 1, s // 128, 128),
                                lambda bi, hi, qi, *_: (bi, hi, 0, 0))
    else:
        lse_shape = (b, h, s, 1)
        lse_spec = pl.BlockSpec((1, 1, block_q, 1),
                                lambda bi, hi, qi, *_: (bi, hi, qi, 0))
    out_shape = [
        jax.ShapeDtypeStruct(qt.shape, qt.dtype),
        jax.ShapeDtypeStruct(lse_shape, jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, *_: (bi, hi, qi, 0)),
        lse_spec,
    ]

    rope = rope_tables is not None
    if s <= STREAM_THRESHOLD:
        kernel = functools.partial(_fwd_kernel, block_k=block_k, scale=scale,
                                   causal=causal, rope=rope, group=group,
                                   lse_blocked=(layout == "blocked"))
        in_specs = [
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
        ]
        operands = (qt, kt, vt)
        scratch = []
        if rope:
            cq_spec = pl.BlockSpec((block_q, d), lambda bi, hi, qi: (qi, 0))
            ck_spec = pl.BlockSpec((s, d), lambda bi, hi, qi: (0, 0))
            in_specs += [cq_spec, cq_spec, ck_spec, ck_spec]
            operands += (*rope_tables, *rope_tables)
            scratch = [pltpu.VMEM((s, d), kt.dtype)]
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, h, s // block_q),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
        )(*operands)
    else:
        kernel = functools.partial(_fwd_stream_kernel, block_q=block_q,
                                   block_k=block_k, scale=scale,
                                   causal=causal, lse_layout=layout,
                                   rope=rope)
        # Causal: grid steps past the diagonal are no-ops in the kernel, so
        # clamp their K/V block index to the last useful one — an unchanged
        # index makes the pipeline skip the HBM fetch entirely.
        if causal:
            def kv_idx(bi, hi, qi, ki):
                last = (qi * block_q + block_q - 1) // block_k
                return (bi, hi // group, jnp.minimum(ki, last), 0)

            def ck_idx(bi, hi, qi, ki):
                last = (qi * block_q + block_q - 1) // block_k
                return (jnp.minimum(ki, last), 0)
        else:
            def kv_idx(bi, hi, qi, ki):
                return (bi, hi // group, ki, 0)

            def ck_idx(bi, hi, qi, ki):
                return (ki, 0)
        kv_spec = pl.BlockSpec((1, 1, block_k, d), kv_idx)
        in_specs = [
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            kv_spec, kv_spec,
        ]
        operands = (qt, kt, vt)
        if rope:
            cq_spec = pl.BlockSpec((block_q, d),
                                   lambda bi, hi, qi, ki: (qi, 0))
            ck_spec = pl.BlockSpec((block_k, d), ck_idx)
            in_specs += [cq_spec, cq_spec, ck_spec, ck_spec]
            operands += (*rope_tables, *rope_tables)
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, h, s // block_q, s // block_k),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            interpret=interpret,
        )(*operands)
    return out, lse


def _flash_bwd(q, k, v, o, lse, g, causal, interpret):
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    ot = jnp.transpose(o, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))
    dq, dk, dv = _flash_bwd_t(qt, kt, vt, ot, lse, dot, causal, interpret)
    return (jnp.transpose(dq, (0, 2, 1, 3)),
            jnp.transpose(dk, (0, 2, 1, 3)),
            jnp.transpose(dv, (0, 2, 1, 3)))


def _flash_bwd_t(qt, kt, vt, ot, lse, dot, causal, interpret,
                 rope_tables=None):
    """Pallas backward on head-major operands. Resident family: ONE fused
    kernel on a (b, h, q-tile) grid producing dq, dk and dv per pass
    (_bwd_fused_kernel). Streaming family: split kernels — dq via a
    (head, q-tile, k-step) grid, dk/dv via a (kv-head, k-tile, q-step)
    grid that accumulates the GQA group in-kernel.

    rope_tables: optional (cos2, sin2) (S, D) fp32 — in-kernel RoPE mode
    (q/k and the saved residuals are RAW; dq/dk come back w.r.t. raw)."""
    b, h, s, d = qt.shape
    kv_heads = kt.shape[1]
    group = h // kv_heads
    (_, __), (dq_q, dq_k), (dkv_q, dkv_k) = _active_tiles(s)
    dq_bq, dq_bk = _blocks(s, dq_q, dq_k)
    dkv_bq, dkv_bk = _blocks(s, dkv_q, dkv_k)
    scale = 1.0 / (d ** 0.5)
    layout = _lse_layout(s, d)
    rope = rope_tables is not None
    # delta (rowwise dO . O) is computed inside the kernels from the do/o
    # tiles (see _delta) — no fp32 materialization at the XLA level.

    if _fused_bwd_fits(s, d):
        # Fused single-pass backward (see _bwd_fused_kernel): dq, dk, dv
        # from one walk of the causal tile triangle. Runs past the
        # forward's STREAM_THRESHOLD (see RESIDENT_BWD_SD_BUDGET) — there
        # the forward emitted the packed lse layout.
        q_spec = pl.BlockSpec((1, 1, dq_bq, d), lambda bi, hi, qi: (bi, hi, qi, 0))
        kv_full = pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // group, 0, 0))
        if layout == "packed":
            row_spec = pl.BlockSpec((1, 1, 1, dq_bq),
                                    lambda bi, hi, qi: (bi, hi, 0, qi))
        elif layout == "blocked":
            row_spec = pl.BlockSpec((1, 1, s // 128, 128),
                                    lambda bi, hi, qi: (bi, hi, 0, 0))
        else:
            row_spec = pl.BlockSpec((1, 1, dq_bq, 1),
                                    lambda bi, hi, qi: (bi, hi, qi, 0))
        in_specs = [q_spec, kv_full, kv_full, q_spec, row_spec, q_spec]
        operands = (qt, kt, vt, dot, lse, ot)
        scratch = [pltpu.VMEM((s, d), jnp.float32),
                   pltpu.VMEM((s, d), jnp.float32)]
        if rope:
            cq_spec = pl.BlockSpec((dq_bq, d), lambda bi, hi, qi: (qi, 0))
            ck_spec = pl.BlockSpec((s, d), lambda bi, hi, qi: (0, 0))
            in_specs += [cq_spec, cq_spec, ck_spec, ck_spec]
            operands += (*rope_tables, *rope_tables)
            scratch.append(pltpu.VMEM((s, d), kt.dtype))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, block_k=dq_bk, scale=scale,
                              causal=causal, group=group, lse_layout=layout,
                              rope=rope),
            grid=(b, h, s // dq_bq),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((1, 1, dq_bq, d),
                                    lambda bi, hi, qi: (bi, hi, qi, 0)),
                       kv_full, kv_full],
            out_shape=[jax.ShapeDtypeStruct(qt.shape, qt.dtype),
                       jax.ShapeDtypeStruct(kt.shape, kt.dtype),
                       jax.ShapeDtypeStruct(vt.shape, vt.dtype)],
            scratch_shapes=scratch,
            interpret=interpret,
        )(*operands)
    else:
        q_spec = pl.BlockSpec((1, 1, dq_bq, d),
                              lambda bi, hi, qi, ki: (bi, hi, qi, 0))
        if causal:  # same fetch-elision clamp as the fwd streaming kernel
            def dq_kv_idx(bi, hi, qi, ki):
                last = (qi * dq_bq + dq_bq - 1) // dq_bk
                return (bi, hi // group, jnp.minimum(ki, last), 0)
        else:
            def dq_kv_idx(bi, hi, qi, ki):
                return (bi, hi // group, ki, 0)
        kv_spec = pl.BlockSpec((1, 1, dq_bk, d), dq_kv_idx)
        if layout == "packed":
            row_spec = pl.BlockSpec((1, 1, 1, dq_bq),
                                    lambda bi, hi, qi, ki: (bi, hi, 0, qi))
        else:
            row_spec = pl.BlockSpec((1, 1, dq_bq, 1),
                                    lambda bi, hi, qi, ki: (bi, hi, qi, 0))
        in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, q_spec]
        operands = (qt, kt, vt, dot, lse, ot)
        scratch = [pltpu.VMEM((dq_bq, d), jnp.float32),
                   pltpu.VMEM((dq_bq, 1), jnp.float32),
                   pltpu.VMEM((dq_bq, 1), jnp.float32)]
        if rope:
            if causal:
                def dq_ck_idx(bi, hi, qi, ki):
                    last = (qi * dq_bq + dq_bq - 1) // dq_bk
                    return (jnp.minimum(ki, last), 0)
            else:
                def dq_ck_idx(bi, hi, qi, ki):
                    return (ki, 0)
            cq_spec = pl.BlockSpec((dq_bq, d),
                                   lambda bi, hi, qi, ki: (qi, 0))
            ck_spec = pl.BlockSpec((dq_bk, d), dq_ck_idx)
            in_specs += [cq_spec, cq_spec, ck_spec, ck_spec]
            operands += (*rope_tables, *rope_tables)
            scratch.append(pltpu.VMEM((dq_bq, d), qt.dtype))
        dq = pl.pallas_call(
            functools.partial(_dq_stream_kernel, block_q=dq_bq, block_k=dq_bk,
                              scale=scale, causal=causal, lse_layout=layout,
                              rope=rope),
            grid=(b, h, s // dq_bq, s // dq_bk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, dq_bq, d),
                                   lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            out_shape=jax.ShapeDtypeStruct(qt.shape, qt.dtype),
            scratch_shapes=scratch,
            interpret=interpret,
        )(*operands)

        # Grid over KV heads: block index maps pick up this head's group
        # of G query heads ((1, G, ...) blocks); dk/dv land at KV-head
        # granularity — no (B, H, S, D) expansion buffer. (Streaming
        # only: the resident family's fused kernel produced dk/dv above.)
        kv_spec = pl.BlockSpec((1, 1, dkv_bk, d),
                               lambda bi, hi, ki, qi: (bi, hi, ki, 0))
        if causal:  # steps before the diagonal are no-ops: pin their q fetch
            def dkv_q_idx(bi, hi, ki, qi):
                return (bi, hi, jnp.maximum(qi, ki * dkv_bk // dkv_bq), 0)

            def dkv_row_idx(bi, hi, ki, qi):
                return (bi, hi, 0, jnp.maximum(qi, ki * dkv_bk // dkv_bq))
        else:
            def dkv_q_idx(bi, hi, ki, qi):
                return (bi, hi, qi, 0)

            def dkv_row_idx(bi, hi, ki, qi):
                return (bi, hi, 0, qi)
        qgrp_spec = pl.BlockSpec((1, group, dkv_bq, d), dkv_q_idx)
        rowgrp_spec = (
            pl.BlockSpec((1, group, 1, dkv_bq), dkv_row_idx)
            if layout == "packed"
            else pl.BlockSpec((1, group, dkv_bq, 1), dkv_q_idx))
        in_specs = [qgrp_spec, kv_spec, kv_spec, qgrp_spec, rowgrp_spec,
                    qgrp_spec]
        operands = (qt, kt, vt, dot, lse, ot)
        scratch = [pltpu.VMEM((dkv_bk, d), jnp.float32),
                   pltpu.VMEM((dkv_bk, d), jnp.float32)]
        if rope:
            if causal:
                def dkv_cq_idx(bi, hi, ki, qi):
                    return (jnp.maximum(qi, ki * dkv_bk // dkv_bq), 0)
            else:
                def dkv_cq_idx(bi, hi, ki, qi):
                    return (qi, 0)
            cq_spec = pl.BlockSpec((dkv_bq, d), dkv_cq_idx)
            ck_spec = pl.BlockSpec((dkv_bk, d),
                                   lambda bi, hi, ki, qi: (ki, 0))
            in_specs += [cq_spec, cq_spec, ck_spec, ck_spec]
            operands += (*rope_tables, *rope_tables)
            scratch.append(pltpu.VMEM((dkv_bk, d), kt.dtype))
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_stream_kernel, block_q=dkv_bq,
                              block_k=dkv_bk, scale=scale, causal=causal,
                              lse_layout=layout, rope=rope),
            grid=(b, kv_heads, s // dkv_bk, s // dkv_bq),
            in_specs=in_specs,
            out_specs=[kv_spec, kv_spec],
            out_shape=[
                jax.ShapeDtypeStruct(kt.shape, kt.dtype),
                jax.ShapeDtypeStruct(vt.shape, vt.dtype),
            ],
            scratch_shapes=scratch,
            interpret=interpret,
        )(*operands)
    return dq, dk, dv


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal=True):
    """Causal flash attention; q (B,S,H,D), k/v (B,S,K,D) -> (B,S,H,D)."""
    out, _ = _flash_fwd(q, k, v, causal, _interpret())
    return out


def _flash_attention_fwd(q, k, v, causal):
    out, lse = _flash_fwd(q, k, v, causal, _interpret())
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(causal, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_bwd(q, k, v, o, lse, g, causal, _interpret())


flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention_bhsd(q, k, v, causal=True):
    """Head-major entry: q (B,H,S,D), k/v (B,K,S,D) -> (B,H,S,D).

    Identical kernels and math to :func:`flash_attention`, minus the
    (B,S,H,D) <-> (B,H,S,D) transposes at entry and exit — the caller
    (models/llama.py ``qkv_layout="bhsd"``) already holds operands in the
    kernel-native layout, so rope's elementwise fusion writes exactly
    the layout the custom call consumes and the backward's dq/dk/dv come
    out in the layout the rope backward wants. This is what eliminates
    the fp32 relayout-copy family at the custom-call boundary
    (BASELINE.md round-4)."""
    out, _ = _flash_fwd_t(q, k, v, causal, _interpret())
    return out


def _flash_attention_bhsd_fwd(q, k, v, causal):
    out, lse = _flash_fwd_t(q, k, v, causal, _interpret())
    return out, (q, k, v, out, lse)


def _flash_attention_bhsd_bwd(causal, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_bwd_t(q, k, v, o, lse, g, causal, _interpret())


flash_attention_bhsd.defvjp(_flash_attention_bhsd_fwd,
                            _flash_attention_bhsd_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def flash_attention_rope(q, k, v, cos2, sin2, causal=True):
    """Flash attention with RoPE applied INSIDE the kernels.

    q (B,H,S,D) and k/v (B,K,S,D) are RAW (pre-rope) head-major
    projections; ``cos2``/``sin2`` are (S, D) fp32 interleave-duplicated
    tables (``cos2[t, 2j] == cos2[t, 2j+1] == cos(t * theta^(-2j/D))`` —
    build with ``jnp.repeat(cos, 2, axis=-1)`` from the (S, D/2) tables of
    ops/rope.py). Rotation happens on VMEM tiles via the J-matrix matmul
    (see _rope_j) with the softmax prescale folded into the q-side pass,
    and the backward kernels emit dq/dk through the transpose rotation —
    so NO rotated q/k, fp32 rope intermediate, or rope backward ever
    exists at the XLA level. That eliminates the rope-adjacent relayout
    copies and convert fusions that an XLA-side rope pays at the Pallas
    custom-call boundary (~11 ms/step at the bench shape, BASELINE.md
    round-4 profile).

    Numerics: the rotation runs in fp32 with a single rounding to the
    input dtype. In fp32 (where astype is a no-op) scores, lse and the
    probability recomputation are bit-identical to the non-fused kernels
    fed pre-rotated inputs (tested in tests/test_flash_attention.py);
    under bf16 the q side agrees to one rounding — the fused path rounds
    once where the XLA rope + prescale chain rounds twice (ADVICE r4)."""
    out, _ = _flash_fwd_t(q, k, v, causal, _interpret(), (cos2, sin2))
    return out


def _flash_attention_rope_fwd(q, k, v, cos2, sin2, causal):
    out, lse = _flash_fwd_t(q, k, v, causal, _interpret(), (cos2, sin2))
    return out, (q, k, v, out, lse, cos2, sin2)


def _flash_attention_rope_bwd(causal, residuals, g):
    q, k, v, o, lse, cos2, sin2 = residuals
    dq, dk, dv = _flash_bwd_t(q, k, v, o, lse, g, causal, _interpret(),
                              (cos2, sin2))
    # The tables are position constants — zero cotangents (DCE'd).
    return dq, dk, dv, jnp.zeros_like(cos2), jnp.zeros_like(sin2)


flash_attention_rope.defvjp(_flash_attention_rope_fwd,
                            _flash_attention_rope_bwd)
