"""Pallas TPU flash attention (causal, GQA-aware).

TPU-native replacement for the reference's fused-kernel dependency
``F.scaled_dot_product_attention(is_causal=True)`` (ref: model.py:212), which
on CUDA comes from the NGC container. Here the kernel is first-party:
an online-softmax tiled forward that never materializes the (S, S) score
matrix — O(S) memory, q-tiles streamed through VMEM, scores computed on the
MXU in fp32.

The backward pass currently recomputes attention through the XLA einsum path
(same math, exact gradients, no saved probabilities); a Pallas backward kernel
is the planned upgrade.

GQA: the kernel maps query head ``h`` to KV head ``h // (H // K)`` in the
BlockSpec index map — KV are never repeated in memory (the reference's
``repeat_kv`` at model.py:129-138 materializes the expansion).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float,
                causal: bool):
    # q_ref/o_ref: (1, 1, block_q, D); k_ref/v_ref: (1, 1, S, D)
    q = q_ref[0, 0]
    block_q, d = q.shape
    s_k = k_ref.shape[2]
    qi = pl.program_id(2)
    q_start = qi * block_q

    if causal:
        # Only k-blocks whose start is <= the last query position matter.
        num_k_blocks = jnp.minimum(
            (q_start + block_q + block_k - 1) // block_k, s_k // block_k)
    else:
        num_k_blocks = s_k // block_k

    def body(j, carry):
        m_prev, l_prev, acc_prev = carry
        k_start = j * block_k
        k = k_ref[0, 0, pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        v = v_ref[0, 0, pl.ds(k_start, block_k), :]
        acc_new = acc_prev * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    init = (jnp.full((block_q,), NEG_INF, jnp.float32),
            jnp.zeros((block_q,), jnp.float32),
            jnp.zeros((block_q, d), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, init)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    # (B, S, H, D) -> (B, H, S, D) so heads become a grid axis.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    b, h, s, d = qt.shape
    kv_heads = kt.shape[1]
    group = h // kv_heads
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (
        f"seq len {s} must be divisible by block sizes ({block_q}, {block_k})")
    scale = 1.0 / (d ** 0.5)

    kernel = functools.partial(_fwd_kernel, block_k=block_k, scale=scale,
                               causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal=True):
    """Causal flash attention; q (B,S,H,D), k/v (B,S,K,D) -> (B,S,H,D)."""
    interpret = jax.default_backend() != "tpu"
    return _flash_fwd(q, k, v, causal, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                      interpret)


def _flash_attention_fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal), (q, k, v)


def _flash_attention_bwd(causal, residuals, g):
    from .attention import xla_attention
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: xla_attention(q_, k_, v_, causal=causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)
