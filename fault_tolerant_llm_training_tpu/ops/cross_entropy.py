"""Vocab-blocked cross-entropy: CE at large vocab without fp32 logits.

The reference computes sum-reduced fp32 CE over flattened (B*S, V) logits
(ref: train.py:101-102). At its 131k vocab the fp32 cast of the logits is
the single largest tensor in the step — (B, S, V) fp32 is ~2x the bf16
logits the model already produced, and the softmax residuals double it
again in the backward (VERDICT round-1 weak spot #5).

This module computes the same quantity vocab-block by vocab-block:

- **Forward** keeps three (B, S) fp32 running stats — rowwise max ``m``,
  shifted normalizer ``l``, and the picked (label) logit — and folds one
  (B, S, block) fp32 slice at a time via an online-logsumexp update (the
  same algebra as the flash-attention online softmax, over the vocab axis
  instead of keys). Peak extra memory is one block slice, not V.
- **Backward** is a custom VJP: softmax probabilities are recomputed per
  block from the saved (bf16 logits, fp32 logsumexp) — exactly the
  flash-attention recomputation scheme — and written straight into the
  dlogits buffer in the logits dtype. No fp32 (B, S, V) tensor and no
  stored softmax residuals.

Numerics match ``optax.softmax_cross_entropy_with_integer_labels`` to fp32
tolerance: both compute lse(logits_f32) - picked_f32 per token; the online
update is an exact reassociation of the same sum (tested in
tests/test_train_step.py).

The vocab tail (V % block) is handled as one separate static slice — no
padding copy, no masked lanes.
"""

import functools

import jax
import jax.numpy as jnp

# Vocab sizes at or above this use the blocked path automatically; below it
# the dense optax-style CE is faster (one fused reduction, no loop carries).
# 131072 (the reference's Mistral-Nemo vocab) is the motivating case.
AUTO_THRESHOLD = 65536
DEFAULT_BLOCK = 8192


def _block_update(sl, labels, v0, m, l, picked):
    """Fold one fp32 logits slice ``sl`` (B, S, Vb) starting at vocab index
    ``v0`` into the running (m, l, picked) stats."""
    vb = sl.shape[-1]
    bm = jnp.max(sl, axis=-1)
    m_new = jnp.maximum(m, bm)
    l = l * jnp.exp(m - m_new) + jnp.sum(
        jnp.exp(sl - m_new[..., None]), axis=-1)
    loc = labels - v0
    hit = (loc >= 0) & (loc < vb)
    pick = jnp.take_along_axis(
        sl, jnp.clip(loc, 0, vb - 1)[..., None], axis=-1)[..., 0]
    picked = jnp.where(hit, pick, picked)
    return m_new, l, picked


def _lse_and_picked(logits, labels, block):
    b, s, v = logits.shape
    m = jnp.full((b, s), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, s), jnp.float32)
    picked = jnp.zeros((b, s), jnp.float32)

    def body(j, carry):
        sl = jax.lax.dynamic_slice_in_dim(
            logits, j * block, block, axis=2).astype(jnp.float32)
        return _block_update(sl, labels, j * block, *carry)

    m, l, picked = jax.lax.fori_loop(0, v // block, body, (m, l, picked))
    if v % block:  # static tail slice — no padding copy
        tail = logits[:, :, (v // block) * block:].astype(jnp.float32)
        m, l, picked = _block_update(tail, labels, (v // block) * block,
                                     m, l, picked)
    return m + jnp.log(l), picked


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def chunked_softmax_xent(logits, labels, block: int = DEFAULT_BLOCK):
    """Per-token -log_softmax(logits)[label], fp32 (B, S).

    ``labels`` must already be in-range (callers mask ignore positions
    before/after, as cross_entropy_loss in training/step.py does)."""
    lse, picked = _lse_and_picked(logits, labels, block)
    return lse - picked


def _xent_fwd(logits, labels, block):
    lse, picked = _lse_and_picked(logits, labels, block)
    return lse - picked, (logits, labels, lse)


def _xent_bwd(block, res, g):
    logits, labels, lse = res
    b, s, v = logits.shape
    gf = g.astype(jnp.float32)

    def block_grad(sl, v0):
        # d nll / d logit_j = softmax_j - 1[label == j]
        p = jnp.exp(sl.astype(jnp.float32) - lse[..., None])
        loc = labels - v0
        hit = (loc >= 0) & (loc < sl.shape[-1])
        onehot = (jax.lax.broadcasted_iota(jnp.int32, sl.shape, 2)
                  == loc[..., None]) & hit[..., None]
        return (gf[..., None] * (p - onehot.astype(jnp.float32))
                ).astype(logits.dtype)

    def body(j, dlogits):
        sl = jax.lax.dynamic_slice_in_dim(logits, j * block, block, axis=2)
        return jax.lax.dynamic_update_slice_in_dim(
            dlogits, block_grad(sl, j * block), j * block, axis=2)

    dlogits = jax.lax.fori_loop(0, v // block, body,
                                jnp.zeros_like(logits))
    if v % block:
        v0 = (v // block) * block
        dlogits = jax.lax.dynamic_update_slice_in_dim(
            dlogits, block_grad(logits[:, :, v0:], v0), v0, axis=2)
    return dlogits, None


chunked_softmax_xent.defvjp(_xent_fwd, _xent_bwd)
