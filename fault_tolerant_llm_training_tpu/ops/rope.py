"""Rotary position embeddings, real-arithmetic interleaved form.

The reference computes RoPE with complex arithmetic: it views the head dim as
``head_dim/2`` complex numbers formed from *adjacent* element pairs
``(x[2j], x[2j+1])`` and multiplies by ``exp(i * t * theta^(-2j/d))`` in fp32
(ref: model.py:51-126, esp. ``view_as_complex`` of a ``(..., -1, 2)`` reshape
at model.py:121-122). Complex view tricks lower poorly on TPU, so we express
the identical rotation with real cos/sin pairs — the *interleaved* convention
(NOT the half-split "rotate_half" convention, which permutes differently):

    out[2j]   = x[2j] * cos(a) - x[2j+1] * sin(a)
    out[2j+1] = x[2j] * sin(a) + x[2j+1] * cos(a)

with ``a = t * theta^(-2j/d)``. Computed in fp32, cast back to the input
dtype, exactly like the reference (model.py:121-126 casts via ``.float()`` /
``.type_as``).
"""

from typing import Tuple

import jax.numpy as jnp


def rope_freqs(head_dim: int, theta: float) -> jnp.ndarray:
    """(D/2,) inverse frequencies theta^(-2j/d) (ref: model.py:67-69)."""
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))


def precompute_rope(head_dim: int, seq_len: int, theta: float = 10000.0
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape (seq_len, head_dim // 2), fp32.

    Equivalent to the modulus/argument of the reference's complex table
    (ref: model.py:67-71), precomputed once — the reference keeps it as a
    non-persistent buffer (model.py:342-344); here it is a constant folded
    into the jitted step.
    """
    t = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(t, rope_freqs(head_dim, theta))  # (S, D/2)
    return jnp.cos(angles), jnp.sin(angles)


def rope_cos_sin(head_dim: int, theta: float, positions: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, S, D/2) cos/sin computed directly from ``positions`` (B, S).

    An outer product instead of a table gather: under sequence parallelism
    the positions array is sharded along S, and XLA shards this elementwise
    compute with it — whereas a ``table[positions]`` gather forces an
    involuntary full rematerialization when the table's sharding does not
    match the activations' (observed in the SPMD partitioner on the
    dp/fsdp/sp/tp dryrun mesh).
    """
    angles = positions.astype(jnp.float32)[..., None] * rope_freqs(head_dim, theta)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray = None) -> jnp.ndarray:
    """Rotate ``x`` of shape (B, S, H, D) by the interleaved-pair convention.

    ``cos``/``sin`` are (S_table, D/2) — the first S rows are used (the
    reference slices its table to the runtime seqlen, model.py:91-97) — or
    per-token (B, S, D/2) from :func:`rope_cos_sin` (needed under sequence
    parallelism, where each shard holds a non-prefix slice of the sequence).
    ``positions`` (B, S) selects table rows explicitly via gather; prefer
    :func:`rope_cos_sin` inside sharded code (see its docstring).
    """
    orig_dtype = x.dtype
    b, s, h, d = x.shape
    xf = x.astype(jnp.float32).reshape(b, s, h, d // 2, 2)
    x_even, x_odd = xf[..., 0], xf[..., 1]
    if positions is not None:
        c = cos[positions][:, :, None, :]  # (B, S, 1, D/2)
        si = sin[positions][:, :, None, :]
    elif cos.ndim == 3:
        c = cos[:, :, None, :]  # (B, S, 1, D/2) per-token form
        si = sin[:, :, None, :]
    else:
        c = cos[:s][None, :, None, :]  # (1, S, 1, D/2)
        si = sin[:s][None, :, None, :]
    out_even = x_even * c - x_odd * si
    out_odd = x_even * si + x_odd * c
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(b, s, h, d)
    return out.astype(orig_dtype)


def apply_rope_bhsd(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
                    ) -> jnp.ndarray:
    """:func:`apply_rope` for head-major ``x`` of shape (B, H, S, D).

    Same math, same fp32 internal precision — only the broadcast axes
    move. Used by the ``qkv_layout="bhsd"`` attention path, where q/k are
    transposed to the flash kernel's native layout *before* rope so the
    rope fusion's output layout is exactly what the Pallas custom call
    consumes (no fp32 relayout copies at the boundary; BASELINE.md round-4
    copy-family breakdown). Prefix positions only — the sequence-parallel
    paths (which need per-token positions) keep the (B, S, H, D) form.
    """
    orig_dtype = x.dtype
    b, h, s, d = x.shape
    xf = x.astype(jnp.float32).reshape(b, h, s, d // 2, 2)
    x_even, x_odd = xf[..., 0], xf[..., 1]
    c = cos[:s][None, None, :, :]  # (1, 1, S, D/2)
    si = sin[:s][None, None, :, :]
    out_even = x_even * c - x_odd * si
    out_odd = x_even * si + x_odd * c
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(b, h, s, d)
    return out.astype(orig_dtype)
