"""TPU-native fault-tolerant LLM training framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
``danilodjor/fault-tolerant-llm-training`` (a Slurm-driven, signal-based
fault-tolerant PyTorch pretraining loop), re-designed TPU-first:

- model: Flax Llama-style decoder-only transformer (ref: model.py:9-380)
- data: streaming Parquet pipeline with checkpointable iterator state
  (ref: dataset.py:10-101)
- training: a single jitted ``train_step`` over a ``jax.sharding.Mesh``
  (ref: train.py:92-117 hot loop)
- fault tolerance: USR1/SIGTERM signal protocol, error classification,
  checkpoint + self-resubmit (ref: utils.py:65-97, train.sh:12)
- checkpointing: async sharded Orbax with atomic commit
  (ref: utils.py:74-81 single-file torch.save)
- parallelism: DP / FSDP / TP via NamedSharding + sequence parallelism via
  ring attention (reference has none; required for TPU-pod scale)

The distribution name is ``fault-tolerant-llm-training_tpu``; this package is
its importable form.
"""

__version__ = "0.1.0"
