"""Host -> device double-buffered prefetch.

The reference has *no* prefetch: a synchronous ``.to(device)`` per step with
``num_workers=0`` tokenization on the critical path (ref: train.py:93-96,
dataset.py:27-35; SURVEY.md §5.8 flags this as the gap). Here a background
thread tokenizes/collates ahead while ``jax.device_put`` (async under the
hood) stages batches into HBM with the batch's NamedSharding, so the TPU never
waits on the host in steady state.

Checkpoint correctness under prefetch: the loader's position runs ``depth``
batches ahead of what the trainer has consumed, so each queued batch carries
the loader-state snapshot taken *right after* it was produced. The trainer
checkpoints the snapshot of the last batch it actually consumed — restoring
that state resumes at exactly the first unconsumed batch, prefetch depth
notwithstanding.
"""

import queue
import threading
from typing import Optional, Tuple

import jax
import numpy as np


class DevicePrefetcher:
    """Wraps a DataLoader; yields ``(inputs_dev, labels_dev, data_state)``.

    Single-process: the worker thread both tokenizes and stages to the
    device, so steady state never waits on the host. Multi-process: staging
    moves to the consumer thread — issuing JAX operations from a background
    thread concurrently with the main thread's dispatches is not safe when
    a cross-process runtime (gloo on CPU pods) is underneath (observed as
    collective payload-size mismatches); tokenization, the expensive part,
    still runs ahead in the worker.
    """

    def __init__(self, loader, sharding=None, depth: int = 2,
                 stage_in_worker: Optional[bool] = None,
                 chaos_on_batch=None, start_batch: int = 0):
        self.loader = loader
        self.sharding = sharding
        self.depth = max(1, depth)
        # Chaos hook (chaos/injector.py on_batch): called in the worker
        # with the global step the produced batch will feed, BEFORE it is
        # queued — a loader_stall delays exactly that batch's delivery.
        # start_batch is the resume step so schedule steps stay global.
        self._chaos_on_batch = chaos_on_batch
        self._batch_index = start_batch
        if stage_in_worker is None:
            stage_in_worker = jax.process_count() == 1
        self.stage_in_worker = stage_in_worker
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._started = False

    def _stage(self, arr: np.ndarray):
        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        return jax.device_put(np.asarray(arr))

    def _stage_pair(self, inputs: np.ndarray, labels: np.ndarray):
        """Host-sharded loaders carry only this host's rows and assemble
        the global array themselves (loader.stage_global); replicated
        loaders device_put the full batch against the global sharding."""
        if hasattr(self.loader, "stage_global"):
            return self.loader.stage_global(inputs, labels)
        return self._stage(inputs), self._stage(labels)

    def _worker(self):
        try:
            while not self._stop.is_set():
                try:
                    inputs, labels = next(self.loader)
                except StopIteration:
                    break
                state = self.loader.get_state()
                if self.stage_in_worker:
                    inputs, labels = self._stage_pair(inputs, labels)
                if self._chaos_on_batch is not None:
                    self._chaos_on_batch(self._batch_index)
                self._batch_index += 1
                self._q.put((inputs, labels, state))
        except BaseException as e:  # surfaced to the consumer
            self._exc = e
        finally:
            self._q.put(None)

    def __iter__(self):
        if not self._started:
            self._started = True
            self.loader.resume()
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()
        return self

    def __next__(self) -> Tuple[jax.Array, jax.Array, dict]:
        if not self._started:
            iter(self)
        item = self._q.get()
        if item is None:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        if not self.stage_in_worker:
            inputs, labels, state = item
            inputs, labels = self._stage_pair(inputs, labels)
            return inputs, labels, state
        return item

    def stop(self):
        """Stop the background thread and drain the queue (used on fault
        exits so the checkpoint write is not racing tokenization)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
