"""Tokenizer loading with an offline-safe fallback.

The reference hard-depends on a Hugging Face hub tokenizer
(``unsloth/Mistral-Nemo-Base-2407-bnb-4bit``, ref: utils.py:133-137,
train.py:28) — which requires network or a warm cache. TPU pods frequently run
with no egress, so this framework adds a first-party ``ByteTokenizer``
(UTF-8 bytes + BOS/EOS/PAD specials) selectable as
``--tokenizer-name-or-path byte`` and used as an automatic fallback when the
HF tokenizer cannot be loaded offline.

Only the tokenizer surface the reference actually uses is required:
``encode_plus(text, max_length=, padding=, truncation=, padding_side=)``
returning ``{"input_ids": [...]}`` (ref: dataset.py:29-35,84-89), plus
``vocab_size`` / ``pad_token_id`` / ``bos_token_id`` / ``decode``
(ref: train.py:30,51; dataset.py:58,122).
"""

import logging
from typing import Dict

import numpy as np

from .native import byte_tokenize

logger = logging.getLogger()


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..2 = PAD/BOS/EOS, 3..258 = bytes."""

    pad_token_id = 0
    bos_token_id = 1
    eos_token_id = 2
    _OFFSET = 3

    @property
    def vocab_size(self) -> int:
        return 256 + self._OFFSET

    def encode(self, text: str, add_bos: bool = True) -> np.ndarray:
        return byte_tokenize(text, self.bos_token_id if add_bos else -1,
                             self._OFFSET)

    def encode_plus(self, text: str, max_length: int = None, padding=False,
                    truncation: bool = False, padding_side: str = "right"
                    ) -> Dict[str, np.ndarray]:
        ids = self.encode(text)
        if truncation and max_length is not None:
            ids = ids[:max_length]
        if (padding == "max_length" and max_length is not None
                and len(ids) < max_length):
            pad = np.full((max_length - len(ids),), self.pad_token_id,
                          np.int32)
            ids = (np.concatenate([ids, pad]) if padding_side == "right"
                   else np.concatenate([pad, ids]))
        return {"input_ids": ids}

    def decode(self, ids) -> str:
        data = bytes(int(i) - self._OFFSET for i in ids
                     if int(i) >= self._OFFSET)
        return data.decode("utf-8", errors="replace")


def load_tokenizer(name_or_path: str):
    """HF tokenizer by name/path, or ByteTokenizer for 'byte' / offline."""
    if name_or_path in ("byte", "byte://", ""):
        return ByteTokenizer()
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(name_or_path)
    except Exception as e:  # offline, missing cache, bad name, ...
        logger.warning(
            "Could not load HF tokenizer %r (%s); falling back to the "
            "built-in byte tokenizer", name_or_path, type(e).__name__)
        return ByteTokenizer()
