"""Pretokenized token cache for the map-style data path.

The reference tokenizes every document on the fly, per epoch, on the
training-loop thread (ref: train.py:93 -> dataset.py:29-35); SURVEY.md §7.3
hard part 5 flags host tokenization as the bottleneck at TPU pod speeds.
This cache tokenizes the corpus ONCE into a memory-mapped ``(rows,
seq_len+1)`` int32 array (the exact per-item output of
``ParquetDataset.__getitem__``), so steady-state data loading becomes a
memmap row read — no tokenizer on the hot path, and identical batches to
the uncached path bit-for-bit (tests/test_data.py).

Cache identity: a digest of the resolved shard list (path, size,
nanosecond mtime), the sequence length, and a *behavioral* fingerprint of
the loaded tokenizer instance — its class, vocab/special ids, and the ids
it produces for a fixed probe text (so a retrained tokenizer with the
same class and vocab size still changes the key). The requested tokenizer
name is deliberately NOT part of the key: ``load_tokenizer`` silently
falls back to the byte tokenizer offline, and a name-keyed cache would be
poisoned for a later online run — while two aliases of the same tokenizer
share one cache. Any change produces a new cache file, so stale caches
are never read. Writes are atomic (build to ``.tmp``, then
``os.replace``) and crash-safe (the tmp is unlinked on failure, touched
during long builds, and day-old untouched orphans are swept). On
multi-host pods only process 0 builds; the others poll for the finished
cache instead of tokenizing the corpus N times.
"""

import hashlib
import json
import logging
import os
import time
from typing import Optional

import numpy as np

CACHE_VERSION = 1
_STALE_TMP_AGE_S = 86400
_BUILD_WAIT_TIMEOUT_S = 3600

logger = logging.getLogger()


_PROBE_TEXT = "The 3 qUick brown foxes? é中文 #2024"


def _tokenizer_fingerprint(tokenizer) -> str:
    """Class + ids + the token ids of a fixed probe text: a retrained
    tokenizer with identical class/vocab-size still changes the key."""
    probe = tokenizer.encode_plus(_PROBE_TEXT, padding=False,
                                  truncation=False)["input_ids"]
    return (f"{type(tokenizer).__name__}"
            f":v{getattr(tokenizer, 'vocab_size', '?')}"
            f":p{getattr(tokenizer, 'pad_token_id', '?')}"
            f":b{getattr(tokenizer, 'bos_token_id', '?')}"
            f":{','.join(str(int(t)) for t in probe)}")


class TokenCache:
    """``tokens[idx]`` -> the padded/truncated input_ids row for ``idx``."""

    def __init__(self, cache_dir: str, source, tokenizer,
                 sequence_length: int):
        os.makedirs(cache_dir, exist_ok=True)
        self._source = source
        self._tokenizer = tokenizer
        self._width = sequence_length + 1
        self._sweep_stale_tmps(cache_dir)
        meta = {
            "version": CACHE_VERSION,
            "tokenizer": _tokenizer_fingerprint(tokenizer),
            "sequence_length": sequence_length,
            "shards": [
                {"path": os.path.abspath(f),
                 "size": os.path.getsize(f),
                 "mtime_ns": os.stat(f).st_mtime_ns}
                for f in source.files
            ],
        }
        blob = json.dumps(meta, sort_keys=True).encode()
        digest = hashlib.sha1(blob).hexdigest()[:16]
        self.path = os.path.join(cache_dir, f"tokens_{digest}.npy")
        self._meta_path = os.path.join(cache_dir, f"tokens_{digest}.json")
        if not self._ready():
            if self._is_builder():
                self._build(blob)
            else:
                self._wait_for_builder()
        self.tokens = np.load(self.path, mmap_mode="r")
        assert self.tokens.shape == (len(source), self._width), (
            self.tokens.shape, (len(source), self._width))

    def _ready(self) -> bool:
        return os.path.exists(self.path) and os.path.exists(self._meta_path)

    @staticmethod
    def _is_builder() -> bool:
        """Exactly one builder per pod (process 0); single-process -> True."""
        try:
            import jax
            return jax.process_index() == 0
        except Exception:
            return True

    def _wait_for_builder(self) -> None:
        # Polling assumes pretokenize_dir is on a filesystem shared by all
        # hosts (documented at --pretokenize-dir): with a host-local path
        # the cache can never appear here, only time out below.
        logger.info(f"Waiting for process 0 to build {self.path} "
                    f"(pretokenize dir must be on a shared filesystem) ...")
        deadline = time.time() + _BUILD_WAIT_TIMEOUT_S
        while not self._ready():
            if time.time() > deadline:
                raise TimeoutError(
                    f"token cache {self.path} was not built within "
                    f"{_BUILD_WAIT_TIMEOUT_S}s; did process 0 die — or is "
                    f"--pretokenize-dir not on a shared filesystem?")
            time.sleep(1.0)

    @staticmethod
    def _sweep_stale_tmps(cache_dir: str) -> None:
        """Remove day-old ``*.tmp.<pid>`` orphans from killed builders
        (live builders' tmps are younger and are left alone)."""
        now = time.time()
        for name in os.listdir(cache_dir):
            if ".tmp." not in name:
                continue
            p = os.path.join(cache_dir, name)
            try:
                if now - os.path.getmtime(p) > _STALE_TMP_AGE_S:
                    os.unlink(p)
            except OSError:
                pass

    def _build(self, meta_blob: bytes) -> None:
        n = len(self._source)
        logger.info(f"Pretokenizing {n} documents into {self.path} ...")
        tmp = self.path + f".tmp.{os.getpid()}"
        try:
            arr = np.lib.format.open_memmap(tmp, mode="w+", dtype=np.int32,
                                            shape=(n, self._width))
            for i in range(n):
                arr[i] = np.asarray(self._tokenizer.encode_plus(
                    self._source.text(i),
                    max_length=self._width,
                    padding="max_length",
                    truncation=True,
                    padding_side="right",
                )["input_ids"], dtype=np.int32)
                if i % 10000 == 0:
                    # mmap writes don't bump mtime; keep the stale-tmp
                    # sweeper's hands off multi-day builds
                    os.utime(tmp)
            arr.flush()
            del arr
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        meta_tmp = self._meta_path + f".tmp.{os.getpid()}"
        with open(meta_tmp, "wb") as f:
            f.write(meta_blob)
        os.replace(meta_tmp, self._meta_path)
        logger.info("Pretokenization complete")


def maybe_token_cache(pretokenize_dir: str, source, tokenizer,
                      sequence_length: int) -> Optional[TokenCache]:
    if not pretokenize_dir:
        return None
    return TokenCache(pretokenize_dir, source, tokenizer, sequence_length)
