"""Parquet streaming datasets with checkpointable iterator state.

Two paths, mirroring the reference (ref: dataset.py:10-101):

- ``ParquetDataset``       — map-style, one document per sample, padded /
                             truncated to seq_len+1 (ref: dataset.py:10-35).
                             This is the path the reference trainer uses.
- ``IterableParquetDataset`` — token-buffer document packing
                             (ref: dataset.py:56-101).

Key upgrade over the reference (SURVEY.md §5.4 build note): both datasets
expose ``get_state() / set_state()`` so the *data position is saved in the
checkpoint* — resume is O(1) instead of the reference's O(steps) batch replay
(ref: train.py:36-39, measured at ~9 s per 427 batches in BASELINE.md).

The reference's packing has two quirks (SURVEY.md §2.1 #8): the token buffer
is cleared at the top of every ``__next__`` (dataset.py:78), dropping overflow
tokens, and ``current_index -= 1`` (dataset.py:93) re-reads the last document
from its beginning for the next sample. ``legacy=True`` (default) reproduces
both for behavioral parity; ``legacy=False`` keeps the leftover buffer and
advances monotonically.
"""

import bisect
import glob
import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow.parquet as pq

from .native import pack_clm


_MIX = 0x9E3779B97F4A7C15
_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """SplitMix64 finalizer — a fixed, dependency-free integer hash, so
    the permutation stream can never drift with a library release (the
    NEP-19 hazard the exact path's fingerprint exists to detect)."""
    x = (x + _MIX) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _feistel_keys(seed: int, epoch: int):
    """The 4 per-epoch Feistel round keys — pure in (seed, epoch); callers
    on the per-sample path cache them per epoch (review r5: rederiving
    them per lookup doubled the hash work the O(1) path exists to save)."""
    return [_splitmix64((seed << 32) ^ (epoch << 8) ^ r) for r in range(4)]


def _feistel_row(idx: int, n: int, seed: int, epoch: int, keys=None) -> int:
    """Position -> row under a keyed bijection of [0, n): O(1) memory.

    A 4-round balanced Feistel network over the smallest even-bit power-of
    -two domain >= n, cycle-walked back into [0, n) (each walk step visits
    another in-domain point of the same bijection, so the result stays a
    permutation). The exact-permutation path materializes O(n) indices per
    epoch per host (VERDICT r4 weak #2 scale nit) — fine at 15k rows,
    wrong shape for a pod-scale corpus; this computes each mapping on
    demand at ~4 integer hashes per sample."""
    bits = max((n - 1).bit_length(), 2)
    bits += bits & 1  # balanced halves
    half = bits // 2
    mask = (1 << half) - 1
    if keys is None:
        keys = _feistel_keys(seed, epoch)
    x = idx
    while True:
        left, right = x >> half, x & mask
        for k in keys:
            left, right = right, left ^ (_splitmix64(right ^ k) & mask)
        x = (left << half) | right
        if x < n:
            return x


def _epoch_perm(n: int, seed: int, epoch: int) -> np.ndarray:
    """Deterministic per-epoch permutation of the global row index.

    A pure function of (seed, epoch) — iterator state stays the single
    integer position, so O(1) bit-exact resume is preserved: after
    ``set_state`` the permutation is regenerated from the epoch the
    position implies. The reference trains strictly in document order
    (ref: dataset.py:27-35); seeded shuffling is a beyond-parity fix for
    the document-order artifacts that order produces in multi-epoch runs
    (VERDICT r3 weak #3: train loss swinging 0.52 -> 7.18 as the corpus
    re-walks in order)."""
    return np.random.default_rng(
        np.random.SeedSequence([seed, epoch])).permutation(n)


class _ShuffleMixin:
    """Shared row mapping: global position -> (epoch, permuted row).

    ``holdout_rows``: the first N corpus rows are reserved for held-out
    evaluation and excluded from this mapping entirely (VERDICT r4 weak
    #6: without it, default eval ran on rows the trainer also trains on).
    Training walks/permutes rows [holdout, real_length); the eval dataset
    (holdout_rows=0) reads exactly rows [0, holdout) from position 0.
    """

    _shuffle_seed: Optional[int] = None

    def _init_shuffle(self, shuffle_seed: Optional[int],
                      holdout_rows: int = 0,
                      shuffle_impl: str = "exact") -> None:
        if shuffle_impl not in ("exact", "feistel"):
            raise ValueError(f"shuffle_impl {shuffle_impl!r} must be "
                             f"'exact' or 'feistel'")
        self._shuffle_seed = shuffle_seed
        self._shuffle_impl = shuffle_impl
        self._holdout_rows = int(holdout_rows)
        if self._holdout_rows >= self._source.real_length:
            raise ValueError(
                f"eval holdout of {self._holdout_rows} rows consumes the "
                f"whole {self._source.real_length}-row corpus — lower "
                f"--eval-batches/--batch-size or pass --eval-dataset")
        self._perm_epoch = -1
        self._perm = None
        self._fingerprint = self._compute_fingerprint()

    def _data_rows(self) -> int:
        """Rows available to THIS dataset (corpus minus the eval carve)."""
        return self._source.real_length - self._holdout_rows

    def _compute_fingerprint(self) -> Optional[List[int]]:
        if self._shuffle_seed is None:
            return None
        n = self._data_rows()
        if self._shuffle_impl == "feistel":
            # pure-integer stream: stable by construction, but the
            # fingerprint still guards corpus-size and impl drift
            return [_feistel_row(i, n, self._shuffle_seed, 0)
                    for i in range(min(8, n))]
        return [int(x) for x in
                _epoch_perm(n, self._shuffle_seed, 0)[:min(8, n)]]

    def _shuffle_fingerprint(self) -> Optional[List[int]]:
        """First-k indices of the epoch-0 permutation — a cheap witness of
        the Generator STREAM itself. NumPy's NEP-19 policy permits stream
        changes across releases, so a resume under a different NumPy could
        silently reorder data while seed equality still holds (ADVICE r4);
        the fingerprint catches exactly that. Computed once at init: the
        exact path's witness costs a full O(n) permutation, which must not
        ride every checkpoint save (the fault path races the USR1 lead)."""
        return self._fingerprint

    def _row(self, idx: int) -> int:
        n = self._data_rows()
        if self._shuffle_seed is None:
            return self._holdout_rows + idx % n
        epoch, pos = divmod(idx, n)
        if self._shuffle_impl == "feistel":
            if self._perm_epoch != epoch:  # reuse the exact path's marker
                self._feistel_epoch_keys = _feistel_keys(self._shuffle_seed,
                                                         epoch)
                self._perm_epoch = epoch
            return self._holdout_rows + _feistel_row(
                pos, n, self._shuffle_seed, epoch,
                keys=self._feistel_epoch_keys)
        if self._perm_epoch != epoch:
            self._perm = _epoch_perm(n, self._shuffle_seed, epoch)
            self._perm_epoch = epoch
        return self._holdout_rows + int(self._perm[pos])

    def _check_shuffle_state(self, state: Dict) -> None:
        saved = state.get("shuffle_seed", None)
        if saved != self._shuffle_seed:
            raise ValueError(
                f"checkpoint data state was saved with shuffle_seed={saved!r} "
                f"but this run uses {self._shuffle_seed!r}; resuming would "
                f"silently change the data order — pass the same --shuffle/"
                f"--seed the checkpoint was written with")
        saved_impl = state.get("shuffle_impl", "exact")
        if self._shuffle_seed is not None and saved_impl != self._shuffle_impl:
            raise ValueError(
                f"checkpoint data state was saved with shuffle_impl="
                f"{saved_impl!r} but this run uses "
                f"{self._shuffle_impl!r}; the two permutations differ — "
                f"resume with the same --shuffle-impl")
        saved_holdout = int(state.get("holdout_rows", 0) or 0)
        if saved_holdout != self._holdout_rows:
            raise ValueError(
                f"checkpoint data state was saved with an eval holdout of "
                f"{saved_holdout} rows but this run carves "
                f"{self._holdout_rows}; the training-row mapping would "
                f"silently shift — resume with the same --eval-frequency/"
                f"--eval-batches/--batch-size (or --eval-dataset) the "
                f"checkpoint was written with")
        want = state.get("shuffle_fingerprint", None)
        if want is not None and want != self._shuffle_fingerprint():
            import numpy as _np

            raise ValueError(
                f"checkpoint shuffle fingerprint {want} does not match this "
                f"environment's {self._shuffle_fingerprint()} despite equal "
                f"seeds: the NumPy Generator stream differs (NEP-19 allows "
                f"stream changes across releases; this host runs numpy "
                f"{_np.__version__}) or the corpus row count changed — "
                f"resuming would silently reorder the data; resume under "
                f"the environment the checkpoint was written in")


class _ParquetText:
    """Memory-mapped 'text' column access (ref: dataset.py:18,28), extended
    to sharded datasets: ``path`` may be one file, a directory of
    ``*.parquet`` shards, or a glob pattern. Shards are ordered
    lexicographically and indexed as one logical table, so the datasets'
    checkpointable positions (a single global index) are shard-layout
    agnostic — the reference reads exactly one file (dataset.py:18)."""

    def __init__(self, path: str):
        files = self._resolve(path)
        self.files = files  # resolved shard list (cache identity, cache.py)
        self._columns = []
        self._offsets: List[int] = []  # start row of each shard
        total = 0
        for f in files:
            table = pq.read_table(f, memory_map=True)
            self._offsets.append(total)
            self._columns.append(table["text"])
            total += len(table)
        self.real_length = total
        if total == 0:
            raise ValueError(f"parquet source {path!r} has no rows")

    @staticmethod
    def _resolve(path: str) -> List[str]:
        if os.path.isdir(path):
            files = sorted(glob.glob(os.path.join(path, "*.parquet")))
        elif os.path.exists(path):
            files = [path]  # an existing literal file wins, even if globby
        elif any(c in path for c in "*?["):
            files = sorted(glob.glob(path))
        else:
            files = [path]
        if not files:
            raise FileNotFoundError(f"no parquet shards match {path!r}")
        return files

    def __len__(self) -> int:
        return self.real_length

    def text(self, idx: int) -> str:
        idx %= self.real_length
        shard = bisect.bisect_right(self._offsets, idx) - 1
        return str(self._columns[shard][idx - self._offsets[shard]])


class ParquetDataset(_ShuffleMixin):
    """Map-style: doc -> tokenize -> pad/truncate to seq_len+1
    (ref: dataset.py:10-35). ``__len__`` is the *requested* sample count with
    wraparound indexing (ref: dataset.py:24-28).

    ``shuffle_seed``: None = the reference's strict document order;
    an int = a deterministic per-epoch permutation (see _epoch_perm) whose
    position rides the same checkpointable ``next_index``."""

    def __init__(self, parquet_file: str, tokenizer, sequence_length: int,
                 training_samples: int, pretokenize_dir: str = "",
                 shuffle_seed: Optional[int] = None,
                 holdout_rows: int = 0, shuffle_impl: str = "exact"):
        self._source = _ParquetText(parquet_file)
        self.tokenizer = tokenizer
        self.sequence_length = sequence_length
        self.training_samples = training_samples
        self._next_index = 0
        self._init_shuffle(shuffle_seed, holdout_rows, shuffle_impl)
        from .cache import maybe_token_cache
        self._cache = maybe_token_cache(pretokenize_dir, self._source,
                                        tokenizer, sequence_length)

    def __len__(self) -> int:
        return self.training_samples

    def __getitem__(self, idx: int) -> Dict:
        row = self._row(idx)
        if self._cache is not None:
            # memmap row read; identical to the tokenize path bit-for-bit
            return {"input_ids": self._cache.tokens[row]}
        return self.tokenizer.encode_plus(
            self._source.text(row),
            max_length=self.sequence_length + 1,
            padding="max_length",
            truncation=True,
            padding_side="right",
        )

    # --- sequential iteration with explicit, checkpointable position ---
    def __iter__(self):
        return self

    def __next__(self) -> Dict:
        if self._next_index >= self.training_samples:
            raise StopIteration
        item = self[self._next_index]
        self._next_index += 1
        return item

    def get_state(self) -> Dict:
        return {"kind": "map", "next_index": self._next_index,
                "shuffle_seed": self._shuffle_seed,
                "shuffle_fingerprint": self._shuffle_fingerprint(),
                "shuffle_impl": self._shuffle_impl,
                "holdout_rows": self._holdout_rows}

    def set_state(self, state: Dict) -> None:
        if state.get("kind") != "map":
            raise ValueError(
                f"checkpoint data state is kind {state.get('kind')!r} but "
                f"--data-loading map expects 'map'; resume with the data "
                f"loading mode the checkpoint was saved with")
        self._check_shuffle_state(state)
        self._next_index = int(state["next_index"])


class IterableParquetDataset(_ShuffleMixin):
    """Token-buffer packing (ref: dataset.py:56-101), checkpointable.

    Yields ``(inputs, labels)`` int32 arrays of length seq_len; labels mask
    BOS positions with -100 where either the input or the label is BOS
    (ref: dataset.py:99-100).

    ``shuffle_seed``: None = document order; an int = per-epoch permuted
    document order (``current_index`` walks the permutation, so the
    legacy re-read quirk and checkpoint state work unchanged).
    """

    def __init__(self, parquet_file: str, tokenizer, sequence_length: int,
                 bos_token_id: int = 1, legacy: bool = True,
                 shuffle_seed: Optional[int] = None,
                 holdout_rows: int = 0, shuffle_impl: str = "exact"):
        self._source = _ParquetText(parquet_file)
        self.tokenizer = tokenizer
        self.sequence_length = sequence_length
        self.bos_token_id = bos_token_id
        self.legacy = legacy
        self.current_index = 0
        self.token_buffer = []
        self._init_shuffle(shuffle_seed, holdout_rows, shuffle_impl)

    def __iter__(self):
        # Reset position on fresh iteration (ref: dataset.py:68-72).
        self.token_buffer = []
        self.current_index = 0
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        need = self.sequence_length + 1
        if self.legacy:
            # ref quirk: buffer cleared every sample (dataset.py:78)
            self.token_buffer = []
        while len(self.token_buffer) < need:
            # Legacy truncates each document to seq_len+1 (ref:
            # dataset.py:86-88) — combined with the buffer clear this drops
            # the tail of every long document. Fixed mode packs whole docs.
            tokens = self.tokenizer.encode_plus(
                self._source.text(self._row(self.current_index)),
                padding=False,
                truncation=self.legacy,
                max_length=need if self.legacy else None,
            )
            self.token_buffer.extend(tokens["input_ids"])
            self.current_index += 1
        if self.legacy:
            # ref quirk: last doc re-read from its start next time
            # (dataset.py:93)
            self.current_index -= 1
            chunk = self.token_buffer[:need]
        else:
            chunk, self.token_buffer = (self.token_buffer[:need],
                                        self.token_buffer[need:])
        arr = np.asarray(chunk, dtype=np.int32)
        return pack_clm(arr, self.bos_token_id)

    def get_state(self) -> Dict:
        return {
            "kind": "packed",
            "current_index": self.current_index,
            "token_buffer": [int(t) for t in self.token_buffer],
            "legacy": self.legacy,
            "shuffle_seed": self._shuffle_seed,
            "shuffle_fingerprint": self._shuffle_fingerprint(),
            "shuffle_impl": self._shuffle_impl,
            "holdout_rows": self._holdout_rows,
        }

    def set_state(self, state: Dict) -> None:
        if state.get("kind") != "packed":
            raise ValueError(
                f"checkpoint data state is kind {state.get('kind')!r} but "
                f"--data-loading packed expects 'packed'; resume with the "
                f"data loading mode the checkpoint was saved with (converted "
                f"reference checkpoints are always 'map')")
        self._check_shuffle_state(state)
        self.current_index = int(state["current_index"])
        self.token_buffer = list(state["token_buffer"])
        self.legacy = bool(state["legacy"])
