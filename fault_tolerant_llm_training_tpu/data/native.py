"""ctypes bindings for the native host-loader (native/hostloader.cpp).

The shared library is compiled lazily on first use with the system g++ (no
build step, no pybind11 dependency) and cached, keyed by a hash of the .cpp
source *and* the host CPU (the build uses ``-march=native``, so a cache dir
on shared storage must not serve another machine's code). Every binding has
a numpy fallback with identical semantics — ``have_native()`` reports which
path is active, and ``FTL_DISABLE_NATIVE=1`` forces the fallback as an
escape hatch (the parity tests instead monkeypatch ``_LIB`` so both branches
run in one process).
"""

import ctypes
import hashlib
import logging
import os
import platform
import subprocess
import tempfile
import threading
import uuid

import numpy as np

logger = logging.getLogger()

_SRC = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                    "native", "hostloader.cpp")
_LIB = None
_TRIED = False
_LOCK = threading.Lock()


def _host_key() -> str:
    """Discriminates -march=native artifacts between host CPU types."""
    parts = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            found = set()
            for line in f:
                key = line.split(":", 1)[0].strip()
                if key in ("model name", "flags") and key not in found:
                    found.add(key)
                    parts.append(line.strip())
                if len(found) == 2:
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:8]


def _build_and_load():
    src = os.path.abspath(_SRC)
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.environ.get(
        "FTL_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "ftl_native"))
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir,
                           f"hostloader_{digest}_{_host_key()}.so")
    if not os.path.exists(so_path):
        # unique per builder (pid AND thread/uuid): concurrent builders each
        # write their own temp file, and the os.replace install is atomic.
        tmp = so_path + f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-o", tmp, src],
            check=True, capture_output=True)
        os.replace(tmp, so_path)
    lib = ctypes.CDLL(so_path)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ftl_collate_clm.argtypes = [i32p, ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_int32, i32p, i32p]
    lib.ftl_collate_clm.restype = None
    lib.ftl_pack_clm.argtypes = [i32p, ctypes.c_int64, ctypes.c_int32,
                                 i32p, i32p]
    lib.ftl_pack_clm.restype = None
    lib.ftl_byte_tokenize.argtypes = [u8p, ctypes.c_int64, ctypes.c_int32,
                                      ctypes.c_int32, i32p]
    lib.ftl_byte_tokenize.restype = ctypes.c_int64
    return lib


def _lib():
    """Build/load on first call; None when disabled or the build failed.
    Thread-safe: the prefetch thread and main thread may race here."""
    global _LIB, _TRIED
    if not _TRIED:
        with _LOCK:
            if not _TRIED:
                if os.environ.get("FTL_DISABLE_NATIVE") != "1":
                    try:
                        _LIB = _build_and_load()
                    except Exception as e:  # no g++, read-only fs, ...
                        logger.warning(
                            "native hostloader unavailable (%s: %s); "
                            "using numpy fallback", type(e).__name__, e)
                _TRIED = True
    return _LIB


def have_native() -> bool:
    return _lib() is not None


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def collate_clm(batch: np.ndarray, pad_id: int):
    """(B, S+1) int32 ids -> (inputs, labels), labels pad-masked to -100
    (ref: dataset.py:44-53)."""
    batch = np.ascontiguousarray(batch, dtype=np.int32)
    b, seq_plus1 = batch.shape
    s = seq_plus1 - 1
    inputs = np.empty((b, s), np.int32)
    labels = np.empty((b, s), np.int32)
    lib = _lib()
    if lib is not None:
        lib.ftl_collate_clm(_i32(batch), b, seq_plus1, pad_id,
                            _i32(inputs), _i32(labels))
    else:
        inputs[:] = batch[:, :-1]
        labels[:] = batch[:, 1:]
        labels[labels == pad_id] = -100
    return inputs, labels


def pack_clm(chunk: np.ndarray, bos_id: int):
    """(S+1,) packed int32 ids -> (inputs, labels), BOS positions masked
    to -100 on both sides (ref: dataset.py:96-100)."""
    chunk = np.ascontiguousarray(chunk, dtype=np.int32)
    s = chunk.shape[0] - 1
    inputs = np.empty((s,), np.int32)
    labels = np.empty((s,), np.int32)
    lib = _lib()
    if lib is not None:
        lib.ftl_pack_clm(_i32(chunk), s + 1, bos_id, _i32(inputs),
                         _i32(labels))
    else:
        inputs[:] = chunk[:-1]
        labels[:] = chunk[1:]
        labels[inputs == bos_id] = -100
        labels[labels == bos_id] = -100
    return inputs, labels


def byte_tokenize(text: str, bos_id: int, offset: int) -> np.ndarray:
    """UTF-8 bytes + ``offset`` with optional BOS prefix (bos_id < 0 omits)."""
    data = text.encode("utf-8")
    n = len(data)
    out = np.empty((n + (1 if bos_id >= 0 else 0),), np.int32)
    lib = _lib()
    if lib is not None:
        buf = (ctypes.c_uint8 * n).from_buffer_copy(data) if n else \
            (ctypes.c_uint8 * 1)()
        lib.ftl_byte_tokenize(buf, n, bos_id, offset, _i32(out))
    else:
        w = 0
        if bos_id >= 0:
            out[0] = bos_id
            w = 1
        out[w:] = np.frombuffer(data, np.uint8).astype(np.int32) + offset
    return out
