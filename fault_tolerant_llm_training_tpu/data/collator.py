"""CLM collation (ref: dataset.py:38-53).

Stacks ``seq_len + 1``-long id lists to (B, S+1), shifts into inputs/labels,
and masks padding labels with -100 — byte-identical semantics to the
reference's ``CollatorForCLM``, producing numpy int32 (device transfer happens
in the prefetcher, not here).
"""

import dataclasses
from typing import Dict, List, Tuple

import numpy as np

from .native import collate_clm


@dataclasses.dataclass
class CollatorForCLM:
    sequence_length: int
    pad_token_id: int

    def __call__(self, examples: List[Dict]) -> Tuple[np.ndarray, np.ndarray]:
        input_ids = np.asarray([e["input_ids"] for e in examples],
                               dtype=np.int32)  # (B, S+1)
        inputs, labels = collate_clm(input_ids, self.pad_token_id)
        assert inputs.shape[1] == labels.shape[1] == self.sequence_length
        assert inputs.shape == labels.shape
        return inputs, labels
