from .tokenizer import ByteTokenizer, load_tokenizer
from .parquet import ParquetDataset, IterableParquetDataset
from .collator import CollatorForCLM
from .loader import DataLoader

__all__ = [
    "ByteTokenizer",
    "load_tokenizer",
    "ParquetDataset",
    "IterableParquetDataset",
    "CollatorForCLM",
    "DataLoader",
]
