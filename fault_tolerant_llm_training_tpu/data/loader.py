"""Batching iterator over the datasets (the reference uses
``torch.utils.data.DataLoader`` with default workers — ref: train.py:31-34).

Yields ``(inputs, labels)`` numpy batches; delegates position state to the
underlying dataset so the loader itself is checkpointable. Device transfer /
double buffering lives in ``prefetch.py``.
"""

from typing import Dict, Iterator, Tuple

import numpy as np

from .collator import CollatorForCLM
from .parquet import ParquetDataset


class DataLoader:
    def __init__(self, dataset, batch_size: int, collator: CollatorForCLM = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collator = collator
        self._iter = None

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        self._iter = iter(self.dataset)  # rewinds the packed dataset
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._iter is None:
            self.resume()
        if isinstance(self.dataset, ParquetDataset):
            examples = [next(self._iter) for _ in range(self.batch_size)]
            return self.collator(examples)
        # packed path: items are already (inputs, labels) pairs
        pairs = [next(self._iter) for _ in range(self.batch_size)]
        inputs = np.stack([p[0] for p in pairs])
        labels = np.stack([p[1] for p in pairs])
        return inputs, labels

    def resume(self) -> None:
        """Continue from the dataset's current (possibly restored) position
        without resetting it — unlike ``__iter__`` which rewinds the packed
        dataset (ref: dataset.py:68-72)."""
        self._iter = self.dataset  # both datasets are self-iterators

    def get_state(self) -> Dict:
        return self.dataset.get_state()

    def set_state(self, state: Dict) -> None:
        self.dataset.set_state(state)
        self.resume()
