"""Batching iterator over the datasets (the reference uses
``torch.utils.data.DataLoader`` with default workers — ref: train.py:31-34).

Yields ``(inputs, labels)`` numpy batches; delegates position state to the
underlying dataset so the loader itself is checkpointable. Device transfer /
double buffering lives in ``prefetch.py``.

``HostShardedDataLoader`` is the pod-scale map-path variant: each host
tokenizes ONLY the global-batch rows its own devices consume (SURVEY.md §7.3
hard part 5 — the replicated loader does O(hosts) redundant tokenization on
exactly the path the survey names as the pod bottleneck), while the
checkpointed position stays the single GLOBAL sample index, so data state is
host-count-agnostic and cross-topology resume is unchanged.
"""

from typing import Dict, Iterator, Tuple

import jax
import numpy as np

from .collator import CollatorForCLM
from .parquet import ParquetDataset


class DataLoader:
    def __init__(self, dataset, batch_size: int, collator: CollatorForCLM = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collator = collator
        self._iter = None

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        self._iter = iter(self.dataset)  # rewinds the packed dataset
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._iter is None:
            self.resume()
        if isinstance(self.dataset, ParquetDataset):
            examples = [next(self._iter) for _ in range(self.batch_size)]
            return self.collator(examples)
        # packed path: items are already (inputs, labels) pairs
        pairs = [next(self._iter) for _ in range(self.batch_size)]
        inputs = np.stack([p[0] for p in pairs])
        labels = np.stack([p[1] for p in pairs])
        return inputs, labels

    def resume(self) -> None:
        """Continue from the dataset's current (possibly restored) position
        without resetting it — unlike ``__iter__`` which rewinds the packed
        dataset (ref: dataset.py:68-72)."""
        self._iter = self.dataset  # both datasets are self-iterators

    def get_state(self) -> Dict:
        return self.dataset.get_state()

    def set_state(self, state: Dict) -> None:
        self.dataset.set_state(state)
        self.resume()


class HostShardedDataLoader(DataLoader):
    """Map-path loader that materializes only this host's batch rows.

    The row set is derived exactly from the batch ``NamedSharding``'s
    device→index map (no contiguity or host-layout assumption): the union
    of the batch-dim slices of this process's addressable devices. With N
    hosts each tokenizes ~B/N rows instead of all B. ``stage_global``
    assembles the global (B, S) array from per-device shards
    (``jax.make_array_from_single_device_arrays``) — the replicated
    ``device_put``-the-whole-batch path stays available as
    ``--data-sharding replicated``.

    Correctness contract: the sample at global batch row ``b`` of the batch
    starting at global position ``base`` is ``dataset[base + b]`` — the
    same element the replicated loader's sequential ``next()`` walk hands
    to row ``b`` — so the training trajectory is bit-identical to the
    replicated path (asserted by tests/test_sharded_data.py). Shuffle and
    wraparound live in ``dataset.__getitem__`` and apply unchanged; the
    position advances by the full global batch size regardless of host
    count.
    """

    def __init__(self, dataset: ParquetDataset, batch_size: int,
                 collator: CollatorForCLM, sharding,
                 sequence_length: int):
        super().__init__(dataset, batch_size, collator)
        self.sharding = sharding
        self._shape = (batch_size, sequence_length)
        proc = jax.process_index()
        self._dev_slices = [
            (d, idx)
            for d, idx in sharding.devices_indices_map(self._shape).items()
            if d.process_index == proc
        ]
        rows = set()
        for _, (idx_b, _) in self._dev_slices:
            rows.update(range(idx_b.start or 0,
                              batch_size if idx_b.stop is None else idx_b.stop))
        self.host_rows = np.asarray(sorted(rows), dtype=np.int64)
        self.rows_tokenized = 0  # diagnostic: disjointness is tested on this

    def __iter__(self) -> Iterator:
        return self

    def resume(self) -> None:
        pass  # position lives in the dataset; nothing to rebind

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        base = self.dataset._next_index
        if base + self.batch_size > len(self.dataset):
            raise StopIteration
        examples = [self.dataset[base + int(b)] for b in self.host_rows]
        self.dataset._next_index = base + self.batch_size  # GLOBAL advance
        self.rows_tokenized += len(examples)
        return self.collator(examples)

    def stage_global(self, inputs: np.ndarray, labels: np.ndarray):
        """(host_rows, S) local arrays -> global (B, S) jax.Arrays on this
        host's devices, sharded per ``self.sharding``."""
        out = []
        for arr in (inputs, labels):
            shards = []
            for d, (idx_b, idx_s) in self._dev_slices:
                lo = int(np.searchsorted(self.host_rows, idx_b.start or 0))
                hi = int(np.searchsorted(
                    self.host_rows,
                    self._shape[0] if idx_b.stop is None else idx_b.stop))
                shards.append(jax.device_put(arr[lo:hi, idx_s], d))
            out.append(jax.make_array_from_single_device_arrays(
                self._shape, self.sharding, shards))
        return out[0], out[1]
