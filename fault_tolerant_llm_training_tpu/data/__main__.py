"""Data-layer smoke harness (ref: dataset.py:104-166 — the reference's only
executable "test").

Mirrors the reference's ``__main__`` block: decode one sample, run one batch
through *both* dataset classes (map-style + packed iterable), and print shapes
plus the -100 loss-mask percentage. Upgrade over the reference (SURVEY.md §4):
it is hermetic — with no ``--dataset`` it synthesizes a parquet file, and the
default tokenizer is the offline byte tokenizer, so it runs with no cluster
filesystem and no network.

    python -m fault_tolerant_llm_training_tpu.data [--dataset X.parquet]
        [--tokenizer-name-or-path byte] [--sequence-length 128]
        [--batch-size 2]
"""

import argparse
import os
import tempfile

import numpy as np

from .collator import CollatorForCLM
from .loader import DataLoader
from .parquet import IterableParquetDataset, ParquetDataset
from .tokenizer import load_tokenizer


def _synthesize_parquet(path: str, n_docs: int = 64) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot",
             "golf", "hotel", "india", "juliet"]
    docs = [" ".join(rng.choice(words, size=int(rng.integers(5, 120))))
            for _ in range(n_docs)]
    pq.write_table(pa.table({"text": docs}), path)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dataset", type=str, default="",
                   help="parquet file with a 'text' column; default: synthetic")
    p.add_argument("--tokenizer-name-or-path", type=str, default="byte")
    p.add_argument("--sequence-length", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=2)
    args = p.parse_args(argv)

    synthesized = None
    dataset_path = args.dataset
    if not dataset_path:
        fd, dataset_path = tempfile.mkstemp(suffix=".parquet")
        os.close(fd)
        synthesized = dataset_path
        _synthesize_parquet(dataset_path)
        print(f"synthesized dataset: {dataset_path}")

    tok = load_tokenizer(args.tokenizer_name_or_path)
    # HF tokenizers may lack pad/bos tokens (e.g. gpt2 has neither); the
    # harness needs both, so substitute usable ids rather than crash in
    # encode_plus / pack_clm.
    if tok.pad_token_id is None:
        if getattr(tok, "eos_token", None):
            tok.pad_token = tok.eos_token
        else:
            # add_special_tokens registers the new token in the vocab;
            # plain `tok.pad_token = ...` would leave pad_token_id None.
            tok.add_special_tokens({"pad_token": "<|pad|>"})
        print(f"tokenizer has no pad token; using id {tok.pad_token_id}")
    bos_id = tok.bos_token_id
    if bos_id is None:
        bos_id = tok.eos_token_id if tok.eos_token_id is not None else tok.pad_token_id
        print(f"tokenizer has no BOS token; packing with id {bos_id}")
    seq, bs = args.sequence_length, args.batch_size

    # --- map-style path (ref: dataset.py:119-143) ---
    ds = ParquetDataset(dataset_path, tok, seq, training_samples=bs * 4)
    sample = ds[0]
    decoded = tok.decode([t for t in sample["input_ids"]
                          if t != tok.pad_token_id])
    print(f"[map] decoded sample 0 (first 80 chars): {decoded[:80]!r}")
    collator = CollatorForCLM(seq, tok.pad_token_id)
    inputs, labels = next(iter(DataLoader(ds, bs, collator)))
    masked = float((labels == -100).mean()) * 100
    print(f"[map] batch: inputs {inputs.shape} {inputs.dtype}, "
          f"labels {labels.shape}; -100 mask: {masked:.1f}%")

    # --- packed iterable path (ref: dataset.py:146-166) ---
    for legacy in (True, False):
        it = IterableParquetDataset(dataset_path, tok, seq,
                                    bos_token_id=bos_id,
                                    legacy=legacy)
        inputs, labels = next(iter(DataLoader(it, bs)))
        masked = float((labels == -100).mean()) * 100
        mode = "legacy (reference quirks)" if legacy else "fixed"
        print(f"[packed/{mode}] batch: inputs {inputs.shape} {inputs.dtype}, "
              f"labels {labels.shape}; -100 mask (BOS): {masked:.1f}%")

    if synthesized is not None:
        os.unlink(synthesized)
    print("data smoke test OK")


if __name__ == "__main__":
    main()
