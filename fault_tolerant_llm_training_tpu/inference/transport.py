"""Pluggable KV transport: the lane KV block trains travel on.

Every KV movement in the system — disaggregated prefill->decode
shipments, fleet-store publish/fetch, migration handoff — speaks ONE
contract: an artifact handle plus a manifest (geometry, block list,
length, meta) whose verification gates any device write. This module
puts a seam under that contract so the same scheduler/store/router code
can move blocks over two very different fabrics:

- ``FsTransport`` (lane ``fs``): the filesystem artifacts of
  kv_cache.py, unchanged — byte payloads CRC-verified end to end, the
  durable cross-host/cross-process form every committed receipt and
  journal record names. The laptop transport, and the only one that
  survives a process boundary.

- ``MemTransport`` (lane ``mem``): a same-pod fast path. Export still
  writes the fs artifact (it IS the durable record, the journal entry,
  and the fallback lane), but additionally pushes the train's pool
  slices device-to-device — ``jax.device_put`` of each
  :func:`block_layout` segment's gathered rows, scale rows included for
  int8 — into a process-local :class:`MemFabric` keyed by the SAME
  artifact path. Import tries the fabric first: verification is on the
  manifest *metadata* (a sha256 digest over geometry, block list,
  length and meta — chain hashes ride in meta), never a re-CRC of
  payload bytes, and landing is one device-side scatter per pool array
  through the same index discipline as ``import_block_batch``. Any
  miss or metadata mismatch degrades to the fs lane, whose CRC verify
  can still reject down to the committed-prefix replay — the
  mem -> fs -> replay ladder is structural, not a special case.

Handles are identical across lanes (the artifact directory path), so
ship/handoff/store journal records, router verification and receipts
need no new addressing scheme. The fabric is process-local by design:
one JAX process == one ICI domain here, which is exactly the DistServe
"same pod" assumption — :func:`resolve_lane` is the auto-detect that
degrades a cross-process fleet host's ``--kv-transport mem`` request
back to ``fs``.
"""

import hashlib
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import (
    KVBlockIntegrityError,
    PagedKVCache,
    QuantPool,
    _cache_geometry,
    block_layout,
    export_blocks,
    verify_block_artifact,
)

LANES = ("fs", "mem")


def meta_digest(manifest: Dict) -> str:
    """The mem lane's verification token: sha256 over the manifest's
    METADATA — geometry, block list, length, meta (chain hashes, request
    identity) — in canonical JSON. Deliberately excludes ``files``: the
    whole point of the lane is that payload bytes pushed device-to-device
    inside one pod are not re-hashed, their integrity is the fabric's."""
    body = {k: manifest.get(k) for k in
            ("version", "geometry", "blocks", "length", "meta")}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()


def _payload_bytes(manifest: Dict, n_blocks: int) -> int:
    """Payload bytes ``n_blocks`` blocks of this train cost (every block
    of one artifact is the same size by construction)."""
    files = manifest.get("files", {})
    if not files:
        return 0
    per = int(files[sorted(files)[0]].get("size", 0))
    return per * int(n_blocks)


class _MemTrain:
    """One pushed train resident in the fabric: the manifest it was
    exported under, its per-segment device arrays (block_layout order),
    and the metadata digest captured at push time."""

    __slots__ = ("manifest", "arrays", "digest")

    def __init__(self, manifest: Dict, arrays: List, digest: str):
        self.manifest = manifest
        self.arrays = arrays
        self.digest = digest


class MemFabric:
    """Process-local stand-in for the pod's ICI domain: artifact handle
    -> pushed :class:`_MemTrain`. Exporter and importer must share ONE
    fabric instance — there is no cross-process form, on purpose."""

    def __init__(self):
        self._trains: Dict[str, _MemTrain] = {}

    def __len__(self) -> int:
        return len(self._trains)

    def __contains__(self, handle) -> bool:
        return str(handle) in self._trains

    def put(self, handle, train: _MemTrain) -> None:
        self._trains[str(handle)] = train

    def get(self, handle) -> Optional[_MemTrain]:
        return self._trains.get(str(handle))

    def drop(self, handle) -> None:
        self._trains.pop(str(handle), None)

    def poison(self, handle) -> str:
        """Chaos hook (``mem_corrupt``): mutate a resident train's
        manifest METADATA without refreshing its push-time digest — the
        in-memory analogue of the artifact byte-flip faults. The mem
        verify must catch the digest disagreement and degrade the import
        to the fs lane. Returns a description of the mutation ('' when
        the handle holds no train)."""
        train = self._trains.get(str(handle))
        if train is None:
            return ""
        train.manifest["length"] = int(train.manifest.get("length", 0)) + 1
        return "manifest length incremented without re-digest"


class FsTransport:
    """The filesystem lane, verbatim: export/verify/import are the
    kv_cache.py artifact functions, byte payloads CRC-verified before
    any device write. ``lane_bytes`` / ``land_seconds`` feed the
    ``kv_transport_bytes_total{lane=}`` counters and the transport
    bench's shipment-landing clock."""

    name = "fs"
    lanes: Tuple[str, ...] = ("fs",)

    def __init__(self):
        self.lane_bytes: Dict[str, int] = {"fs": 0, "mem": 0}
        self.land_seconds: Dict[str, float] = {"fs": 0.0, "mem": 0.0}

    def export(self, cache: PagedKVCache, blocks: Sequence[int],
               out_dir: str, *, length: int,
               meta: Optional[Dict] = None) -> Dict:
        manifest = export_blocks(cache, blocks, out_dir,
                                 length=length, meta=meta)
        self.lane_bytes["fs"] += _payload_bytes(manifest,
                                                len(manifest["blocks"]))
        return manifest

    def verify(self, handle: str, lane: str = "fs") -> Dict:
        if lane != "fs":
            raise KVBlockIntegrityError(
                f"transport {self.name!r} has no {lane!r} lane")
        return verify_block_artifact(str(handle))

    def import_batch(self, engine, parts: Sequence[Tuple[str, Sequence[int]]],
                     lane: str = "fs",
                     allow_partial: bool = False) -> List[Dict]:
        if lane != "fs":
            raise KVBlockIntegrityError(
                f"transport {self.name!r} has no {lane!r} lane")
        t0 = time.monotonic()
        manifests = engine.import_pool_block_batch(
            list(parts), allow_partial=allow_partial)
        self.land_seconds["fs"] += time.monotonic() - t0
        self.lane_bytes["fs"] += sum(
            _payload_bytes(m, len(dest))
            for m, (_, dest) in zip(manifests, parts))
        return manifests


def _land_mem_trains(cache: PagedKVCache,
                     entries: Sequence[Tuple[_MemTrain, Sequence[int]]],
                     allow_partial: bool = False) -> PagedKVCache:
    """The mem lane's ``import_block_batch``: land every train with ONE
    scatter per pool array, sources already on device. Geometry and
    destination checks (vs the LIVE pool) precede the first write, same
    contract as the fs path; under ``allow_partial=True`` a train may
    land a prefix of its blocks (sub-train addressability)."""
    live = _cache_geometry(cache)
    dests: List[int] = []
    for train, dest_blocks in entries:
        geo = train.manifest.get("geometry")
        if geo != live:
            raise KVBlockIntegrityError(
                f"mem train geometry {geo} does not fit pool {live}")
        n = len(train.manifest.get("blocks", []))
        if (len(dest_blocks) > n
                or (not allow_partial and len(dest_blocks) != n)):
            raise ValueError(
                f"mem train has {n} block(s) but {len(dest_blocks)} "
                f"destination row(s) given")
        if 0 in dest_blocks:
            raise ValueError("refusing to import into reserved null "
                             "block 0")
        dests.extend(int(b) for b in dest_blocks)
    idx = jnp.asarray(np.asarray(dests, np.int32))
    layout = block_layout(cache)
    srcs = []
    for si in range(len(layout)):
        chunks = [train.arrays[si][:len(dest_blocks)]
                  for train, dest_blocks in entries if len(dest_blocks)]
        srcs.append(chunks[0] if len(chunks) == 1
                    else jnp.concatenate(chunks, axis=0))
    by_key = {(seg["layer"], seg["field"]): srcs[si]
              for si, seg in enumerate(layout)}

    def rebuild(pool, layer, field):
        if isinstance(pool, QuantPool):
            return QuantPool(
                q=pool.q.at[idx].set(by_key[(layer, field)]),
                scale=pool.scale.at[idx].set(
                    by_key[(layer, field + "_scale")]))
        return pool.at[idx].set(by_key[(layer, field)])

    new_k = tuple(rebuild(cache.k[layer], layer, "k")
                  for layer in range(len(cache.k)))
    new_v = tuple(rebuild(cache.v[layer], layer, "v")
                  for layer in range(len(cache.k)))
    return cache.replace(k=new_k, v=new_v)


class MemTransport(FsTransport):
    """The same-pod push lane. Export piggybacks on the fs lane (the
    artifact stays the durable record and the fallback), then pushes the
    train's device arrays into the shared :class:`MemFabric` under the
    artifact path. ``on_push(fabric, handle, ordinal)`` is the chaos
    seam (``mem_corrupt``), keyed by push ordinal like the artifact
    corruption faults."""

    name = "mem"
    lanes: Tuple[str, ...] = ("mem", "fs")

    def __init__(self, fabric: Optional[MemFabric] = None,
                 on_push: Optional[Callable[..., None]] = None):
        super().__init__()
        self.fabric = fabric if fabric is not None else MemFabric()
        self.on_push = on_push
        self.pushes = 0

    def export(self, cache: PagedKVCache, blocks: Sequence[int],
               out_dir: str, *, length: int,
               meta: Optional[Dict] = None) -> Dict:
        manifest = super().export(cache, blocks, out_dir,
                                  length=length, meta=meta)
        idx = jnp.asarray(np.asarray(list(blocks), np.int32))
        arrays = [jax.device_put(seg["array"][idx])
                  for seg in block_layout(cache)]
        # the fabric gets its OWN manifest copy: chaos poisons it, the
        # on-disk artifact (the fallback lane) must stay pristine
        self.fabric.put(out_dir, _MemTrain(
            manifest=json.loads(json.dumps(manifest)), arrays=arrays,
            digest=meta_digest(manifest)))
        self.lane_bytes["mem"] += _payload_bytes(manifest,
                                                 len(manifest["blocks"]))
        ordinal, self.pushes = self.pushes, self.pushes + 1
        if self.on_push is not None:
            self.on_push(self.fabric, out_dir, ordinal)
        return manifest

    def verify(self, handle: str, lane: str = "fs") -> Dict:
        if lane != "mem":
            return verify_block_artifact(str(handle))
        train = self.fabric.get(handle)
        if train is None:
            raise KVBlockIntegrityError(
                f"mem lane: no pushed train for "
                f"{os.path.basename(str(handle))}")
        if meta_digest(train.manifest) != train.digest:
            raise KVBlockIntegrityError(
                f"mem lane: manifest metadata digest mismatch for "
                f"{os.path.basename(str(handle))}")
        return train.manifest

    def import_batch(self, engine, parts: Sequence[Tuple[str, Sequence[int]]],
                     lane: str = "fs",
                     allow_partial: bool = False) -> List[Dict]:
        if lane != "mem":
            return super().import_batch(engine, parts, lane="fs",
                                        allow_partial=allow_partial)
        if getattr(engine, "kv_layout", "paged") != "paged":
            raise ValueError("block import requires the paged KV layout")
        t0 = time.monotonic()
        entries, manifests = [], []
        for handle, dest_blocks in parts:
            manifest = self.verify(handle, lane="mem")
            entries.append((self.fabric.get(handle), list(dest_blocks)))
            manifests.append(manifest)
        engine.cache = _land_mem_trains(engine.cache, entries,
                                        allow_partial=allow_partial)
        self.land_seconds["mem"] += time.monotonic() - t0
        self.lane_bytes["mem"] += sum(
            _payload_bytes(m, len(dest))
            for m, (_, dest) in zip(manifests, entries))
        return manifests


def make_transport(lane: str, fabric: Optional[MemFabric] = None,
                   on_push: Optional[Callable[..., None]] = None):
    """Build the transport for a resolved lane name."""
    if lane == "mem":
        return MemTransport(fabric=fabric, on_push=on_push)
    if lane == "fs":
        return FsTransport()
    raise ValueError(f"unknown kv transport lane {lane!r} "
                     f"(expected one of {LANES})")


def resolve_lane(requested: str, *, colocated: bool) -> str:
    """Same-pod auto-detect. The mem lane needs exporter and importer on
    one shared fabric (one process == one ICI domain here); a caller
    whose peers live in OTHER processes — a fleet prefill/decode host —
    degrades ``mem`` to ``fs``. ``colocated`` is the caller's claim that
    every import of its exports happens in this process."""
    if requested == "mem" and not colocated:
        return "fs"
    return requested
