"""Static-shape GQA-aware KV caches: per-slot ring buffers and paged blocks.

Two layouts share one contract (fixed-shape pytree in, pytree out, buffers
donatable by the jitted step):

**Ring** (:class:`KVCache`) — one pair of head-major buffers per layer,
``[slots, kv_heads, max_len, head_dim]``. ``slots`` is the
continuous-batching dimension: each slot holds one in-flight request's
prefix, and the per-slot ``lengths`` vector is both the decode position
offset and the attention-mask boundary (ops/attention.py
``cached_attention``). Simple, but every slot reserves ``max_len``
positions: long-context configs strand most of HBM on empty reservation.

**Paged** (:class:`PagedKVCache`, vLLM's PagedAttention layout, Kwon et al.
2023) — one GLOBAL block pool per layer, ``[num_blocks, kv_heads,
block_size, head_dim]``, plus a host-owned int32 block table per slot
mapping logical block position -> pool block. A request only occupies the
blocks its actual ``prompt + max_new_tokens`` needs, so at a fixed HBM
budget far more requests fit concurrently. Block 0 is the reserved
null/scratch block: free block-table entries point at it, and writes from
masked positions (bucket padding, inactive decode slots) are redirected
into it, so a static-shape step never scribbles on another request's
blocks. The block allocator lives host-side in the scheduler
(inference/scheduler.py ``BlockAllocator``); the device only ever sees the
pool and the tables.

Because the tables are plain indices, a pool block can appear in SEVERAL
slots' tables at once — that is the prefix cache
(inference/prefix_cache.py): requests sharing a committed prompt prefix
point their tables at the same blocks and skip the prefill compute for
them. Sharing is refcounted in the allocator and strictly READ-only: the
only write a shared block ever sees is :func:`copy_kv_block` — the
copy-on-write primitive that duplicates it into a private block before a
slot resumes prefill inside it.

Everything is a fixed-shape pytree argument (flax ``struct``), NOT a flax
mutable collection: the jitted decode step takes the cache in and returns it
out, which lets the engine donate the buffers (jax.jit ``donate_argnums``)
so XLA updates them in place — no per-token reallocation of the largest
serving tensor.

Sharding under the training mesh (parallel/mesh.py): ``kv_heads`` rides the
'tensor' axis exactly like the wk/wv projections that produce it
(parallel/sharding.py LOGICAL_RULES) in BOTH layouts (it is dim 1 of the
ring buffer and of the block pool alike); slots/blocks/positions stay
replicated.

**Quantized paged mode** (``init_paged_cache(dtype=jnp.int8)``) stores each
layer's pool as a :class:`QuantPool`: an int8 block pool plus a parallel
per-(block, kv_head) fp32 scale pool, vLLM/KIVI-style symmetric per-block
quantization. Halving bytes-per-position doubles ``kv_blocks_total`` at a
fixed HBM budget — which the paged admission gate converts directly into
concurrency. The scale invariant is deliberately simple (a block's scale is
owned by the row at its local position 0; see ``_quantized_scatter``) so
every write stays row-granular like the bf16 path and the within-dtype
bit-exactness contracts survive unchanged.
"""

import json
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.configs import TransformerConfig


class KVCache(struct.PyTreeNode):
    """Per-layer (slots, kv_heads, max_len, head_dim) buffers + fill counts."""

    k: Tuple[jax.Array, ...]  # length n_layers
    v: Tuple[jax.Array, ...]
    lengths: jax.Array        # (slots,) int32 tokens written per slot

    @property
    def slots(self) -> int:
        return self.k[0].shape[0]

    @property
    def max_len(self) -> int:
        return self.k[0].shape[2]


def init_cache(cfg: TransformerConfig, slots: int, max_len: int,
               dtype=None) -> KVCache:
    """Zero-filled cache; ``dtype`` defaults to the model's activation dtype
    (bf16) so cached keys/values are bit-identical to the training forward's."""
    dtype = cfg.dtype if dtype is None else dtype
    shape = (slots, cfg.kv_heads, max_len, cfg.head_dim)
    zeros = tuple(jnp.zeros(shape, dtype) for _ in range(cfg.n_layers))
    return KVCache(k=zeros, v=tuple(jnp.zeros(shape, dtype)
                                    for _ in range(cfg.n_layers)),
                   lengths=jnp.zeros((slots,), jnp.int32))


class PagedKVCache(struct.PyTreeNode):
    """Per-layer (num_blocks, kv_heads, block_size, head_dim) pools + per-slot
    fill counts. The block tables stay HOST-side (scheduler) and are passed
    into each compiled step as a plain int32 argument — they are tiny
    (slots x blocks_per_slot) and change at admission/eviction, not per
    token, so shipping them per call costs nothing while keeping the donated
    device state to the pools themselves."""

    k: Tuple[jax.Array, ...]  # length n_layers
    v: Tuple[jax.Array, ...]
    lengths: jax.Array        # (slots,) int32 tokens written per slot

    @property
    def slots(self) -> int:
        return self.lengths.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k[0].shape[0]

    @property
    def block_size(self) -> int:
        return self.k[0].shape[2]


def blocks_per_slot(max_len: int, block_size: int) -> int:
    """Block-table row length covering ``max_len`` positions."""
    return -(-max_len // block_size)


KV_QUANT_QMAX = 127.0  # symmetric int8: q in [-127, 127], -128 unused


class QuantPool(struct.PyTreeNode):
    """One layer's int8 paged block pool plus its parallel scale pool.

    ``q`` keeps the bf16 pool's exact geometry at one byte per element;
    ``scale`` holds one fp32 dequant scale per (block, kv_head). The
    ``shape``/``dtype`` properties mirror a plain array pool so every
    shape-derived consumer (block table reach in models/llama.py, engine
    geometry, export manifests) reads a QuantPool without branching, and as
    a ``struct.PyTreeNode`` it is transparent to jit/donation/eval_shape —
    the int8-mode :class:`PagedKVCache` simply carries QuantPools in its
    ``k``/``v`` tuples.

    The dequant rule — ``q.astype(float32) * scale`` cast once to the
    compute dtype — is THE shared contract: the gather reference applies it
    after the gather (ops/attention.py ``gather_kv_blocks``) and the Pallas
    kernels apply it to the block right after its DMA lands in VMEM
    (ops/paged_attention.py), so the two impls differ only by the online
    softmax's fp32 reordering, same as the bf16 parity story."""

    q: jax.Array      # (num_blocks, kv_heads, block_size, head_dim) int8
    scale: jax.Array  # (num_blocks, kv_heads) fp32

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


def quantize_rows(rows: jax.Array, scale: jax.Array) -> jax.Array:
    """Symmetric round-to-nearest: (R, K, D) fp32 rows at per-(row, head)
    ``scale`` (R, K) into int8 [-127, 127]. Zero scales (a block whose
    position-0 row was exactly zero) degrade to divisor 1 so the result
    stays finite and deterministic — dequant then reproduces the zeros
    exactly."""
    safe = jnp.where(scale > 0, scale, 1.0)[:, :, None]
    return jnp.clip(jnp.round(rows / safe), -KV_QUANT_QMAX,
                    KV_QUANT_QMAX).astype(jnp.int8)


def _quantized_scatter(pool: QuantPool, blk: jax.Array, off: jax.Array,
                       rows: jax.Array) -> QuantPool:
    """Land fp32 ``rows`` (R, kv_heads, head_dim) at ``(blk[r], :, off[r],
    :)`` of an int8 pool, maintaining the scale invariant:

    **A block's scale is owned by its local position 0.** A row landing at
    block-local offset 0 SETS the block's per-head scale to its own
    amax/127 — a plain overwrite, never a running max — and every row
    landing at offset > 0 quantizes at the scale already in the pool,
    clipped into [-127, 127]. Positions are committed in sequence order, so
    a block's position 0 is always written before its higher offsets, and
    existing content is NEVER requantized: a write stays row-granular
    exactly like the bf16 scatter. That is the property the within-dtype
    bit-exactness contracts (exact spec-verify, burst decode, packed
    prefill, COW resume) lean on — a rejected speculative row can disturb a
    scale only at an offset-0 position the committed stream's own next
    write deterministically resets with identical inputs. Clipping rows
    that outgrow their block's committed scale is the accuracy cost of that
    determinism; the parity check's adversarial matrix bounds it.

    Rows diverted to null block 0 (masked writes, and offset>0 rows' scale
    lane below) may scribble scale[0]; harmless — null-block lanes are
    additively masked to exactly zero attention weight, so scale[0] is
    never read live."""
    amax = jnp.max(jnp.abs(rows), axis=-1)            # (R, K)
    setter = off == 0
    scale_blk = jnp.where(setter, blk, 0)
    new_scale = pool.scale.at[scale_blk, :].set(amax / KV_QUANT_QMAX)
    row_scale = new_scale[blk]                        # post-update gather
    return QuantPool(
        q=pool.q.at[blk, :, off, :].set(quantize_rows(rows, row_scale)),
        scale=new_scale)


def init_paged_cache(cfg: TransformerConfig, slots: int, max_len: int,
                     block_size: int, num_blocks: Optional[int] = None,
                     dtype=None) -> PagedKVCache:
    """Zero-filled block pool. ``num_blocks`` defaults to full reservation
    parity with the ring layout (slots * ceil(max_len/block_size)) plus the
    null block — the interesting configs pass FEWER blocks than that and let
    the scheduler admit by actual per-request need instead."""
    dtype = cfg.dtype if dtype is None else dtype
    if num_blocks is None:
        num_blocks = slots * blocks_per_slot(max_len, block_size) + 1
    if num_blocks < 2:
        raise ValueError(f"num_blocks {num_blocks} < 2: block 0 is the "
                         f"reserved null block, at least one usable block "
                         f"is required")
    shape = (num_blocks, cfg.kv_heads, block_size, cfg.head_dim)
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        # Quantized mode: int8 pools + per-(block, kv_head) fp32 scales.
        # Requesting the pool dtype IS the mode switch, so reset/rebuild
        # paths that thread ``cache.k[0].dtype`` round-trip for free.
        def pool():
            return QuantPool(
                q=jnp.zeros(shape, jnp.int8),
                scale=jnp.zeros((num_blocks, cfg.kv_heads), jnp.float32))
        return PagedKVCache(
            k=tuple(pool() for _ in range(cfg.n_layers)),
            v=tuple(pool() for _ in range(cfg.n_layers)),
            lengths=jnp.zeros((slots,), jnp.int32))
    return PagedKVCache(
        k=tuple(jnp.zeros(shape, dtype) for _ in range(cfg.n_layers)),
        v=tuple(jnp.zeros(shape, dtype) for _ in range(cfg.n_layers)),
        lengths=jnp.zeros((slots,), jnp.int32))


def write_paged_kv(pool: jax.Array, new: jax.Array, block_tables: jax.Array,
                   start: jax.Array, valid: jax.Array) -> jax.Array:
    """Scatter ``new`` (B, K, S, D) into the block ``pool`` (N, K, bs, D) at
    each slot's positions ``start[b] + [0, S)``, translated through
    ``block_tables`` (B, blocks_per_slot). Only the NEW tokens move — one
    (B*S)-row scatter per call, never the whole cache. Positions with
    ``valid`` (B, S) False (bucket padding past the prompt, inactive decode
    slots) are redirected into null block 0, so a static-shape write can
    never land in another request's blocks. Positions past the table's reach
    (start + S can exceed blocks_per_slot * bs in a speculative verify round
    whose draft overruns a nearly-full slot) also divert to the null block —
    clipping them into the last table column would wrap the write onto the
    slot's OWN committed KV at ``pos % bs`` and silently corrupt it. Valid
    in-range positions map to distinct (block, offset) pairs (the allocator
    hands each slot disjoint blocks), so the scatter is collision-free where
    it matters.

    A :class:`QuantPool` takes the identical (block, offset) routing; the
    rows quantize through :func:`_quantized_scatter` (offset-0 rows set
    their block's scale, the rest quantize at it)."""
    bs = pool.shape[2]
    b, k, s, d = new.shape
    pos = start[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]   # (B, S)
    raw = pos // bs
    idx = jnp.clip(raw, 0, block_tables.shape[1] - 1)
    in_table = raw < block_tables.shape[1]
    blk = jnp.where(valid & in_table,
                    jnp.take_along_axis(block_tables, idx, axis=1), 0)
    off = pos % bs
    upd = jnp.transpose(new, (0, 2, 1, 3)).reshape(b * s, k, d)
    if isinstance(pool, QuantPool):
        return _quantized_scatter(pool, blk.reshape(-1), off.reshape(-1),
                                  upd.astype(jnp.float32))
    return pool.at[blk.reshape(-1), :, off.reshape(-1), :].set(upd)


def remap_paged_path(pool: jax.Array, block_tables: jax.Array,
                     start: jax.Array, src_nodes: jax.Array,
                     accepted: jax.Array) -> jax.Array:
    """Commit a tree-verify round's WINNING path: move each accepted
    node's (kv_heads, head_dim) row from its tree-window position to its
    committed position, inside the slot's own blocks.

    A tree round writes node i's KV at position ``start[b] + i`` (row
    order), but the accepted path's nodes p_0 < p_1 < ... are generally
    non-contiguous rows; the committed stream needs them at
    ``start[b] + 1 + j``. ``src_nodes`` (B, depth) holds the path's node
    row indices, ``accepted`` (B,) how many are live. Moves with
    ``j >= accepted[b]`` divert to null block 0 (same discipline as
    :func:`write_paged_kv`), so rejected branches simply rot as stale
    bytes past the new committed length — the linear-spec rejected-suffix
    story, no allocator traffic. Primary-chain moves (src == dst) are
    harmless bitwise no-ops: every source row is gathered before the one
    scatter writes. This runs as the tree-verify program's epilogue
    (inference/engine.py), one gather+scatter per layer per pool.
    """
    bs = pool.shape[2]
    b, depth = src_nodes.shape
    nb = block_tables.shape[1]
    steps = jnp.arange(depth, dtype=jnp.int32)[None, :]
    src_pos = start[:, None] + src_nodes                        # (B, depth)
    dst_pos = start[:, None] + 1 + steps
    live = steps < accepted[:, None]
    src_blk = jnp.take_along_axis(
        block_tables, jnp.clip(src_pos // bs, 0, nb - 1), axis=1)
    dst_blk = jnp.where(live & (dst_pos // bs < nb),
                        jnp.take_along_axis(
                            block_tables, jnp.clip(dst_pos // bs, 0, nb - 1),
                            axis=1), 0)
    if isinstance(pool, QuantPool):
        # Dequantize the gathered rows at their SOURCE blocks' scales, then
        # requantize through the standard scatter at the destination (a
        # move crossing into a fresh block lands at local offset 0 and sets
        # that block's scale, same as a sequential write would have).
        q_rows = pool.q[src_blk.reshape(-1), :, (src_pos % bs).reshape(-1), :]
        src_scale = pool.scale[src_blk.reshape(-1)]
        rows = q_rows.astype(jnp.float32) * src_scale[:, :, None]
        return _quantized_scatter(pool, dst_blk.reshape(-1),
                                  (dst_pos % bs).reshape(-1), rows)
    vals = pool[src_blk.reshape(-1), :, (src_pos % bs).reshape(-1), :]
    return pool.at[dst_blk.reshape(-1), :,
                   (dst_pos % bs).reshape(-1), :].set(vals)


def copy_kv_block(pool: jax.Array, src: jax.Array, dst: jax.Array
                  ) -> jax.Array:
    """Copy one pool block's (kv_heads, block_size, head_dim) contents from
    row ``src`` to row ``dst`` — the copy-on-write primitive. A slot about
    to write INSIDE a block it shares with other requests (prefix-cache
    full-prompt hit resuming at the last prompt position) first duplicates
    the block into a private one and remaps its table entry; the shared
    original is never written. Bitwise copy of committed bytes, so the
    divergent stream stays bit-identical to an uncached run. A
    :class:`QuantPool` copies BOTH the int8 row and its scale row bitwise —
    the copy dequantizes to exactly the original's values, so COW resumes
    stay bit-identical within the quantized mode too."""
    if isinstance(pool, QuantPool):
        return QuantPool(q=pool.q.at[dst].set(pool.q[src]),
                         scale=pool.scale.at[dst].set(pool.scale[src]))
    return pool.at[dst].set(pool[src])


def write_slot_kv(buf: jax.Array, new: jax.Array,
                  start: jax.Array) -> jax.Array:
    """Write ``new`` (B, K, S, D) into ``buf`` (B, K, T, D) at each slot's
    ``start`` (B,) position along the T axis — a vmap'd dynamic_update_slice,
    so every slot writes at its own offset in one fused XLA op. Callers
    guarantee ``start + S <= T`` for multi-token (prefill) writes; the
    single-token decode write always fits (start is taken mod T)."""
    return jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0)))(
        buf, new, start)


# ---------------------------------------------------------------------------
# Block export / import — the tiered-KV block-move primitive.
#
# A block artifact is a DIRECTORY: one payload file per exported pool block
# (``block_00000.bin`` = that row's bytes across every layer, K then V per
# layer) plus an ``integrity.json`` manifest recording geometry, the slot's
# committed KV length, per-file size + CRC32, and caller metadata (request
# id, committed tokens, row positions). The manifest is written atomic
# tmp+fsync+rename exactly like checkpoint/manager.py's checkpoint
# manifests, and import verifies every payload's size and CRC BEFORE any
# device write — a flipped byte, truncated file, or swapped manifest raises
# :class:`KVBlockIntegrityError` and the device pool is untouched, so every
# consumer (spill restore, handoff import) can fall back to the bit-exact
# committed-prefix replay instead of decoding garbage. The manifest file
# deliberately reuses the checkpoint manifest's name: the chaos injector's
# byte-flipper spares ``integrity.json``, so injected corruption always
# lands in a payload where the CRC must catch it.
# ---------------------------------------------------------------------------

BLOCK_MANIFEST_NAME = "integrity.json"
_BLOCK_ARTIFACT_VERSION = 1


class KVBlockIntegrityError(RuntimeError):
    """A KV block artifact failed verification (missing/torn manifest,
    size or CRC32 mismatch, or geometry that does not fit the live pool).
    Raised BEFORE any device write, so the pool is never half-imported."""


def _fsync_dir(path: str) -> None:
    """Flush directory metadata so a rename survives power loss (same
    best-effort semantics as checkpoint/manager.py)."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _block_file_name(i: int) -> str:
    return f"block_{i:05d}.bin"


def _cache_geometry(cache: PagedKVCache) -> Dict[str, object]:
    return {
        "n_layers": len(cache.k),
        "kv_heads": int(cache.k[0].shape[1]),
        "block_size": int(cache.block_size),
        "head_dim": int(cache.k[0].shape[3]),
        "dtype": str(np.dtype(cache.k[0].dtype)
                     if not hasattr(cache.k[0].dtype, "name")
                     else cache.k[0].dtype.name),
    }


def _np_dtype(arr) -> np.dtype:
    return np.dtype(arr.dtype.name if hasattr(arr.dtype, "name")
                    else arr.dtype)


def _pool_parts(field: str, pool):
    """The named device arrays one logical pool contributes to a block's
    payload: ``(field, array)`` for a plain pool, plus ``(field_scale,
    scales)`` for a :class:`QuantPool` — the scales ride INSIDE the
    per-block payload so the artifact CRC covers them like any other KV
    byte."""
    if isinstance(pool, QuantPool):
        return ((field, pool.q), (field + "_scale", pool.scale))
    return ((field, pool),)


def block_layout(cache: PagedKVCache) -> List[Dict[str, object]]:
    """THE per-block payload layout, shared by :func:`export_blocks`
    (payload assembly) and :func:`import_blocks` (payload slicing) so the
    two can never drift: an ordered segment list, one entry per pool array,
    layer-major with K before V (and each quantized pool's scale row
    directly after its int8 data). Each segment describes ONE block's slice
    of its array — ``array[j]`` — as ``{layer, field, array, shape, dtype,
    nbytes, offset}`` with ``offset`` its byte position inside the
    concatenated payload."""
    segs: List[Dict[str, object]] = []
    off = 0
    for layer in range(len(cache.k)):
        for field, base in (("k", cache.k[layer]), ("v", cache.v[layer])):
            for name, arr in _pool_parts(field, base):
                dt = _np_dtype(arr)
                shape = tuple(int(s) for s in arr.shape[1:])
                nbytes = int(np.prod(shape)) * dt.itemsize
                segs.append({"layer": layer, "field": name, "array": arr,
                             "shape": shape, "dtype": dt, "nbytes": nbytes,
                             "offset": off})
                off += nbytes
    return segs


def block_bytes(cache: PagedKVCache) -> int:
    """One pool block's payload bytes across every layer — K, V, and in
    the quantized layout their scale rows. Both the export payload size
    and the /metrics ``kv_bytes_per_block`` gauge."""
    return sum(int(seg["nbytes"]) for seg in block_layout(cache))


def bf16_block_bytes(cache: PagedKVCache) -> int:
    """What one block of the SAME geometry costs in the bf16 layout —
    the denominator of the [KV QUANT] capacity ratio. Data elements at
    2 bytes each, scale rows excluded (the bf16 layout has none). Equal
    to :func:`block_bytes` on a bf16 cache by construction."""
    return sum(
        (int(seg["nbytes"]) // seg["dtype"].itemsize) * 2
        for seg in block_layout(cache)
        if not str(seg["field"]).endswith("_scale"))


def export_blocks(cache: PagedKVCache, blocks: Sequence[int], out_dir: str,
                  *, length: int, meta: Optional[Dict] = None) -> Dict:
    """Serialize pool rows ``blocks`` device->host into artifact ``out_dir``.

    Payload file i holds block ``blocks[i]``'s bytes for every layer
    (layer-major, K before V). ``length`` is the slot's committed KV fill
    count (``cache.lengths[slot]`` at export) so import can restore the
    decode position exactly; ``meta`` is caller context carried verbatim
    (request id, committed tokens, row positions). Payloads are flushed and
    fsynced before the manifest commits via tmp+fsync+rename, so a torn
    artifact is detectable as missing-manifest, never as silent garbage.
    Returns the manifest dict."""
    if 0 in blocks:
        raise ValueError("refusing to export reserved null block 0")
    os.makedirs(out_dir, exist_ok=True)
    idx = np.asarray(list(blocks), np.int32)
    # One device->host gather per pool array, not per block; payload byte
    # order is block_layout()'s segment order, the same order import
    # slices by.
    hosts = [np.asarray(seg["array"][idx]) for seg in block_layout(cache)]
    files: Dict[str, Dict[str, int]] = {}
    for j in range(len(idx)):
        payload = b"".join(h[j].tobytes() for h in hosts)
        name = _block_file_name(j)
        path = os.path.join(out_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        files[name] = {"size": len(payload),
                       "crc32": zlib.crc32(payload) & 0xFFFFFFFF}
    manifest = {
        "version": _BLOCK_ARTIFACT_VERSION,
        "geometry": _cache_geometry(cache),
        "blocks": [int(b) for b in blocks],
        "length": int(length),
        "files": files,
        "meta": dict(meta or {}),
    }
    man_path = os.path.join(out_dir, BLOCK_MANIFEST_NAME)
    tmp = man_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, man_path)
    _fsync_dir(out_dir)
    return manifest


def verify_block_artifact(art_dir: str) -> Dict:
    """Read and CRC-verify a block artifact; returns the manifest.

    Checks, in order: manifest present and parseable, every payload file
    present, size match, CRC32 match. Any failure raises
    :class:`KVBlockIntegrityError` with the failing file named. No device
    state is involved — the router uses this to decide ship-vs-replay
    before a survivor ever sees the artifact."""
    man_path = os.path.join(art_dir, BLOCK_MANIFEST_NAME)
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise KVBlockIntegrityError(
            f"block artifact manifest unreadable: {man_path}: {e}") from e
    files = manifest.get("files", {})
    if len(files) != len(manifest.get("blocks", [])):
        raise KVBlockIntegrityError(
            f"block artifact manifest torn: {len(files)} file(s) for "
            f"{len(manifest.get('blocks', []))} block(s)")
    for name in sorted(files):
        want = files[name]
        path = os.path.join(art_dir, name)
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except OSError as e:
            raise KVBlockIntegrityError(
                f"block payload missing: {name}: {e}") from e
        if len(payload) != int(want["size"]):
            raise KVBlockIntegrityError(
                f"block payload size mismatch: {name}: "
                f"{len(payload)} != {want['size']}")
        got = zlib.crc32(payload) & 0xFFFFFFFF
        if got != int(want["crc32"]):
            raise KVBlockIntegrityError(
                f"block payload CRC mismatch: {name}: "
                f"{got:#010x} != {int(want['crc32']):#010x}")
    return manifest


def import_block_batch(cache: PagedKVCache,
                       parts: Sequence[Tuple[str, Sequence[int]]],
                       allow_partial: bool = False
                       ) -> Tuple[PagedKVCache, List[Dict]]:
    """Verify EVERY artifact in ``parts`` (``(art_dir, dest_blocks)``
    pairs, payload i of each artifact -> its ``dest_blocks[i]``) and land
    them all with ONE gather-scatter per pool array — a request's
    multi-chunk shipment train costs a single pool copy instead of one
    per artifact, which is what keeps a decode engine's admission stall
    off its decode-round tail. ALL verification — CRC of every payload,
    geometry vs the live pool, destination-row counts — happens before
    the first device write; on any mismatch
    :class:`KVBlockIntegrityError` is raised and ``cache`` is returned
    unmodified by the caller's contract. ``lengths`` is NOT touched here
    (the destination slot differs between spill-restore, handoff-import
    and shipment-import); callers set it from the manifests' ``length``.

    Under ``allow_partial=True`` a part may name FEWER destination rows
    than its artifact has blocks: payload files
    ``0..len(dest_blocks)-1`` land and the tail is left on disk —
    sub-train addressability, the store's partial prefix hit (a train
    published at depth N serves any prompt sharing its first
    ``len(dest_blocks)`` blocks; chain-hash keys make position
    content-determined, so a prefix of the payload files IS a prefix of
    the prompt). Verification still covers the WHOLE artifact. By
    default a count mismatch in EITHER direction is a caller bug
    (``ValueError``) — only the store's prefix-addressed fetch opts in.
    Returns ``(new_cache, manifests)`` in ``parts`` order."""
    live = _cache_geometry(cache)
    manifests: List[Dict] = []
    dests: List[int] = []
    for art_dir, dest_blocks in parts:
        manifest = verify_block_artifact(art_dir)
        geo = manifest["geometry"]
        if geo != live:
            raise KVBlockIntegrityError(
                f"block artifact geometry {geo} does not fit pool {live}")
        n = len(manifest["blocks"])
        if (len(dest_blocks) > n
                or (not allow_partial and len(dest_blocks) != n)):
            raise ValueError(
                f"artifact has {n} block(s) but {len(dest_blocks)} "
                f"destination row(s) given")
        if 0 in dest_blocks:
            raise ValueError("refusing to import into reserved null "
                             "block 0")
        manifests.append(manifest)
        dests.extend(int(b) for b in dest_blocks)
    n_layers = len(cache.k)
    layout = block_layout(cache)
    total = sum(int(seg["nbytes"]) for seg in layout)
    hosts = {(seg["layer"], seg["field"]):
             np.empty((len(dests),) + seg["shape"], seg["dtype"])
             for seg in layout}
    row = 0
    for (art_dir, dest_blocks), manifest in zip(parts, manifests):
        for j in range(len(dest_blocks)):
            with open(os.path.join(art_dir, _block_file_name(j)),
                      "rb") as f:
                payload = f.read()
            if len(payload) != total:
                raise KVBlockIntegrityError(
                    f"block payload {j} has {len(payload)} byte(s), "
                    f"geometry needs {total}")
            for seg in layout:
                off = int(seg["offset"])
                hosts[(seg["layer"], seg["field"])][row] = np.frombuffer(
                    payload[off:off + int(seg["nbytes"])],
                    seg["dtype"]).reshape(seg["shape"])
            row += 1
    idx = jnp.asarray(np.asarray(dests, np.int32))

    # Import is rare (restore/handoff/shipment admission, not per token),
    # so plain .at[].set per pool array is fine — no AOT program, no
    # donation games; the batching above keeps it to one set per array.
    def rebuild(pool, layer, field):
        if isinstance(pool, QuantPool):
            return QuantPool(
                q=pool.q.at[idx].set(
                    jnp.asarray(hosts[(layer, field)])),
                scale=pool.scale.at[idx].set(
                    jnp.asarray(hosts[(layer, field + "_scale")])))
        return pool.at[idx].set(jnp.asarray(hosts[(layer, field)]))

    new_k = tuple(rebuild(cache.k[layer], layer, "k")
                  for layer in range(n_layers))
    new_v = tuple(rebuild(cache.v[layer], layer, "v")
                  for layer in range(n_layers))
    return cache.replace(k=new_k, v=new_v), manifests


def import_blocks(cache: PagedKVCache, art_dir: str,
                  dest_blocks: Sequence[int]
                  ) -> Tuple[PagedKVCache, Dict]:
    """Single-artifact :func:`import_block_batch` — same
    verify-everything-before-any-device-write contract; returns
    ``(new_cache, manifest)``."""
    new_cache, manifests = import_block_batch(
        cache, [(art_dir, dest_blocks)])
    return new_cache, manifests[0]


def artifact_bytes(manifest: Dict) -> int:
    """Total payload bytes recorded in a block-artifact manifest."""
    return sum(int(f["size"]) for f in manifest.get("files", {}).values())


def block_payload(cache: PagedKVCache, block: int) -> bytes:
    """One pool block's host-side payload bytes, in :func:`block_layout`
    segment order — byte-identical to what :func:`export_blocks` writes
    for that block, which is what lets tests assert a store/ship
    roundtrip bitwise without re-exporting."""
    return b"".join(
        np.asarray(seg["array"][int(block)]).tobytes()
        for seg in block_layout(cache))


def cache_pspec() -> P:
    """(slots|blocks, kv_heads, positions, head_dim): slots/blocks replicated
    — every device decodes every request — only the heads shard: kv_heads
    on 'tensor', matching the wk/wv kernels that fill the buffer. The spec
    serves BOTH layouts because the paged pool keeps kv_heads at dim 1."""
    return P(None, "tensor", None, None)


def cache_shardings(cache, mesh):
    """NamedSharding pytree for a :class:`KVCache` or :class:`PagedKVCache`
    on ``mesh`` (None -> None), with the same divisibility degrade as the
    param shardings."""
    if mesh is None:
        return None
    from ..parallel.sharding import _fit_spec

    def shard(a):
        return NamedSharding(mesh, _fit_spec(cache_pspec(), a.shape, mesh))

    def shard_pool(p):
        if isinstance(p, QuantPool):
            # scale pools are (blocks, kv_heads): same head sharding as
            # the int8 data, one axis shorter.
            return QuantPool(
                q=shard(p.q),
                scale=NamedSharding(
                    mesh, _fit_spec(P(None, "tensor"), p.scale.shape, mesh)))
        return shard(p)

    return type(cache)(
        k=tuple(shard_pool(a) for a in cache.k),
        v=tuple(shard_pool(a) for a in cache.v),
        lengths=NamedSharding(mesh, P(None)),
    )
