"""Static-shape GQA-aware KV slot cache.

One pair of head-major ring buffers per layer, ``[slots, kv_heads, max_len,
head_dim]`` — KV heads at their native (grouped) count, mirroring the
training attention's no-repeat_kv einsum, so the cache is ``n_heads /
n_kv_heads`` times smaller than a repeated-head layout. ``slots`` is the
continuous-batching dimension: each slot holds one in-flight request's
prefix, and the per-slot ``lengths`` vector is both the decode position
offset and the attention-mask boundary (ops/attention.py
``cached_attention``).

Everything is a fixed-shape pytree argument (flax ``struct``), NOT a flax
mutable collection: the jitted decode step takes the cache in and returns it
out, which lets the engine donate the buffers (jax.jit ``donate_argnums``)
so XLA updates them in place — no per-token reallocation of the largest
serving tensor.

Sharding under the training mesh (parallel/mesh.py): ``kv_heads`` rides the
'tensor' axis exactly like the wk/wv projections that produce it
(parallel/sharding.py LOGICAL_RULES), slots/positions stay replicated.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.configs import TransformerConfig


class KVCache(struct.PyTreeNode):
    """Per-layer (slots, kv_heads, max_len, head_dim) buffers + fill counts."""

    k: Tuple[jax.Array, ...]  # length n_layers
    v: Tuple[jax.Array, ...]
    lengths: jax.Array        # (slots,) int32 tokens written per slot

    @property
    def slots(self) -> int:
        return self.k[0].shape[0]

    @property
    def max_len(self) -> int:
        return self.k[0].shape[2]


def init_cache(cfg: TransformerConfig, slots: int, max_len: int,
               dtype=None) -> KVCache:
    """Zero-filled cache; ``dtype`` defaults to the model's activation dtype
    (bf16) so cached keys/values are bit-identical to the training forward's."""
    dtype = cfg.dtype if dtype is None else dtype
    shape = (slots, cfg.kv_heads, max_len, cfg.head_dim)
    zeros = tuple(jnp.zeros(shape, dtype) for _ in range(cfg.n_layers))
    return KVCache(k=zeros, v=tuple(jnp.zeros(shape, dtype)
                                    for _ in range(cfg.n_layers)),
                   lengths=jnp.zeros((slots,), jnp.int32))


def write_slot_kv(buf: jax.Array, new: jax.Array,
                  start: jax.Array) -> jax.Array:
    """Write ``new`` (B, K, S, D) into ``buf`` (B, K, T, D) at each slot's
    ``start`` (B,) position along the T axis — a vmap'd dynamic_update_slice,
    so every slot writes at its own offset in one fused XLA op. Callers
    guarantee ``start + S <= T`` for multi-token (prefill) writes; the
    single-token decode write always fits (start is taken mod T)."""
    return jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0)))(
        buf, new, start)


def cache_pspec() -> P:
    """(slots, kv_heads, max_len, head_dim): slots replicated — every device
    decodes every request, only the heads shard — kv_heads on 'tensor',
    matching the wk/wv kernels that fill the buffer."""
    return P(None, "tensor", None, None)


def cache_shardings(cache: KVCache, mesh) -> Optional[KVCache]:
    """NamedSharding pytree for ``cache`` on ``mesh`` (None -> None), with
    the same divisibility degrade as the param shardings."""
    if mesh is None:
        return None
    from ..parallel.sharding import _fit_spec

    def shard(a):
        return NamedSharding(mesh, _fit_spec(cache_pspec(), a.shape, mesh))

    return KVCache(
        k=tuple(shard(a) for a in cache.k),
        v=tuple(shard(a) for a in cache.v),
        lengths=NamedSharding(mesh, P(None)),
    )
