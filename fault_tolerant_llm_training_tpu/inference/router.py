"""Fleet router: admission, lease sweeps, dead verdicts and migration.

``python -m fault_tolerant_llm_training_tpu.inference.router`` is the
fleet's control plane — deliberately NOT load-bearing for the data path:
hosts decode from the journal, the router only appends ``assign`` /
``migrate`` records to its own file, so a router crash stalls NEW
admissions but never running requests, and a restarted router recovers
its entire state from :func:`journal.fold` (it keeps no private truth).

Responsibilities, once per loop:

1. tail the intake JSONL (text prompts) and queue new requests;
2. sweep heartbeat leases (ft/lease.py): a lease older than its own ttl
   is a DEAD VERDICT — the router tombstones the host FIRST (fencing any
   zombie), then folds the journal and re-admits every in-flight request
   of the dead host on a survivor via a ``migrate`` record at gen+1
   carrying the committed token baseline (prompt + committed replay on
   the survivor continues the stream bit-exactly — scheduler.py);
3. adopt ``requeue`` records that draining hosts (or a single-host
   ``serve.py --journal-dir`` drain) persisted; when the drain also left
   a ``handoff`` record (``--handoff`` block shipment), the router
   CRC-verifies the artifact (ft/retry.py backoff around the reads) and
   names it in the ``migrate`` record so the survivor imports blocks
   instead of replaying — a torn or corrupt artifact is rejected here
   and the migration silently degrades to committed-prefix replay;
4. advance disaggregated requests whose prefill-role host journaled
   ``prefill_done``: CRC-verify every incremental ``ship`` artifact of
   the newest generation, pick a decode-capable host whose lease
   advertises the SAME kv-dtype, and write a ``decode`` record at gen+1
   naming the verified shipment list — ownership transfer prefill ->
   decode. ANY rejected shipment drops the whole list (the decode
   admission replays the committed prefix bit-exactly instead), and a
   missing dtype-matching decode host degrades to the same replay on any
   decode-capable host;
5. assign queued requests to the live host with the most estimated free
   KV blocks (lease capacity metadata, decremented locally per
   assignment so a burst between heartbeats doesn't dogpile one host —
   over-assignment is safe anyway: the scheduler queues on block
   exhaustion). Placement is ROLE-aware: fresh intake lands on
   prefill-capable hosts, committed history on decode-capable ones, and
   a dedicated prefill host is refused AT PLACEMENT TIME (before any
   prefill runs) when no decode-capable peer of its kv-dtype exists —
   the mixed-dtype pair can never produce an importable shipment.

Exactly-once: the router is the ONLY writer of assign/migrate records,
a dead host is swept once (tombstone + ``handled`` latch), and fold
resolves ownership by highest generation — a second sweep of the same
host finds every request already owned by a survivor and migrates
nothing.

/metrics (when --metrics-port is set): ``fleet_hosts_live``,
``requests_migrated_total``, ``fleet_lease_age_seconds{host=...}``,
``handoff_crc_rejected_total``, ``ship_crc_rejected_total``,
``disagg_decode_placements_total``,
``disagg_placements_rejected_total``.
"""

import argparse
import json
import os
import sys
import time
from collections import deque
from typing import Dict, Optional

from ..data.tokenizer import load_tokenizer
from ..ft.lease import FileKVStore, LeaseRegistry
from ..ft.retry import RetryDeadlineExceeded, retry_with_backoff
from ..obs import events, reqtrace
from ..obs.prometheus import MetricsServer
from ..obs.registry import REGISTRY
from ..utils.logging import (
    AUDIT_DISAGG_PLACE_FMT,
    AUDIT_DISAGG_SHIP_FMT,
    AUDIT_FLEET_DEAD_FMT,
    AUDIT_FLEET_MIGRATE_FMT,
    AUDIT_HANDOFF_FMT,
    init_logger,
    logger,
)
from .journal import RequestJournal, RequestState, fold
from .kv_cache import KVBlockIntegrityError, verify_block_artifact
from .kvstore import BlockStore
from .prefix_cache import chain_hashes

_M_HOSTS_LIVE = REGISTRY.gauge(
    "fleet_hosts_live",
    "Serving-fleet hosts holding a live, untombstoned lease")
_M_MIGRATED = REGISTRY.counter(
    "requests_migrated_total",
    "Requests re-admitted on a survivor after a dead verdict or requeue")
_M_LEASE_AGE = REGISTRY.gauge(
    "fleet_lease_age_seconds",
    "Age of each fleet host's heartbeat lease at the last router sweep")
_M_HANDOFF_REJECTED = REGISTRY.counter(
    "handoff_crc_rejected_total",
    "Handoff artifacts rejected by CRC/size/geometry verification "
    "(the request falls back to committed-prefix replay)")
_M_SHIP_REJECTED = REGISTRY.counter(
    "ship_crc_rejected_total",
    "Incremental block shipments rejected by CRC/size verification; one "
    "bad shipment drops the request's whole list and the decode "
    "admission replays the committed prefix")
_M_DECODE_PLACED = REGISTRY.counter(
    "disagg_decode_placements_total",
    "Ownership transfers prefill host -> decode host ('decode' journal "
    "records written after prefill_done)")
_M_PLACE_REJECTED = REGISTRY.counter(
    "disagg_placements_rejected_total",
    "Dedicated-prefill placements refused at placement time because no "
    "decode-capable peer of the same kv-dtype held a live lease")


class Router:
    """Journal-driven fleet control plane (module docstring). Pure state
    machine over (store, journal) — the CLI below just loops it."""

    def __init__(self, store: FileKVStore, journal_dir: str,
                 deadline_seconds: float = 1.0, clock=time.time,
                 kv_store_dir: str = ""):
        self.lease = LeaseRegistry(store, host_id=None,
                                   deadline_seconds=deadline_seconds,
                                   clock=clock)
        self.journal = RequestJournal(journal_dir, writer="router")
        self.journal_dir = journal_dir
        self.clock = clock
        self.pending: deque = deque()  # dicts awaiting a host
        self.pending_ids = set()
        self.assigned: Dict[str, tuple] = {}  # rid -> (host, gen) I wrote
        self.handled_dead = set()
        self.migrated_total = 0
        self.decode_placed_total = 0
        # (request_id, host) pairs whose mixed-dtype placement rejection
        # was already audited — the once-latch keeps the per-loop
        # pick_host retry from spamming the log
        self._place_rejected = set()
        # per-host capacity estimate, reset whenever the host stamps a
        # fresh lease, decremented locally per assignment in between
        self.est: Dict[str, dict] = {}
        # fleet-global KV store (inference/kvstore.py): read-only here —
        # the router folds its journal for cache-affinity placement
        # (SGLang-style: land an intake where the longest matching prefix
        # already resides), never publishes or evicts
        self.kv_store = (BlockStore(kv_store_dir, writer="router",
                                    clock=clock)
                         if kv_store_dir else None)

    # ---------------------------------------------------------------- intake
    def submit(self, request_id: str, prompt, max_new_tokens: int,
               temperature: float, top_p: float, seed: int,
               trace_id: str = "") -> bool:
        if request_id in self.pending_ids or request_id in self.assigned:
            return False
        trace_id = str(trace_id or reqtrace.mint_trace_id(request_id))
        self.pending.append({
            "id": request_id, "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature), "top_p": float(top_p),
            "seed": int(seed), "committed": [], "gen": 0, "src": None,
            "trace_id": trace_id, "enqueued": self.clock()})
        self.pending_ids.add(request_id)
        reqtrace.emit(trace_id, request_id, "intake",
                      prompt_tokens=len(prompt),
                      max_new_tokens=int(max_new_tokens))
        return True

    # ------------------------------------------------------------- membership
    def refresh(self, now: Optional[float] = None):
        """One lease sweep: returns (leases, tombstones, live) and updates
        the capacity estimates + membership gauges."""
        leases = self.lease.leases(now)
        tombs = set(self.lease.tombstones())
        live = {h: l for h, l in leases.items()
                if l.live and h not in tombs}
        for h, l in live.items():
            e = self.est.get(h)
            if e is None or e["stamp"] != l.t:
                self.est[h] = {"stamp": l.t, "slots": l.slots_free,
                               "blocks": l.blocks_free,
                               "block_size": max(1, l.block_size),
                               "role": getattr(l, "role", "both") or "both",
                               "kv_dtype": (getattr(l, "kv_dtype", "bf16")
                                            or "bf16")}
        for h in list(self.est):
            if h not in live:
                del self.est[h]
        _M_HOSTS_LIVE.set(len(live))
        for h, l in leases.items():
            _M_LEASE_AGE.labels(host=h).set(l.age)
        return leases, tombs, live

    def _blocks_needed(self, item: dict, block_size: int) -> int:
        n = len(item["prompt"]) + item["max_new_tokens"]
        return -(-n // max(1, block_size))

    def pick_host(self, item: dict) -> Optional[str]:
        """Admission policy: the live host with the most estimated free
        blocks, hosts with a free slot preferred. Returns None when no
        eligible host exists (the request waits in ``pending``).

        Role-aware: fresh intake (no committed history) needs a
        prefill-capable host, anything carrying committed tokens needs a
        decode-capable one (the replay that continues the stream IS a
        decode). A dedicated prefill host is refused at placement time —
        before its prefill ever runs — unless a decode-capable peer of
        the same kv-dtype holds a live lease, because a mixed-dtype pair
        can never produce an importable shipment.

        Cache-affinity aware when a fleet KV store is configured
        (SGLang-style): among hosts with a free slot, prefer the one
        whose published trains cover the longest prefix of this prompt
        — it admits with a store fetch instead of a cold prefill. A
        free slot still dominates affinity, so a full affinity host
        never starves an intake that a cold peer could run now."""
        stage = "decode" if item.get("committed") else "prefill"
        depths = self._affinity_depths(item)
        best = None
        for h in sorted(self.est):
            e = self.est[h]
            role = e.get("role", "both")
            if stage == "prefill" and role == "decode":
                continue
            if stage == "decode" and role == "prefill":
                continue
            if stage == "prefill" and role == "prefill":
                dtype = e.get("kv_dtype", "bf16")
                if self._pick_decode_host(dtype) is None:
                    self._reject_place(item, h, dtype)
                    continue
            key = (e["slots"] > 0, depths.get(h, 0), e["blocks"])
            if best is None or key > best[0]:
                best = (key, h)
        return best[1] if best else None

    def _affinity_depths(self, item: dict) -> Dict[str, int]:
        """Per-host affinity depth (whole blocks of this item's prompt
        resident in trains that host published or fetched), from one
        fold of the fleet store journal. Empty when no store is wired
        — the placement key then degrades to the classic
        (free slot, free blocks) pair."""
        if self.kv_store is None:
            return {}
        depths: Dict[str, int] = {}
        prompt = list(item["prompt"]) + list(item.get("committed", ()))[:-1]
        cache: Dict[int, Dict[str, int]] = {}
        for h, e in self.est.items():
            bs = e["block_size"]
            if bs not in cache:
                cache[bs] = self.kv_store.affinity(chain_hashes(prompt, bs))
            if h in cache[bs]:
                depths[h] = cache[bs][h]
        return depths

    def _pick_decode_host(self, kv_dtype: Optional[str] = None
                          ) -> Optional[str]:
        """The decode-capable live host with the most estimated free
        blocks, optionally pinned to a kv-dtype (shipment imports need
        the pool dtypes to match; the replay fallback does not)."""
        best = None
        for h in sorted(self.est):
            e = self.est[h]
            if e.get("role", "both") not in ("both", "decode"):
                continue
            if (kv_dtype is not None
                    and e.get("kv_dtype", "bf16") != kv_dtype):
                continue
            key = (e["slots"] > 0, e["blocks"])
            if best is None or key > best[0]:
                best = (key, h)
        return best[1] if best else None

    def _reject_place(self, item: dict, host: str, dtype: str) -> None:
        key = (item["id"], host)
        if key in self._place_rejected:
            return
        self._place_rejected.add(key)
        _M_PLACE_REJECTED.inc()
        events.emit_audit(
            logger, AUDIT_DISAGG_PLACE_FMT.format(
                action="reject", id=item["id"], gen=item["gen"],
                detail=f"prefill host {host} pools kv_dtype {dtype} but "
                       f"no {dtype} decode-capable peer is live — "
                       f"mixed-dtype pair refused before prefill"),
            "disagg_place", id=item["id"], gen=item["gen"],
            action="reject", host=host, kv_dtype=dtype)

    def _charge(self, host: str, item: dict) -> None:
        e = self.est.get(host)
        if e is None:
            return
        e["slots"] = max(0, e["slots"] - 1)
        e["blocks"] = max(
            0, e["blocks"] - self._blocks_needed(item, e["block_size"]))

    # -------------------------------------------------------------- migration
    def _item_from_state(self, st: RequestState, src: str) -> dict:
        # A handoff artifact rides along only while it is CURRENT: the
        # drain writes it at gen N and the paired requeue at N+1, so a
        # later re-admission (gen >= N+2) means some survivor already
        # consumed or outran the artifact — ship nothing, replay instead.
        handoff = (st.handoff_artifact
                   if st.handoff_artifact and st.handoff_gen >= st.gen - 1
                   else "")
        return {"id": st.request_id, "prompt": list(st.prompt),
                "max_new_tokens": st.max_new_tokens,
                "temperature": st.temperature, "top_p": st.top_p,
                "seed": st.seed, "committed": list(st.committed),
                "gen": st.gen, "src": src, "trace_id": st.trace_id,
                "handoff": handoff, "enqueued": self.clock()}

    def _verify_handoff(self, item: dict) -> str:
        """CRC-verify the handoff artifact attached to a migration before
        naming it in the migrate record. Transient read errors (the
        drain's filesystem may lag the journal) are retried with backoff;
        a CRC/size/torn-manifest failure is TERMINAL — the manifest was
        fsynced before the journal record, so a bad byte is corruption,
        not a race. Returns the artifact dir, or '' to degrade the
        migration to committed-prefix replay."""
        art = str(item.get("handoff", "") or "")
        if not art:
            return ""

        def _verify_once():
            try:
                return verify_block_artifact(art)
            except KVBlockIntegrityError as e:
                if isinstance(e.__cause__, OSError):
                    raise e.__cause__  # transient read error: retryable
                raise

        try:
            manifest = retry_with_backoff(
                _verify_once, deadline_seconds=1.0, retry_on=(OSError,),
                clock=time.monotonic, sleep=time.sleep,
                what=f"handoff artifact read {art}")
        except (KVBlockIntegrityError, RetryDeadlineExceeded) as e:
            _M_HANDOFF_REJECTED.inc()
            events.emit_audit(
                logger, AUDIT_HANDOFF_FMT.format(
                    action="reject", id=item["id"], gen=item["gen"] + 1,
                    blocks=0, detail=str(e)),
                "handoff", id=item["id"], gen=item["gen"] + 1,
                action="reject", artifact=art, detail=str(e))
            return ""
        events.emit_audit(
            logger, AUDIT_HANDOFF_FMT.format(
                action="ship", id=item["id"], gen=item["gen"] + 1,
                blocks=len(manifest.get("blocks", [])),
                detail=f"artifact {os.path.basename(art)} verified"),
            "handoff", id=item["id"], gen=item["gen"] + 1, action="ship",
            blocks=len(manifest.get("blocks", [])), artifact=art)
        return art

    def _admit(self, item: dict, dst: str) -> None:
        """Journal one admission: a fresh ``assign`` at gen 0, or a
        ``migrate`` at gen+1 for anything carrying history."""
        rid = item["id"]
        trace_id = str(item.get("trace_id", "") or "")
        wait = self.clock() - item.get("enqueued", self.clock())
        if item["gen"] == 0 and item["src"] is None:
            self.journal.assign(rid, dst, item["prompt"],
                                item["max_new_tokens"], item["temperature"],
                                item["top_p"], item["seed"],
                                trace_id=trace_id)
            self.assigned[rid] = (dst, 0)
            if trace_id:
                reqtrace.emit(trace_id, rid, "queue", dur=max(wait, 0.0),
                              where="router")
                reqtrace.emit(trace_id, rid, "placement", host=dst, gen=0)
        else:
            gen = item["gen"] + 1
            handoff = self._verify_handoff(item)
            self.journal.migrate(rid, item["src"], dst, gen,
                                 item["prompt"], item["max_new_tokens"],
                                 item["temperature"], item["top_p"],
                                 item["seed"], item["committed"],
                                 trace_id=trace_id, handoff=handoff)
            self.assigned[rid] = (dst, gen)
            self.migrated_total += 1
            _M_MIGRATED.inc()
            events.emit_audit(
                logger, AUDIT_FLEET_MIGRATE_FMT.format(
                    id=rid, src=item["src"], dst=dst, gen=gen,
                    committed=len(item["committed"])),
                "fleet_migrate", id=rid, src=item["src"], dst=dst,
                gen=gen, committed=len(item["committed"]))
            if trace_id:
                reqtrace.emit(trace_id, rid, "migration", src=item["src"],
                              dst=dst, gen=gen,
                              replayed=len(item["committed"]))
        self._charge(dst, item)

    # ------------------------------------------- disaggregated decode handoff
    def _verify_shipments(self, st: RequestState) -> list:
        """CRC-verify every incremental shipment of the newest
        generation, in seq order. ALL-OR-NOTHING: one rejected artifact
        drops the whole list (returns []), because the decode admission
        needs contiguous coverage of the effective prompt — a hole means
        replaying anyway, and mixing verified blocks with a replay buys
        nothing. Same retry/terminal split as :meth:`_verify_handoff`.
        The router sits across a process boundary, so it always verifies
        the fs form — the artifact path is the handle on every transport
        lane, and the exporter's mem push (if any) is invisible here;
        the journaled ``lane`` rides through for the decode host's own
        ladder and the audit trail."""
        if st.ship_gen != st.prefill_gen or not st.shipments:
            return []
        ships = sorted(st.shipments, key=lambda s: int(s.get("seq", 0)))
        for s in ships:
            art = str(s.get("artifact", "") or "")

            def _verify_once(art=art):
                try:
                    return verify_block_artifact(art)
                except KVBlockIntegrityError as e:
                    if isinstance(e.__cause__, OSError):
                        raise e.__cause__
                    raise

            try:
                retry_with_backoff(
                    _verify_once, deadline_seconds=1.0,
                    retry_on=(OSError,), clock=time.monotonic,
                    sleep=time.sleep,
                    what=f"shipment artifact read {art}")
            except (KVBlockIntegrityError, RetryDeadlineExceeded) as e:
                _M_SHIP_REJECTED.inc()
                lane = str(s.get("lane", "fs") or "fs")
                events.emit_audit(
                    logger, AUDIT_DISAGG_SHIP_FMT.format(
                        action="reject", id=st.request_id,
                        seq=int(s.get("seq", 0)), gen=st.gen + 1,
                        start=int(s.get("start_block", 0)),
                        end=int(s.get("end_block", 0)),
                        detail=f"lane {lane}: {e}"),
                    "disagg_ship", id=st.request_id,
                    seq=int(s.get("seq", 0)), gen=st.gen + 1,
                    action="reject", artifact=art, lane=lane,
                    detail=str(e))
                return []
        return ships

    def advance_prefilled(self) -> int:
        """Place the decode half of every request whose prefill-role host
        journaled ``prefill_done``: verify the shipments, pick a
        dtype-matching decode-capable host, and write the ``decode``
        record at gen+1 (ownership transfer — the prefill host is done
        with it whether it lives or dies). Returns placements written.

        Degradations, in order: a rejected shipment ships nothing (the
        decode host replays the committed prefix bit-exactly); verified
        shipments with no dtype-matching decode host also ship nothing
        (any decode-capable host can replay); no decode-capable host at
        all leaves the request waiting for the next sweep to find one."""
        n = 0
        for st in fold(self.journal_dir).values():
            if st.done or not st.prefill_done or st.gen > st.prefill_gen:
                continue
            if st.request_id in self.pending_ids:
                continue
            a = self.assigned.get(st.request_id)
            if a is not None and a[1] > st.gen:
                continue
            gen = st.gen + 1
            if len(st.committed) >= st.max_new_tokens:
                # max_new_tokens == 1: the sampled first token IS the
                # whole stream — complete in place, no decode half
                self.journal.done(st.request_id, "router", st.committed,
                                  "length", gen=gen,
                                  trace_id=st.trace_id)
                self.assigned[st.request_id] = ("router", gen)
                continue
            dtype = st.kv_dtype or "bf16"
            ships = self._verify_shipments(st)
            dst = self._pick_decode_host(dtype if ships else None)
            if dst is None and ships:
                events.emit_audit(
                    logger, AUDIT_DISAGG_PLACE_FMT.format(
                        action="replay", id=st.request_id, gen=gen,
                        detail=f"no {dtype} decode-capable host for "
                               f"{len(ships)} verified shipment(s); "
                               f"falling back to committed-prefix "
                               f"replay"),
                    "disagg_place", id=st.request_id, gen=gen,
                    action="replay", kv_dtype=dtype)
                ships = []
                dst = self._pick_decode_host(None)
            if dst is None:
                continue  # no decode capacity yet — retry next loop
            self.journal.decode(st.request_id, st.host or "", dst, gen,
                                list(st.prompt), st.max_new_tokens,
                                st.temperature, st.top_p, st.seed,
                                list(st.committed), shipments=ships,
                                trace_id=st.trace_id)
            self.assigned[st.request_id] = (dst, gen)
            self.decode_placed_total += 1
            _M_DECODE_PLACED.inc()
            events.emit_audit(
                logger, AUDIT_DISAGG_PLACE_FMT.format(
                    action="decode", id=st.request_id, gen=gen,
                    detail=f"{st.host or '?'} -> {dst}, "
                           f"{len(ships)} shipment(s), kv_dtype {dtype}"),
                "disagg_place", id=st.request_id, gen=gen,
                action="decode", src=st.host, dst=dst,
                shipments=len(ships), kv_dtype=dtype)
            if st.trace_id:
                reqtrace.emit(st.trace_id, st.request_id,
                              "decode_placement", src=st.host, dst=dst,
                              gen=gen, shipments=len(ships))
            self._charge(dst, {"prompt": st.prompt,
                               "max_new_tokens": st.max_new_tokens})
            n += 1
        return n

    def sweep(self, now: Optional[float] = None) -> int:
        """Render dead verdicts and migrate the victims' in-flight
        requests. Returns how many requests were queued for migration."""
        leases, tombs, live = self.refresh(now)
        moved = 0
        for h in sorted(leases):
            l = leases[h]
            if h in self.handled_dead or (l.live and h not in tombs):
                continue
            # fence FIRST: after the tombstone a zombie that wakes up
            # late self-fences instead of double-committing (ft/lease.py)
            self.lease.tombstone(h)
            states = fold(self.journal_dir)
            inflight = sorted(
                (st for st in states.values()
                 if st.host == h and not st.done
                 # a prefill-done request is NOT lost with its prefill
                 # host: the shipments live on shared disk and
                 # advance_prefilled() still owns the decode placement
                 # (verified import, or replay if an artifact is bad)
                 and not (st.prefill_done and st.gen <= st.prefill_gen)),
                key=lambda st: st.request_id)
            events.emit_audit(
                logger, AUDIT_FLEET_DEAD_FMT.format(
                    host=h, age=l.age, ttl=l.ttl, inflight=len(inflight)),
                "fleet_dead", host=h, age=l.age, ttl=l.ttl,
                inflight=len(inflight))
            for st in inflight:
                if len(st.committed) >= st.max_new_tokens:
                    # the journal already holds the full stream — nothing
                    # to decode; the router completes it in place
                    self.journal.done(st.request_id, "router",
                                      st.committed, "length",
                                      gen=st.gen + 1,
                                      trace_id=st.trace_id)
                    continue
                item = self._item_from_state(st, src=h)
                if st.request_id not in self.pending_ids:
                    self.pending.append(item)
                    self.pending_ids.add(st.request_id)
                    moved += 1
            self.handled_dead.add(h)
        return moved

    def adopt_requeued(self) -> int:
        """Queue ``requeue`` records from draining hosts/servers for
        re-admission (idempotent across loops via the assigned map)."""
        n = 0
        for st in fold(self.journal_dir).values():
            if st.done or not st.requeued:
                continue
            if st.request_id in self.pending_ids:
                continue
            a = self.assigned.get(st.request_id)
            if a is not None and a[1] >= st.gen:
                continue  # my later (re-)admission already outranks it
            self.pending.append(
                self._item_from_state(st, src=st.host or "requeue"))
            self.pending_ids.add(st.request_id)
            n += 1
        return n

    def assign_pending(self) -> int:
        """Hand queued requests to hosts; stops when no live host is
        available (they stay queued for the next loop)."""
        n = 0
        while self.pending:
            dst = self.pick_host(self.pending[0])
            if dst is None:
                break
            item = self.pending.popleft()
            self.pending_ids.discard(item["id"])
            self._admit(item, dst)
            n += 1
        return n

    # -------------------------------------------------------------- liveness
    def status(self, expected: int):
        """(done_count, total_known, all_done): the zero-lost check is
        ``all_done`` — every request the journal has ever seen is done."""
        states = fold(self.journal_dir)
        done = sum(1 for st in states.values() if st.done)
        total = len(states) + len(self.pending)
        all_done = (not self.pending and total >= expected
                    and all(st.done for st in states.values()))
        return done, total, all_done


class _IntakeFollower:
    """Tail the intake JSONL for new requests (text prompts); the same
    complete-lines-only byte-offset discipline as serve.py."""

    def __init__(self, path: str, tokenizer, args):
        self.path = path
        self.tokenizer = tokenizer
        self.args = args
        self.offset = 0
        self.count = 0

    def ingest(self, router: Router) -> int:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        if size <= self.offset:
            return 0
        with open(self.path, "rb") as fh:
            fh.seek(self.offset)
            data = fh.read()
        end = data.rfind(b"\n")
        if end < 0:
            return 0
        chunk = data[:end + 1]
        self.offset += len(chunk)
        n = 0
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                prompt = self.tokenizer.encode(str(d["prompt"]))
            except (ValueError, KeyError, TypeError):
                logger.warning(f"[ROUTER] skipping malformed intake line "
                               f"{line!r}")
                continue
            rid = str(d.get("id", f"req{self.count}"))
            self.count += 1
            if router.submit(
                    rid, prompt,
                    int(d.get("max_new_tokens", self.args.max_new_tokens)),
                    float(d.get("temperature", self.args.temperature)),
                    float(d.get("top_p", self.args.top_p)),
                    int(d.get("seed", self.args.seed + self.count)),
                    trace_id=str(d.get("trace_id", "") or "")):
                n += 1
        return n


def get_router_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="fault_tolerant_llm_training_tpu.inference.router",
        description="Fleet router: admit intake requests to lease-live "
                    "hosts and migrate in-flight work off dead ones.")
    p.add_argument("--store", required=True,
                   help="shared KV-store directory (leases + tombstones)")
    p.add_argument("--journal-dir", required=True,
                   help="shared request-journal directory")
    p.add_argument("--intake", required=True,
                   help="JSONL file tailed for requests "
                        "({'id','prompt',...} per line, text prompts)")
    p.add_argument("--expected", type=int, required=True,
                   help="exit once this many requests have been ingested "
                        "AND every journaled request is done")
    p.add_argument("--kv-deadline", type=float, default=1.0,
                   help="bounded retry deadline per KV-store operation")
    p.add_argument("--kv-store-dir", default="",
                   help="fleet-global KV block store root "
                        "(inference/kvstore.py); when set, intake "
                        "placement prefers the host whose published "
                        "trains cover the longest prefix of the prompt")
    p.add_argument("--tokenizer-name-or-path", default="byte")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--poll-seconds", type=float, default=0.1)
    p.add_argument("--max-seconds", type=float, default=300.0,
                   help="safety timeout: exit 1 if the fleet has not "
                        "finished by then")
    p.add_argument("--metrics-port", type=int, default=0)
    p.add_argument("--event-log", default="")
    p.add_argument("--trace-log", default="",
                   help="request-span JSONL (obs/reqtrace.py); defaults "
                        "to trace_<name>.jsonl next to --event-log")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = get_router_args(argv)
    init_logger()
    if args.event_log:
        events.configure(args.event_log, job="router", host=os.getpid())
    trace_log = args.trace_log or (
        reqtrace.derive_trace_path(args.event_log) if args.event_log
        else "")
    if trace_log:
        reqtrace.configure(trace_log, job="router", host="router")
    metrics_server = None
    if args.metrics_port:
        metrics_server = MetricsServer(port=args.metrics_port)
        port = metrics_server.start()
        logger.info(f"Metrics | serving /metrics on port {port}")
    tokenizer = load_tokenizer(args.tokenizer_name_or_path)
    store = FileKVStore(args.store)
    router = Router(store, args.journal_dir,
                    deadline_seconds=args.kv_deadline,
                    kv_store_dir=args.kv_store_dir)
    follower = _IntakeFollower(args.intake, tokenizer, args)
    logger.info("Fleet router | store=%s journal=%s expecting %d "
                "request(s)", args.store, args.journal_dir, args.expected)

    t0 = time.monotonic()
    rc = 0
    while True:
        follower.ingest(router)
        router.sweep()
        router.advance_prefilled()
        router.adopt_requeued()
        router.assign_pending()
        done, total, all_done = router.status(args.expected)
        if all_done and follower.count >= args.expected:
            break
        if time.monotonic() - t0 > args.max_seconds:
            logger.error(
                "[ROUTER] timed out: %d/%d done, %d pending", done, total,
                len(router.pending))
            rc = 1
            break
        time.sleep(args.poll_seconds)

    done, total, _ = router.status(args.expected)
    lost = total - done
    logger.info("Fleet router complete: %d request(s) done, %d migrated, "
                "%d lost", done, router.migrated_total, lost)
    events.flush()
    reqtrace.flush()
    if metrics_server is not None:
        metrics_server.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
