"""Fleet serving host: one member of a multi-host serving fleet.

``python -m fault_tolerant_llm_training_tpu.inference.fleet`` runs ONE
engine+scheduler process that (a) registers a heartbeat lease with
capacity metadata in the shared KV store (ft/lease.py) and renews it
every loop iteration, (b) tails the router's journal file
(inference/journal.py) for ``assign``/``migrate`` records addressed to
it and submits them to the continuous-batching scheduler, and (c)
journals its own ``progress`` records (the FULL committed token list) at
every decode-round boundary plus a ``done`` record per completion — the
replayable trail the router migrates from when this host dies.

Migrated requests arrive with a non-empty ``committed`` baseline: the
scheduler replays ``prompt + committed[:-1]`` as the prefill (cheap under
the prefix cache), seeds the slot with the committed stream, and the
``fold_in(seed, step)`` PRNG makes the continuation bit-identical to the
stream the dead host would have produced (scheduler.py `_Slot`).

Death and fencing (the split-brain contract, ft/lease.py docstring):

- A SIGKILL (chaos ``host_kill``) leaves no handler, no drain — the
  lease simply stops renewing, the router's sweep renders the dead
  verdict and tombstones BEFORE migrating.
- The host self-fences when it cannot prove its own lease live
  (tombstoned, or ttl elapsed since its last successful renewal): it
  exits WITHOUT another journal write, so a zombie that stalled past its
  ttl (chaos ``heartbeat_delay`` > ttl) can never double-commit against
  the migrated replica.
- A signal drain (SIGUSR1/SIGTERM) finishes in-flight requests, then
  persists anything still queued as ``requeue`` records and runs the
  KV-block leak guard — the campaign pins "Fleet drain leak guard:
  clean" on every survivor.

With ``--handoff`` a signal drain SHIPS its in-flight requests instead of
finishing them: each active slot's committed KV blocks are exported as a
checksummed artifact next to the journal (scheduler ``export_handoff``), a
``handoff`` journal record points at it, and the request is requeued with
its committed baseline. The router then migrates by block import on the
survivor when the artifact CRC-verifies, and by the ordinary
committed-prefix replay when it is missing, torn, or rejected — a SIGKILL
leaves no artifact and naturally takes the replay path, so the handoff
fast path adds no new way to lose a request.

``--role prefill|decode`` splits the host into one side of the
disaggregated pipeline (DistServe/Splitwise over the artifact path): a
prefill host admits ``assign``/``migrate`` records, exports each committed
chunk's blocks as an incremental shipment (``ship`` journal records, chaos
``ship_corrupt`` keyed by export ordinal) and journals ``prefill_done``; a
decode host admits the router's ``decode`` records, imports the verified
shipments into its own pool (prefix-cache-deduped) and decodes bit-exactly
from the committed offset. The role and the pool's kv-dtype ride in the
heartbeat lease, so the router places by role and rejects mixed-dtype
prefill->decode pairs at placement time. Death of either side is the
ordinary fence/migrate machinery; a rejected or stale shipment degrades to
the committed-prefix replay on whatever host holds the request.
"""

import argparse
import json
import os
import sys
import threading
import time

from ..chaos import FLEET_FAULTS, ChaosInjector, parse_schedule
from ..data.tokenizer import load_tokenizer
from ..ft.lease import FileKVStore, LeaseRegistry
from ..ft.retry import RetryDeadlineExceeded, retry_with_backoff
from ..ft.signals import SignalFlag
from ..models.configs import get_config
from ..obs import events, reqtrace
from ..obs.prometheus import MetricsServer
from ..obs.registry import REGISTRY
from ..utils.logging import (
    AUDIT_ADAPTER_SUMMARY_FMT,
    AUDIT_FLEET_JOIN_FMT,
    AUDIT_FLEET_LEAVE_FMT,
    AUDIT_KV_QUANT_FMT,
    AUDIT_KV_XPORT_FMT,
    AUDIT_KV_STORE_FMT,
    AUDIT_LATENCY_FMT,
    AUDIT_REQUEST_DONE_FMT,
    AUDIT_SERVE_DRAINING_FMT,
    AUDIT_SERVE_READY_FMT,
    init_logger,
    logger,
)
from .engine import (
    DEFAULT_COMPILE_CACHE_DIR,
    InferenceEngine,
    enable_compilation_cache,
)
from .journal import RequestJournal, persist_unserved
from .kv_cache import bf16_block_bytes, block_bytes
from .kvstore import BlockStore, run_sweeper
from .scheduler import Request, Scheduler
from .transport import make_transport, resolve_lane

ROUTER_JOURNAL = "router.jsonl"

_M_ENGINE_ROLE = REGISTRY.gauge(
    "engine_role",
    "Disaggregated serving role as an info label "
    "(engine_role{engine_role=...} 1)")
_M_KV_TRANSPORT = REGISTRY.gauge(
    "kv_transport_lane",
    "Resolved KV transport lane as an info label "
    "(kv_transport_lane{lane=...} 1): the lane this process exports "
    "block trains on after same-pod auto-detect")


class _AssignmentFollower:
    """Tail ``router.jsonl`` for assign/migrate records addressed to this
    host. Byte-offset tracking, complete (newline-terminated) lines only —
    the same torn-read discipline as serve.py's request follower."""

    def __init__(self, journal_dir: str, host_id: str,
                 read_deadline: float = 0.5):
        self.path = os.path.join(journal_dir, ROUTER_JOURNAL)
        self.host_id = host_id
        self.offset = 0
        self.read_deadline = read_deadline

    def _read_tail(self) -> bytes:
        with open(self.path, "rb") as fh:
            fh.seek(self.offset)
            return fh.read()

    def poll(self):
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []  # router not started yet — normal, don't retry
        if size <= self.offset:
            return []
        try:
            # the file exists and has new bytes: a read failure here is
            # transient (ft/retry.py backoff), not a missing journal
            data = retry_with_backoff(self._read_tail,
                                      deadline_seconds=self.read_deadline,
                                      retry_on=(OSError,),
                                      what="router journal read")
        except RetryDeadlineExceeded:
            return []  # next poll re-reads from the same offset
        end = data.rfind(b"\n")
        if end < 0:
            return []
        chunk = data[:end + 1]
        self.offset += len(chunk)
        out = []
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if (rec.get("kind") in ("assign", "migrate", "decode")
                    and rec.get("host") == self.host_id):
                out.append(rec)
        return out


def get_fleet_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="fault_tolerant_llm_training_tpu.inference.fleet",
        description="One serving-fleet host: heartbeat lease + journal-"
                    "driven request intake with migration replay.")
    p.add_argument("--host-id", required=True,
                   help="this host's fleet identity (lease + journal key)")
    p.add_argument("--store", required=True,
                   help="shared KV-store directory (leases + tombstones)")
    p.add_argument("--journal-dir", required=True,
                   help="shared request-journal directory")
    p.add_argument("--lease-ttl", type=float, default=2.0,
                   help="heartbeat lease ttl in seconds: miss renewals for "
                        "longer and the router declares this host dead")
    p.add_argument("--kv-deadline", type=float, default=1.0,
                   help="bounded retry deadline per KV-store operation")
    p.add_argument("--checkpoint-path", required=True)
    p.add_argument("--checkpoint-job-id", required=True)
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--model", default="tiny")
    p.add_argument("--vocab-size", type=int, default=0)
    p.add_argument("--tokenizer-name-or-path", default="byte")
    p.add_argument("--layer-impl", default="loop", choices=("loop", "scan"))
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--max-len", type=int, default=0)
    p.add_argument("--prefill-buckets", default="",
                   help="comma-separated AOT prefill lengths (default: "
                        "power-of-two ladder); the largest bucket is the "
                        "prefill CHUNK size, so a prefill-role host ships "
                        "one incremental block artifact per largest-"
                        "bucket's worth of committed prompt")
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--kv-num-blocks", type=int, default=0)
    p.add_argument("--kv-dtype", default="bf16",
                   choices=("bf16", "int8"),
                   help="paged KV pool storage dtype (serve.py "
                        "--kv-dtype): int8 stores blocks quantized with "
                        "per-(block, kv-head) scales, ~2x blocks at the "
                        "same HBM. Handoff/spill artifacts carry the "
                        "scales inside the CRC'd payload, so migration "
                        "stays bit-exact within the dtype — but every "
                        "fleet host must run the SAME kv-dtype: an "
                        "artifact exported under one dtype is geometry-"
                        "rejected by the other and the migration falls "
                        "back to the committed-prefix replay")
    p.add_argument("--paged-kernel", default="gather",
                   choices=("gather", "pallas"))
    p.add_argument("--adapter-rank", type=int, default=0,
                   help="multi-tenant LoRA serving rank (serve.py "
                        "--adapter-rank); 0 = off. Every fleet host must "
                        "run the same rank or migrated adapter streams "
                        "land on a host that cannot serve them")
    p.add_argument("--adapter-pages", type=int, default=0,
                   help="adapter page pool size incl. the null page "
                        "(serve.py --adapter-pages); 0 = room for 4")
    p.add_argument("--adapter", action="append", default=[],
                   metavar="NAME=DIR", dest="adapters",
                   help="register a published adapter artifact at startup "
                        "(repeatable, serve.py --adapter)")
    p.add_argument("--compile-cache-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-eos", action="store_true")
    p.add_argument("--log-frequency", type=int, default=8)
    p.add_argument("--poll-seconds", type=float, default=0.05,
                   help="idle sleep between loop iterations with no work")
    p.add_argument("--max-run-seconds", type=float, default=0.0,
                   help="safety timeout: drain and exit after this long "
                        "(0 = run until signaled)")
    p.add_argument("--metrics-port", type=int, default=0)
    p.add_argument("--event-log", default="")
    p.add_argument("--trace-log", default="",
                   help="request-span trail (obs/reqtrace.py); defaults "
                        "to trace_<name>.jsonl next to --event-log")
    p.add_argument("--chaos", default="",
                   help="fault schedule: host_kill / sigusr1 / sigterm "
                        "keyed by decode iteration (serve.py convention); "
                        "heartbeat_delay keyed by fleet loop iteration; "
                        "handoff_corrupt / spill_corrupt / ship_corrupt / "
                        "store_corrupt keyed by export ordinal; "
                        "prefill_kill keyed by completed-prefill-chunk "
                        "ordinal")
    p.add_argument("--handoff", action="store_true",
                   help="on a signal drain, ship in-flight requests' "
                        "committed KV blocks as checksummed artifacts "
                        "(journal 'handoff' records) instead of finishing "
                        "them; survivors import the blocks, or replay the "
                        "committed prefix if the artifact fails CRC")
    p.add_argument("--spill-dir", default="",
                   help="enable the scheduler's spill tier: on pool "
                        "exhaustion, preempt the coldest request's blocks "
                        "into checksummed artifacts under this directory "
                        "and restore on demand")
    p.add_argument("--kv-store-dir", default="",
                   help="fleet-global KV block store root "
                        "(inference/kvstore.py): publish every finished "
                        "prefill's full-block KV train as a checksummed, "
                        "content-addressed artifact and fetch the deepest "
                        "published prefix before each local prefill; a "
                        "CRC reject or miss degrades to the ordinary "
                        "local chunked prefill")
    p.add_argument("--kv-store-max-bytes", type=int, default=0,
                   help="fleet-store byte budget: > 0 starts the in-"
                        "process sweeper daemon (lease-elected leader "
                        "LRU-evicts down to the budget) AND applies "
                        "publish backpressure — publishers skip store "
                        "publishes (kv_store_publish_skipped_total) "
                        "while resident bytes exceed the budget; 0 = "
                        "unbounded, no sweeper")
    p.add_argument("--kv-store-sweep-interval", type=float, default=2.0,
                   help="seconds between sweeper daemon rounds "
                        "(--kv-store-max-bytes > 0)")
    p.add_argument("--kv-transport", default="fs", choices=("fs", "mem"),
                   help="requested KV block-train transport lane "
                        "(inference/transport.py). Fleet peers are "
                        "separate OS processes with no shared fabric, so "
                        "'mem' auto-detects down to 'fs' here (with a "
                        "log line); the in-process transport drills "
                        "(decode_bench/chaos_campaign 'transport') are "
                        "where the mem lane actually engages")
    p.add_argument("--role", default="both",
                   choices=("both", "prefill", "decode"),
                   help="disaggregated pipeline role: 'prefill' admits "
                        "assign/migrate records, ships each committed "
                        "chunk's KV blocks as CRC'd artifacts and journals "
                        "prefill_done; 'decode' admits the router's "
                        "'decode' records and imports the verified "
                        "shipments before decoding bit-exactly from the "
                        "committed offset; 'both' (default) is the "
                        "colocated host")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = get_fleet_args(argv)
    init_logger()
    flag = SignalFlag()
    flag.register()
    chaos = None
    if args.chaos:
        chaos = ChaosInjector(
            parse_schedule(args.chaos, allowed=FLEET_FAULTS),
            seed=args.seed)
        logger.info(f"Chaos schedule | {chaos.describe()}")
    if args.event_log:
        events.configure(args.event_log, job=f"fleet_{args.host_id}",
                         host=os.getpid())
    trace_log = args.trace_log or (
        reqtrace.derive_trace_path(args.event_log) if args.event_log
        else "")
    if trace_log:
        reqtrace.configure(trace_log, job=f"fleet_{args.host_id}",
                           host=args.host_id)
    metrics_server = None
    bound_metrics_port = 0
    if args.metrics_port:
        metrics_server = MetricsServer(port=args.metrics_port)
        # the BOUND port (not the requested one: port 0 = ephemeral)
        # rides in the lease value so the federation aggregator can
        # discover scrape targets from the lease sweep alone
        bound_metrics_port = metrics_server.start()
        logger.info(f"Metrics | serving /metrics on port "
                    f"{bound_metrics_port}")

    with flag.deferred():  # block delivery across compile + Orbax restore
        cache_dir = (DEFAULT_COMPILE_CACHE_DIR
                     if args.compile_cache_dir is None
                     else args.compile_cache_dir)
        if enable_compilation_cache(cache_dir):
            logger.info(f"Compilation cache | {cache_dir}")
        tokenizer = load_tokenizer(args.tokenizer_name_or_path)
        vocab = args.vocab_size or tokenizer.vocab_size
        cfg = get_config(args.model, vocab_size=vocab,
                         layer_impl=args.layer_impl)
        buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
                   if args.prefill_buckets else None)
        engine = InferenceEngine.from_checkpoint(
            args.checkpoint_path, args.checkpoint_job_id, cfg,
            step=args.step, slots=args.slots,
            max_len=args.max_len or None, prefill_buckets=buckets,
            kv_layout="paged",
            kv_block_size=args.kv_block_size,
            kv_num_blocks=args.kv_num_blocks or None,
            paged_kernel=args.paged_kernel,
            kv_dtype=args.kv_dtype,
            adapter_rank=args.adapter_rank,
            adapter_num_pages=args.adapter_pages)
        if args.adapters:
            if not args.adapter_rank:
                raise SystemExit("--adapter requires --adapter-rank")
            for spec in args.adapters:
                name, sep, art_dir = spec.partition("=")
                if not (sep and name and art_dir):
                    raise SystemExit(f"--adapter expects NAME=DIR, "
                                     f"got {spec!r}")
                engine.adapters.register(name, art_dir)
                logger.info("Adapter registered | %s -> %s", name, art_dir)
        events.emit_audit(
            logger, AUDIT_SERVE_READY_FMT.format(
                model=args.model, step=engine.restored_step,
                slots=args.slots),
            "ready", step=engine.restored_step, slots=args.slots,
            model=args.model)
        # Same-pod auto-detect: every consumer of a fleet host's exports
        # (the router, survivors, its decode peer) is ANOTHER OS process,
        # and the mem fabric is process-local — a requested mem lane
        # degrades to fs here, by construction rather than by failure.
        lane = resolve_lane(args.kv_transport, colocated=False)
        if lane != args.kv_transport:
            # auditable, not just a log line: the degradation rides the
            # same [KV XPORT] contract + fallback counter the scheduler's
            # per-shipment mem->fs misses use, so a fleet that silently
            # lost its fast lane shows up in both the audit grep and the
            # /metrics rollup
            events.emit_audit(
                logger, AUDIT_KV_XPORT_FMT.format(
                    action="degrade", lane=lane, id="-", blocks=0,
                    detail=f"requested {args.kv_transport} lane — fleet "
                           f"peers are separate processes with no shared "
                           f"fabric"),
                "kv_xport", action="degrade", lane=lane,
                requested=args.kv_transport)
            REGISTRY.counter(
                "kv_transport_lane_fallbacks_total",
                "Block-train imports that degraded from the mem lane to "
                "the fs artifact (fabric miss or metadata digest "
                "mismatch)").inc()
        transport = make_transport(lane)
        _M_KV_TRANSPORT.labels(lane=lane).set(1)

        def on_ship(req, art_dir, ordinal, seq, start, end, length):
            # Late-bound over `journal`/`gens` (created right below, before
            # the scheduler can run a prefill). Chaos first (ship_corrupt,
            # keyed by export ordinal) so the journal record always names
            # the artifact in its final — possibly poisoned — state.
            if chaos is not None:
                chaos.on_ship(art_dir, ordinal)
            journal.ship(req.id, args.host_id, art_dir, seq, start, end,
                         length, gens.get(req.id, 0),
                         trace_id=req.trace_id, lane=transport.name)

        def pacing():
            # Decode-fleet landing capacity read off the heartbeat
            # leases: free blocks summed over live decode-capable peers.
            # None (= never stall) when no decode peer is visible — a
            # lone prefill host joining first must not deadlock its own
            # admission on a fleet that has not assembled yet.
            peers = [l for h, l in lease.leases().items()
                     if h != args.host_id and l.live
                     and l.role in ("decode", "both")]
            if not peers:
                return None
            return sum(int(l.blocks_free) for l in peers)

        # writer IS the lease host id: the store journal's residency
        # evidence must key by the same names the router's capacity
        # estimates use, or cache-affinity placement never matches
        kv_store = (BlockStore(args.kv_store_dir, writer=args.host_id)
                    if args.kv_store_dir else None)
        sched = Scheduler(engine,
                          eos_token_id=(None if args.no_eos
                                        else tokenizer.eos_token_id),
                          stop_check=lambda: flag.signum is not None,
                          spill_dir=args.spill_dir or None,
                          on_spill=(chaos.on_spill if chaos is not None
                                    else None),
                          role=args.role,
                          ship_dir=(os.path.join(args.journal_dir,
                                                 f"ships_{args.host_id}")
                                    if args.role == "prefill" else None),
                          on_ship=(on_ship if args.role == "prefill"
                                   else None),
                          on_prefill_chunk=(chaos.on_prefill_chunk
                                            if chaos is not None
                                            else None),
                          kv_store=kv_store,
                          on_store_put=(chaos.on_store_put
                                        if chaos is not None else None),
                          transport=transport,
                          pacing=(pacing if args.role == "prefill"
                                  else None),
                          kv_store_max_bytes=args.kv_store_max_bytes)
    _M_ENGINE_ROLE.labels(engine_role=args.role).set(1)

    store = FileKVStore(args.store)
    lease = LeaseRegistry(store, host_id=args.host_id,
                          ttl_seconds=args.lease_ttl,
                          deadline_seconds=args.kv_deadline)
    journal = RequestJournal(args.journal_dir,
                             writer=f"host_{args.host_id}")
    follower = _AssignmentFollower(args.journal_dir, args.host_id)

    def capacity():
        slots_free = max(0, engine.slots - len(sched.active)
                         - len(sched._pending_prefill) - len(sched.queue))
        blocks_free = (sched.allocator.free_count
                       if sched.kv_layout == "paged" else 0)
        return slots_free, blocks_free, getattr(engine, "block_size", 1)

    slots_free, blocks_free, block_size = capacity()
    lease.register(slots_free, blocks_free, block_size,
                   role=args.role, kv_dtype=engine.kv_dtype,
                   metrics_port=bound_metrics_port)
    events.emit_audit(
        logger, AUDIT_FLEET_JOIN_FMT.format(
            host=args.host_id, slots=slots_free, blocks=blocks_free,
            ttl=lease.ttl),
        "fleet_join", host=args.host_id, slots=slots_free,
        blocks=blocks_free, ttl=lease.ttl)
    events.flush()

    # Fleet-store sweeper daemon: a lease-holding background loop — the
    # lexically-lowest LIVE host (kvstore.sweep_leader over the same
    # heartbeat leases the router reads) LRU-evicts unreferenced trains
    # down to the byte budget; every other host's loop stands down, and
    # leadership follows lease liveness when hosts die or fence. The
    # publish side of the same budget is the scheduler's backpressure
    # skip (kv_store_publish_skipped_total).
    sweeper = None
    sweep_stop = threading.Event()
    if kv_store is not None and args.kv_store_max_bytes > 0:
        def _on_evict(evicted):
            for key in evicted:
                events.emit_audit(
                    logger, AUDIT_KV_STORE_FMT.format(
                        action="sweep", key=key[:12], id="-", blocks=0,
                        detail="fleet LRU eviction (over byte budget)"),
                    "kv_store", action="sweep", key=key,
                    host=args.host_id)

        sweeper = threading.Thread(
            target=run_sweeper,
            args=(kv_store, args.kv_store_max_bytes),
            kwargs=dict(interval=args.kv_store_sweep_interval,
                        stop=sweep_stop.is_set,
                        leases=lease.leases, host_id=args.host_id,
                        on_evict=_on_evict),
            daemon=True, name=f"kvstore-sweeper-{args.host_id}")
        sweeper.start()
        logger.info("Fleet store sweeper | budget %d byte(s), interval "
                    "%.1fs, leader by lease election",
                    args.kv_store_max_bytes,
                    args.kv_store_sweep_interval)

    gens = {}     # rid -> generation of my current/last assignment
    done_ids = set()
    n_done = 0    # consumed prefix of sched.completed
    it = 0
    t0 = time.monotonic()
    exit_reason = None  # None = keep serving; else drain with this reason

    def emit_completions():
        nonlocal n_done
        for c in sched.completed[n_done:]:
            gen = gens.get(c.request_id, 0)
            if c.reason == "prefill":
                # dedicated-prefill completion: the committed stream is
                # ONE token (the first), the KV already shipped — journal
                # prefill_done so the router can place the decode half.
                # No decoded-output print: the request is not finished,
                # the decode host owns the final stream.
                journal.prefill_done(c.request_id, args.host_id, c.tokens,
                                     gen, kv_dtype=engine.kv_dtype,
                                     trace_id=c.trace_id)
                done_ids.add(c.request_id)
                events.emit_audit(
                    logger, AUDIT_REQUEST_DONE_FMT.format(
                        id=c.request_id, reason=c.reason,
                        prompt_tokens=c.prompt_len,
                        new_tokens=len(c.tokens),
                        ttft_ms=c.ttft_seconds * 1e3,
                        tps=c.decode_tokens_per_sec),
                    "request_done", id=c.request_id, reason=c.reason,
                    tokens=len(c.tokens), gen=gen, host=args.host_id)
                continue
            journal.done(c.request_id, args.host_id, c.tokens, c.reason,
                         gen=gen, trace_id=c.trace_id)
            done_ids.add(c.request_id)
            decoded = (c.tokens[:-1]
                       if (not args.no_eos and c.reason == "eos")
                       else c.tokens)
            events.emit_audit(
                logger, AUDIT_REQUEST_DONE_FMT.format(
                    id=c.request_id, reason=c.reason,
                    prompt_tokens=c.prompt_len, new_tokens=len(c.tokens),
                    ttft_ms=c.ttft_seconds * 1e3,
                    tps=c.decode_tokens_per_sec),
                "request_done", id=c.request_id, reason=c.reason,
                tokens=len(c.tokens), gen=gen, host=args.host_id)
            logger.info("Request %s output: %r", c.request_id,
                        tokenizer.decode(decoded))
        n_done = len(sched.completed)

    while exit_reason is None:
        it += 1
        if chaos is not None:
            chaos.on_heartbeat(it)  # heartbeat_delay: a slow-but-alive host
        slots_free, blocks_free, block_size = capacity()
        renewed = lease.renew(slots_free, blocks_free, block_size,
                              role=args.role, kv_dtype=engine.kv_dtype,
                              metrics_port=bound_metrics_port)
        if not renewed or lease.fenced():
            # self-fence: this host can no longer prove its lease live —
            # a migrated replica may already be running, so NO further
            # journal writes (split-brain contract, ft/lease.py)
            events.emit_audit(
                logger, AUDIT_FLEET_LEAVE_FMT.format(
                    host=args.host_id, reason="fenced"),
                "fleet_leave", host=args.host_id, reason="fenced")
            events.flush()
            reqtrace.flush()
            if metrics_server is not None:
                metrics_server.stop()
            sys.exit(0)

        for rec in follower.poll():
            rid = str(rec["id"])
            gen = int(rec.get("gen", 0))
            if rid in done_ids or gens.get(rid, -1) >= gen:
                continue  # stale or duplicate assignment
            gens[rid] = gen
            committed = [int(t) for t in rec.get("committed") or []]
            trace_id = str(rec.get("trace_id", "") or "")
            try:
                sched.submit(Request(
                    id=rid,
                    prompt=[int(t) for t in rec.get("prompt", [])],
                    max_new_tokens=int(rec.get("max_new_tokens", 32)),
                    temperature=float(rec.get("temperature", 0.0)),
                    top_p=float(rec.get("top_p", 1.0)),
                    seed=int(rec.get("seed", 0)),
                    committed=tuple(committed),
                    trace_id=trace_id),
                    # router-verified block-shipment artifact (if any):
                    # admission imports the blocks; any failure falls back
                    # to the committed-prefix replay
                    handoff_artifact=str(rec.get("handoff", "") or ""),
                    handoff_gen=gen,
                    # disaggregated intake: a 'decode' record carries the
                    # prefill host's verified shipment list; admission
                    # imports them (prefix-cache-deduped), or replays the
                    # committed prefix when the list is empty/rejected
                    shipments=rec.get("shipments") or None,
                    ship_gen=gen)
            except ValueError as e:
                logger.warning(f"[FLEET] rejecting assignment {rid}: {e}")
                continue
            if trace_id:
                reqtrace.emit(trace_id, rid, "assign", gen=gen,
                              committed=len(committed),
                              kind=str(rec.get("kind", "assign")))

        if flag.signum is not None:
            exit_reason = "drain"
            break
        if args.max_run_seconds and (time.monotonic() - t0
                                     > args.max_run_seconds):
            logger.warning("[FLEET] max-run-seconds reached; draining")
            exit_reason = "timeout"
            break

        if sched.pending():
            if chaos is not None:
                # host_kill lands here, keyed by decode iteration like
                # serve.py's on_serve_step: SIGKILL mid-decode, no
                # handler, no drain — the router's lease sweep takes it
                # from there. Progress through this round is already
                # journaled, so the migration replays a committed prefix.
                chaos.on_fleet_step(sched.iterations)
            sched.step()
            emit_completions()
            # decode-round boundary: journal the FULL committed stream of
            # every active slot — the baseline a migration replays from
            for st in sched.active.values():
                journal.progress(st.request.id, args.host_id, st.tokens,
                                 gen=gens.get(st.request.id, 0),
                                 trace_id=st.request.trace_id)
            if sched.iterations % args.log_frequency == 0:
                logger.info(
                    "Fleet host %s | iter %d | active %d | queued %d | "
                    "done %d", args.host_id, sched.iterations,
                    len(sched.active), len(sched.queue),
                    len(sched.completed))
        else:
            time.sleep(args.poll_seconds)

    # ---- signal / timeout drain: finish in-flight, requeue the rest ----
    events.emit_audit(
        logger, AUDIT_SERVE_DRAINING_FMT.format(
            signum=flag.signum or 0, active=len(sched.active)),
        "drain", phase="begin", signum=flag.signum,
        active=len(sched.active))
    sched.stop_admission()
    if args.handoff and (sched.active or sched._pending_prefill):
        # Block-shipment drain: instead of finishing in-flight requests,
        # export each active slot's committed blocks as a checksummed
        # artifact next to the journal and record a `handoff` pointer.
        # Mid-prefill rows have no committed KV worth shipping — requeue
        # them first, the ordinary way. The artifact is written and
        # fsynced BEFORE its journal record, so a record always names a
        # complete artifact.
        if sched._pending_prefill:
            sched._abort_pending_prefill()
        n_handoff = 0
        for slot in sorted(sched.active):
            st = sched.active[slot]
            rid = st.request.id
            gen = gens.get(rid, 0)
            art = os.path.join(args.journal_dir,
                               f"handoff_{rid}_g{gen}")
            info = sched.export_handoff(slot, art, gen=gen)
            if chaos is not None:
                # handoff_corrupt: seeded byte flip in a payload (the
                # manifest is spared), keyed by export ordinal — the
                # survivor's CRC verify must reject it and replay
                chaos.on_handoff(art, n_handoff)
            journal.handoff(rid, args.host_id, art, info["tokens"],
                            gen=gen, trace_id=st.request.trace_id)
            n_handoff += 1
    else:
        while sched.active or sched._pending_prefill:
            sched.step()
            emit_completions()
            for st in sched.active.values():
                journal.progress(st.request.id, args.host_id, st.tokens,
                                 gen=gens.get(st.request.id, 0),
                                 trace_id=st.request.trace_id)
    emit_completions()
    persist_unserved(journal, sched.unserved(), reason=exit_reason,
                     gens=gens)
    if sched.enable_spill:
        sched.discard_spilled()
    leaks = sched.audit_block_leaks(strict=False)
    if not leaks:
        logger.info("Fleet drain leak guard: clean")
    else:
        logger.warning("Fleet drain leak guard: %d violation(s)",
                       len(leaks))
    # the --kv-dtype receipt, same line serve.py's drain summary emits
    bpb = block_bytes(engine.cache)
    ratio = bf16_block_bytes(engine.cache) / bpb
    events.emit_audit(
        logger, AUDIT_KV_QUANT_FMT.format(
            dtype=engine.kv_dtype, bytes_per_block=bpb, ratio=ratio,
            blocks_total=engine.num_blocks),
        "kv_quant", dtype=engine.kv_dtype, bytes_per_block=bpb,
        ratio=ratio, blocks_total=engine.num_blocks)
    if sched.adapters is not None:
        # multi-tenant adapter receipt, same line serve.py's drain emits
        am = sched.metrics()
        events.emit_audit(
            logger, AUDIT_ADAPTER_SUMMARY_FMT.format(
                served=am["adapters_served"],
                pageins=am["adapter_pageins"],
                evictions=am["adapter_evictions"],
                resident_bytes=am["adapter_pages_resident_bytes"],
                rejects=am["adapter_rejects"]),
            "adapter_summary", served=am["adapters_served"],
            pageins=am["adapter_pageins"],
            evictions=am["adapter_evictions"],
            resident_bytes=am["adapter_pages_resident_bytes"],
            rejects=am["adapter_rejects"])
    # Per-request latency audit: the drain summary every SLO check greps.
    for c in sched.completed:
        events.emit_audit(
            logger, AUDIT_LATENCY_FMT.format(
                id=c.request_id, trace=c.trace_id or "-",
                ttft_ms=c.ttft_seconds * 1e3,
                tpot_ms=c.tpot_seconds * 1e3,
                tokens=len(c.tokens), reason=c.reason),
            "latency", id=c.request_id, trace=c.trace_id,
            ttft=c.ttft_seconds, tpot=c.tpot_seconds,
            tokens=len(c.tokens), reason=c.reason)
    if sweeper is not None:
        # stop the sweep loop BEFORE the lease leaves: a leaving leader
        # must not race its own liveness test mid-round
        sweep_stop.set()
        sweeper.join(timeout=5.0)
    events.emit_audit(
        logger, AUDIT_FLEET_LEAVE_FMT.format(
            host=args.host_id, reason=exit_reason),
        "fleet_leave", host=args.host_id, reason=exit_reason)
    lease.leave()
    events.flush()
    reqtrace.flush()
    if metrics_server is not None:
        metrics_server.stop()
    # exit 0 always — the exit POLICY is in the logs, same contract as
    # serve.py and training
    sys.exit(0)


if __name__ == "__main__":
    main()
