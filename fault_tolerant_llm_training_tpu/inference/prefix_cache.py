"""Content-addressed prefix cache over the paged KV block pool.

Production traffic is dominated by shared prompt prefixes (system prompts,
few-shot templates, multi-turn history). The paged layout already stores
KV in global pool blocks addressed through per-slot tables
(inference/kv_cache.py) — exactly the substrate vLLM's PagedAttention
assumed and SGLang's RadixAttention built on: if two prompts share their
first k*block_size tokens, their first k blocks hold bitwise-identical KV
(same prefill programs, same shapes, same inputs), so the second request
can point its table at the FIRST request's blocks and skip the prefill
compute for them entirely.

**Keying.** Each fully-committed (block-aligned) prompt block is keyed by a
chain hash ``h_i = sha256(h_{i-1} || tokens of block i)`` — the key of
block i commits the entire token prefix up to and including it, so a flat
``dict`` keyed by chain hash IS a radix tree over token-block paths
(parent = the i-1 prefix, children = every cached one-block extension).
Partial trailing blocks are never cached: a block's bytes are only
reusable once every position in it is committed prompt content.

**Ownership protocol** (the part that must survive drain/eviction/chaos):
the allocator's per-block refcount is the single source of truth.

- The cache holds exactly ONE reference per cached node (taken at
  ``insert``, dropped at ``evict``/``flush``).
- Every slot whose table row contains the block holds one reference:
  fresh blocks are born at refcount 1 by ``alloc``; cache-hit blocks are
  increfed by ``acquire`` at admission. A slot's blocks are released by
  the scheduler's ONE uniform ``allocator.free(slot_blocks)`` at finish /
  drain-rollback — hit or miss, COW or not, every block is freed exactly
  once per holder, and the pool's double-free guard stays load-bearing.
- Eviction (LRU, childless nodes first) only considers nodes whose block
  has refcount 1 — i.e. held by the cache alone. Evicting a node whose
  prefix a live slot still reads would free nothing anyway (the slot's
  reference keeps the block allocated); restricting candidates keeps
  eviction an actual release valve under pool pressure.

The cache itself never touches the device: hits are served by table
indices, and the one device operation sharing requires — copy-on-write
when prefill must resume INSIDE a shared block — lives in the engine
(``InferenceEngine.cow_copy`` over ``kv_cache.copy_kv_block``).
"""

import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence

import numpy as np


def chain_hashes(prompt: Sequence[int], block_size: int) -> List[bytes]:
    """Chain hash per fully-committed prompt block: ``h_i = sha256(h_{i-1}
    || block_i token bytes)`` (int32 little-endian), ``h_{-1} = b""``. The
    trailing partial block (if any) contributes nothing — only bit-reusable
    block contents get keys."""
    ids = np.asarray(prompt, np.int32).reshape(-1)
    out: List[bytes] = []
    h = b""
    for i in range(ids.size // block_size):
        h = hashlib.sha256(
            h + ids[i * block_size:(i + 1) * block_size].tobytes()).digest()
        out.append(h)
    return out


@dataclasses.dataclass
class _Node:
    block: int                 # pool block holding this prefix block's KV
    parent: Optional[bytes]    # chain hash of the one-shorter prefix
    children: int = 0          # cached one-block extensions
    tick: int = 0              # LRU clock (match/insert touch)


@dataclasses.dataclass
class PrefixHit:
    """One admission's lookup result: the longest cached chain-hash walk.

    ``tokens`` is the prompt length the hit covers (``len(blocks) *
    block_size``); ``full`` means the hit covers the ENTIRE prompt — the
    admission still needs the LAST prompt position's logits to sample the
    first token, so prefill resumes at ``prompt_len - 1``, which writes
    inside the final shared block and therefore triggers copy-on-write."""

    keys: List[bytes]
    blocks: List[int]
    tokens: int
    full: bool

    @property
    def depth(self) -> int:
        """Hit depth in whole blocks — the unit the fleet store and the
        router's cache-affinity key compare prefixes in."""
        return len(self.blocks)


class PrefixCache:
    """Host-side radix tree of committed prompt blocks, refcounted through
    the scheduler's :class:`~.scheduler.BlockAllocator` (see module
    docstring for the ownership protocol)."""

    def __init__(self, allocator, block_size: int, evictions_counter=None):
        self.allocator = allocator
        self.block_size = block_size
        self._nodes: Dict[bytes, _Node] = {}
        self._tick = 0
        # admission accounting (kv_prefix_hit_rate is hit_tokens over
        # prompt_tokens: the fraction of admitted prompt positions whose
        # prefill compute the cache absorbed)
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.prompt_tokens = 0
        self.evictions = 0
        self.cow_copies = 0
        self._m_evictions = evictions_counter

    @property
    def cached_blocks(self) -> int:
        return len(self._nodes)

    @property
    def hit_rate(self) -> float:
        return (self.hit_tokens / self.prompt_tokens
                if self.prompt_tokens else 0.0)

    # --- admission-side API (scheduler._admit) -----------------------------

    def match(self, prompt: Sequence[int]) -> PrefixHit:
        """Longest cached prefix of ``prompt``, in whole blocks. Touches the
        LRU tick of every node on the walk but takes NO references —
        ``acquire`` the hit before anything (eviction included) can run."""
        self._tick += 1
        keys: List[bytes] = []
        blocks: List[int] = []
        for key in chain_hashes(prompt, self.block_size):
            node = self._nodes.get(key)
            if node is None:
                break
            node.tick = self._tick
            keys.append(key)
            blocks.append(node.block)
        tokens = len(blocks) * self.block_size
        return PrefixHit(keys=keys, blocks=blocks, tokens=tokens,
                         full=tokens == len(prompt) and tokens > 0)

    def acquire(self, hit: PrefixHit) -> None:
        """Take the admitted slot's reference on every hit block — BEFORE
        any fresh allocation or eviction, so pool-pressure eviction can
        never free the prefix the slot is about to reuse."""
        self.allocator.incref(hit.blocks)

    def insert(self, prompt: Sequence[int], slot_blocks: Sequence[int]
               ) -> int:
        """Cache the fully-committed blocks of a just-prefilled prompt:
        ``slot_blocks[i]`` holds block i's KV. Already-cached keys are
        skipped (their canonical block stays; a COW'd private copy is never
        re-inserted over it). Each NEW node takes the cache's own allocator
        reference. Returns the number of nodes added."""
        added = 0
        parent: Optional[bytes] = None
        self._tick += 1
        for i, key in enumerate(chain_hashes(prompt, self.block_size)):
            node = self._nodes.get(key)
            if node is None:
                block = int(slot_blocks[i])
                self.allocator.incref([block])
                self._nodes[key] = _Node(block=block, parent=parent,
                                         tick=self._tick)
                if parent is not None:
                    self._nodes[parent].children += 1
                added += 1
            else:
                node.tick = self._tick
            parent = key
        return added

    def note_admission(self, skipped_tokens: int, prompt_tokens: int) -> None:
        self.lookups += 1
        self.hits += 1 if skipped_tokens else 0
        self.hit_tokens += skipped_tokens
        self.prompt_tokens += prompt_tokens

    # --- release valve -----------------------------------------------------

    def evict(self, need: int) -> int:
        """Free up to ``need`` blocks by dropping LRU cached prefixes no
        live slot references (allocator refcount 1 == the cache's own).
        Childless nodes only — dropping a leaf may expose its parent as the
        next candidate, so long-dead chains unwind leaf-first. Returns the
        number of blocks actually freed (0 = everything cached is in use)."""
        freed = 0
        while freed < need:
            cands = [(node.tick, key) for key, node in self._nodes.items()
                     if node.children == 0
                     and self.allocator.refcount(node.block) == 1]
            if not cands:
                break
            _, key = min(cands)
            self._drop(key)
            freed += 1
            self.evictions += 1
            if self._m_evictions is not None:
                self._m_evictions.inc()
        return freed

    def flush(self) -> int:
        """Drop every cached prefix (cache references released; blocks a
        live slot still reads stay allocated until that slot finishes).
        Returns the number of nodes dropped. Not counted as eviction —
        this is the explicit reset used by tests and engine resets."""
        n = len(self._nodes)
        for node in self._nodes.values():
            self.allocator.free([node.block])
        self._nodes.clear()
        return n

    def _drop(self, key: bytes) -> None:
        node = self._nodes.pop(key)
        if node.parent is not None and node.parent in self._nodes:
            self._nodes[node.parent].children -= 1
        self.allocator.free([node.block])
