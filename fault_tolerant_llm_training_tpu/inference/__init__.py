"""TPU-native inference & serving subsystem.

Loads any training checkpoint (checkpoint/manager.py cross-topology restore)
and serves it through the trained modules themselves: a static-shape GQA
KV slot cache (kv_cache.py) threaded through ``models/llama.py``'s cached
forward, jitted prefill/decode steps with an AOT-compiled prefill bucket set
(engine.py), per-slot seeded sampling (sampler.py), slot-based continuous
batching (scheduler.py), and a signal-drained lifecycle driver (serve.py)
that reuses the training stack's ``ft/signals.py`` flags and audit-string
logging discipline.

Deliberately import-light: ``models/llama.py`` imports ``kv_cache`` for the
cache write primitive, so this package must not eagerly import the engine
(which imports the models) back.
"""
