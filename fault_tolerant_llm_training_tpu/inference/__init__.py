"""TPU-native inference & serving subsystem.

Loads any training checkpoint (checkpoint/manager.py cross-topology restore)
and serves it through the trained modules themselves: a static-shape GQA KV
cache — block-paged pool + per-slot block tables by default, legacy
per-slot ring buffers behind ``kv_layout="ring"`` (kv_cache.py) — threaded
through ``models/llama.py``'s cached forward, jitted prefill/decode steps
with an AOT-compiled prefill bucket set and chunked prefill for prompts
longer than the largest bucket (engine.py), per-slot seeded sampling
(sampler.py), slot-based continuous batching with block-count admission
(scheduler.py), and a signal-drained lifecycle driver (serve.py) that
reuses the training stack's ``ft/signals.py`` flags and audit-string
logging discipline — including chunk-boundary drain for mid-prompt
signals. The paged attention path gathers blocks into the contiguous
layout and runs the exact ring kernel, so the two layouts bit-match
(tests/test_paged_kv.py).

Deliberately import-light: ``models/llama.py`` imports ``kv_cache`` for the
cache write primitive, so this package must not eagerly import the engine
(which imports the models) back.
"""
