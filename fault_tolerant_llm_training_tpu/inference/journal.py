"""Crash-durable request journal: the fleet's replayable source of truth.

A journal is a DIRECTORY of per-writer JSONL files (``router.jsonl``,
``host_h0.jsonl``, ``serve_1234.jsonl``): each participant appends only to
its own file, so concurrent writers never interleave bytes and a SIGKILL
mid-append can at worst truncate the killer's own last line (torn tails
are skipped at read time). Every append is fsynced — a record that was
journaled survives any process death, which is the property the zero-
lost-requests guarantee stands on.

Record kinds:

- ``assign``   router -> host: request parameters + target host, gen 0.
- ``progress`` host: the FULL committed token list at a decode-round
  boundary (full, not delta — any single record reconstructs the stream).
- ``done``     host: final tokens + finish reason.
- ``migrate``  router: re-admission of a dead host's request on a
  survivor at gen+1; self-contained (carries params + committed baseline)
  so hosts only ever need to tail ``router.jsonl``.
- ``requeue``  a draining host persists requests it will not finish
  (queued, mid-prefill, or in-flight) for later re-admission — the same
  record serves single-host ``serve.py --journal-dir`` drains and fleet
  drains, unifying both on one code path.
- ``handoff``  a draining host exported an in-flight request's committed
  KV blocks as a checksummed artifact (inference/kv_cache.py
  ``export_blocks``) next to the journal. Advisory, NOT ownership: the
  paired ``requeue`` still carries the durable committed baseline, the
  handoff record only tells the router an artifact exists so the
  re-admission can ship blocks instead of replaying the prefix — a
  missing/torn/CRC-rejected artifact degrades to the replay with nothing
  lost.
- ``ship``     a prefill-role host exported one contiguous run of a
  request's committed prompt blocks as a checksummed artifact — the
  incremental block shipments of disaggregated prefill/decode. Advisory
  like ``handoff``: shipments of the newest generation are collected per
  request; a stale/poisoned shipment degrades the decode admission to
  committed-prefix replay.
- ``prefill_done`` a prefill-role host finished a request's prefill: the
  committed baseline is the sampled first token(s), the shipments cover
  the whole effective prompt, and the request now needs DECODE placement.
  Ownership stays with the prefill host (same gen) until the router
  writes the ``decode`` record.
- ``decode``   router -> decode host: ownership transfer at gen+1 after
  ``prefill_done``, self-contained like ``migrate`` (params + committed
  baseline) plus the router-verified shipment list the destination may
  import instead of re-running prefill. An empty shipment list IS the
  replay fallback.

:func:`fold` reduces all files to per-request state. Resolution leans on
the fleet's determinism contract: committed lists written for the same
request at different generations are prefixes of ONE deterministic stream
(``fold_in(seed, step)`` PRNG + bit-exact replay), so the longest list
wins and any prefix mismatch is corruption worth raising on.
"""

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import hlc

__all__ = ["RequestJournal", "RequestState", "fold", "persist_unserved"]


@dataclass
class RequestState:
    """Folded view of one request across every journal file."""
    request_id: str
    prompt: List[int] = field(default_factory=list)
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    gen: int = 0                   # current assignment generation
    host: Optional[str] = None     # current owner (None after requeue)
    committed: List[int] = field(default_factory=list)
    done: bool = False
    done_tokens: List[int] = field(default_factory=list)
    reason: str = ""
    migrations: int = 0
    requeued: bool = False         # latest ownership record is a requeue
    trace_id: str = ""             # obs/reqtrace.py span-trail key
    handoff_artifact: str = ""     # newest exported block-artifact dir
    handoff_gen: int = -1          # generation that exported it
    prefill_done: bool = False     # a prefill-role host finished prefill
    prefill_gen: int = -1          # generation that finished it
    kv_dtype: str = ""             # pool dtype the shipments were cut in
    shipments: List[Dict] = field(default_factory=list)
    ship_gen: int = -1             # generation the shipments belong to


class RequestJournal:
    """One participant's append handle on a journal directory."""

    def __init__(self, root: str, writer: str):
        if "/" in writer or writer.startswith("."):
            raise ValueError(f"bad journal writer name: {writer!r}")
        self.root = root
        self.writer = writer
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, f"{writer}.jsonl")

    def _append(self, rec: Dict) -> None:
        rec = dict(rec, hlc=hlc.tick())
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        # open/append/fsync/close per record: slow-path simple, and the
        # journal must survive the writer being SIGKILLed at any byte.
        with open(self.path, "a") as fh:
            fh.write(line)
            fh.flush()
            os.fsync(fh.fileno())

    # ------------------------------------------------------------ record kinds
    def assign(self, request_id: str, host: str, prompt: List[int],
               max_new_tokens: int, temperature: float, top_p: float,
               seed: int, trace_id: str = "") -> None:
        self._append({"kind": "assign", "id": request_id, "host": host,
                      "prompt": [int(t) for t in prompt],
                      "max_new_tokens": int(max_new_tokens),
                      "temperature": float(temperature),
                      "top_p": float(top_p), "seed": int(seed), "gen": 0,
                      "trace_id": str(trace_id)})

    def progress(self, request_id: str, host: str, committed: List[int],
                 gen: int, trace_id: str = "") -> None:
        self._append({"kind": "progress", "id": request_id, "host": host,
                      "committed": [int(t) for t in committed],
                      "gen": int(gen), "trace_id": str(trace_id)})

    def done(self, request_id: str, host: str, tokens: List[int],
             reason: str, gen: int, trace_id: str = "") -> None:
        self._append({"kind": "done", "id": request_id, "host": host,
                      "tokens": [int(t) for t in tokens],
                      "reason": reason, "gen": int(gen),
                      "trace_id": str(trace_id)})

    def migrate(self, request_id: str, src: str, dst: str, gen: int,
                prompt: List[int], max_new_tokens: int, temperature: float,
                top_p: float, seed: int, committed: List[int],
                trace_id: str = "", handoff: str = "") -> None:
        rec = {"kind": "migrate", "id": request_id, "src": src,
               "host": dst, "gen": int(gen),
               "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens),
               "temperature": float(temperature),
               "top_p": float(top_p), "seed": int(seed),
               "committed": [int(t) for t in committed],
               "trace_id": str(trace_id)}
        if handoff:
            # router-verified block artifact for the destination host to
            # import instead of replaying the committed prefix (advisory —
            # the committed list above remains the durable baseline)
            rec["handoff"] = str(handoff)
        self._append(rec)

    def handoff(self, request_id: str, host: str, artifact: str,
                committed: List[int], gen: int,
                trace_id: str = "") -> None:
        """A drain exported this request's committed KV blocks into the
        ``artifact`` directory. Written AFTER the artifact's manifest
        commit (fsync ordering), so a handoff record always points at a
        complete artifact — a host killed mid-export leaves no record and
        the request takes the replay path."""
        self._append({"kind": "handoff", "id": request_id, "host": host,
                      "artifact": str(artifact),
                      "committed": [int(t) for t in committed],
                      "gen": int(gen), "trace_id": str(trace_id)})

    def ship(self, request_id: str, host: str, artifact: str, seq: int,
             start_block: int, end_block: int, length: int, gen: int,
             trace_id: str = "", lane: str = "fs") -> None:
        """One incremental block shipment: ``artifact`` holds this
        request's prompt blocks ``[start_block, end_block)``, exported at
        a prefill chunk commit with ``length`` tokens committed in the
        slot. Written AFTER the artifact manifest commits (same fsync
        ordering as ``handoff``), so a record always points at a complete
        artifact. ``lane`` names the KV transport lane the exporter used
        (inference/transport.py) — informational: the artifact path is
        the handle on EVERY lane, and a cross-process consumer always has
        the fs form."""
        self._append({"kind": "ship", "id": request_id, "host": host,
                      "artifact": str(artifact), "seq": int(seq),
                      "start_block": int(start_block),
                      "end_block": int(end_block), "length": int(length),
                      "gen": int(gen), "trace_id": str(trace_id),
                      "lane": str(lane)})

    def prefill_done(self, request_id: str, host: str, committed: List[int],
                     gen: int, kv_dtype: str = "bf16",
                     trace_id: str = "") -> None:
        self._append({"kind": "prefill_done", "id": request_id,
                      "host": host,
                      "committed": [int(t) for t in committed],
                      "kv_dtype": str(kv_dtype), "gen": int(gen),
                      "trace_id": str(trace_id)})

    def decode(self, request_id: str, src: str, dst: str, gen: int,
               prompt: List[int], max_new_tokens: int, temperature: float,
               top_p: float, seed: int, committed: List[int],
               shipments: Optional[List[Dict]] = None,
               trace_id: str = "") -> None:
        """Ownership transfer prefill host -> decode host. ``shipments``
        is the router-VERIFIED subset of the prefill host's ship records
        (artifact + block range each); empty/None means the decode host
        replays the committed prefix instead of importing."""
        self._append({"kind": "decode", "id": request_id, "src": src,
                      "host": dst, "gen": int(gen),
                      "prompt": [int(t) for t in prompt],
                      "max_new_tokens": int(max_new_tokens),
                      "temperature": float(temperature),
                      "top_p": float(top_p), "seed": int(seed),
                      "committed": [int(t) for t in committed],
                      "shipments": [
                          {"artifact": str(s["artifact"]),
                           "seq": int(s["seq"]),
                           "start_block": int(s["start_block"]),
                           "end_block": int(s["end_block"]),
                           "length": int(s["length"]),
                           "lane": str(s.get("lane", "fs") or "fs")}
                          for s in (shipments or [])],
                      "trace_id": str(trace_id)})

    def requeue(self, request_id: str, prompt: List[int],
                max_new_tokens: int, temperature: float, top_p: float,
                seed: int, committed: List[int], gen: int,
                host: Optional[str] = None, trace_id: str = "") -> None:
        self._append({"kind": "requeue", "id": request_id, "host": host,
                      "prompt": [int(t) for t in prompt],
                      "max_new_tokens": int(max_new_tokens),
                      "temperature": float(temperature),
                      "top_p": float(top_p), "seed": int(seed),
                      "committed": [int(t) for t in committed],
                      "gen": int(gen), "trace_id": str(trace_id)})


def persist_unserved(journal: "RequestJournal", requests, reason: str,
                     gens: Optional[Dict[str, int]] = None) -> int:
    """Drain-time persistence shared by ``serve.py --journal-dir`` and the
    fleet host: every request the drain will not finish becomes ONE
    self-contained ``requeue`` record (params + committed baseline) the
    router can re-admit later. The requeue is written at gen+1 of the
    request's current assignment so it outranks the old ``assign`` in
    :func:`fold` regardless of file read order. Returns the count."""
    from ..obs import events, reqtrace
    from ..utils.logging import AUDIT_FLEET_REQUEUE_FMT, logger

    n = 0
    for req in requests:
        committed = [int(t) for t in getattr(req, "committed", ()) or ()]
        gen = int((gens or {}).get(req.id, 0)) + 1
        trace_id = str(getattr(req, "trace_id", "") or "")
        journal.requeue(req.id, list(req.prompt), req.max_new_tokens,
                        req.temperature, req.top_p, req.seed, committed,
                        gen=gen, trace_id=trace_id)
        events.emit_audit(
            logger, AUDIT_FLEET_REQUEUE_FMT.format(
                id=req.id, committed=len(committed), reason=reason),
            "fleet_requeue", id=req.id, committed=len(committed),
            reason=reason, gen=gen)
        if trace_id:
            reqtrace.emit(trace_id, req.id, "requeue",
                          committed=len(committed), reason=reason, gen=gen)
        n += 1
    return n


def _read_records(root: str) -> List[Dict]:
    recs: List[Dict] = []
    try:
        names = sorted(os.listdir(root))
    except FileNotFoundError:
        return recs
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(root, name)) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue  # torn tail of a SIGKILLed writer
    return recs


def _is_prefix(a: List[int], b: List[int]) -> bool:
    return len(a) <= len(b) and b[: len(a)] == a


def fold(root: str) -> Dict[str, RequestState]:
    """Reduce every journal file under ``root`` to per-request state.

    Ownership (host/gen) comes from the highest-generation
    assign/migrate/requeue/decode record; the committed list is the longest seen
    anywhere (all are prefixes of the same deterministic stream — verified,
    a mismatch raises); a ``done`` record wins outright, highest gen
    preferred when a fenced host double-reported."""
    states: Dict[str, RequestState] = {}
    for rec in _read_records(root):
        # The fold is a receive event for every record it reads: advance
        # the reader's HLC past all observed writers so anything the
        # reader journals next (a migrate, a tombstone-adjacent assign)
        # sorts causally after the records that justified it. Pre-HLC
        # records have no stamp and are a no-op.
        hlc.observe(rec.get("hlc"))
        rid = rec.get("id")
        if not rid:
            continue
        st = states.get(rid)
        if st is None:
            st = states[rid] = RequestState(request_id=rid)
        kind = rec.get("kind")
        gen = int(rec.get("gen", 0))
        if rec.get("trace_id"):
            st.trace_id = str(rec["trace_id"])
        if kind in ("assign", "migrate", "requeue", "decode"):
            if gen >= st.gen:
                st.gen = gen
                st.host = rec.get("host")
                st.requeued = kind == "requeue"
            if kind == "migrate":
                st.migrations += 1
            st.prompt = [int(t) for t in rec.get("prompt", st.prompt)]
            st.max_new_tokens = int(rec.get("max_new_tokens",
                                            st.max_new_tokens))
            st.temperature = float(rec.get("temperature", st.temperature))
            st.top_p = float(rec.get("top_p", st.top_p))
            st.seed = int(rec.get("seed", st.seed))
        if kind == "handoff" and gen >= st.handoff_gen:
            # advisory block-shipment pointer; never touches ownership
            st.handoff_gen = gen
            st.handoff_artifact = str(rec.get("artifact", ""))
        if kind == "ship":
            # advisory like handoff; only the NEWEST generation's
            # shipments survive (a re-prefill after death/drain re-ships
            # at its own gen and the stale set must not mix in)
            if gen > st.ship_gen:
                st.ship_gen = gen
                st.shipments = []
            if gen == st.ship_gen:
                st.shipments.append({
                    "artifact": str(rec.get("artifact", "")),
                    "seq": int(rec.get("seq", 0)),
                    "start_block": int(rec.get("start_block", 0)),
                    "end_block": int(rec.get("end_block", 0)),
                    "length": int(rec.get("length", 0)),
                    "lane": str(rec.get("lane", "fs") or "fs")})
        if kind == "prefill_done" and gen >= st.prefill_gen:
            st.prefill_done = True
            st.prefill_gen = gen
            st.kv_dtype = str(rec.get("kv_dtype", "") or "")
        committed = rec.get("committed") if kind != "done" else rec.get("tokens")
        if committed is not None:
            committed = [int(t) for t in committed]
            short, long_ = sorted([st.committed, committed], key=len)
            if not _is_prefix(short, long_):
                raise ValueError(
                    f"journal divergence for {rid}: committed lists "
                    f"{st.committed} and {committed} are not prefixes of "
                    f"one stream — determinism contract violated")
            st.committed = long_
        if kind == "done" and (not st.done or gen >= st.gen):
            st.done = True
            st.done_tokens = [int(t) for t in rec.get("tokens", [])]
            st.reason = rec.get("reason", "")
    return states
