"""Fleet-global, content-addressed KV-block store.

Per-host prefix caches (PR 6) stop paying once a fleet serves one shared
system prompt across many hosts: every host the router picks re-prefills
the SAME blocks. This module makes fully-committed prefix trains
fleet-visible, Mooncake-style, over primitives the repo already trusts:

- **Keys ARE content addresses.** ``prefix_cache.chain_hashes`` keys each
  full block by the running hash of every token up to and including it, so
  a train of ``n`` leading blocks is globally identified by its terminal
  chain hash (hex). Identical prefixes hash identically on every host —
  dedup is free, publish of an already-resident key is a no-op.
- **Artifacts are the PR 13 CRC-manifested form.** Publish is an
  ``export_blocks`` into ``<root>/trains/<key>/``; the manifest commits
  last via tmp+fsync+rename, so a host SIGKILLed mid-put leaves a
  missing-manifest directory that is simply invisible (``has`` checks the
  manifest), never silent garbage. Fetch lands through the PR 15
  verify-before-first-device-write batch import; any CRC reject degrades
  to local chunked prefill — corruption costs recompute, never
  correctness.
- **State is journaled like everything else.** ``<root>/journal/`` holds
  per-writer fsync'd JSONL (``put`` / ``touch`` / ``ref`` / ``unref`` /
  ``evict``); :meth:`BlockStore.fold` reduces it to per-train state, a
  restarted sweeper re-folds and re-migrates nothing. In-flight fetches
  hold journaled refcounts, so fleet-global LRU eviction
  (:meth:`BlockStore.sweep`) can never pull a train out from under an
  importer; an ``unref`` below zero is corruption worth raising on,
  exactly like the request journal's prefix-divergence check.

The store itself is pure mechanism — audit lines, metrics and reqtrace
spans for publish/fetch decisions are emitted by the scheduler
(``[KV STORE]`` / ``kv_store_*``), and cache-affinity placement lives in
``router.py`` (:meth:`BlockStore.affinity` feeds its ``pick_host`` key).
"""

import argparse
import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

from ..obs import hlc
from .kv_cache import BLOCK_MANIFEST_NAME, export_blocks

__all__ = ["BlockStore", "StoreHit", "TrainState", "main",
           "run_sweeper", "sweep_leader"]

_TRAINS_DIR = "trains"
_JOURNAL_DIR = "journal"


@dataclass(frozen=True)
class StoreHit:
    """One matching train: ``depth`` full blocks of the prompt covered by
    train ``key``. ``blocks`` is the PUBLISHED train's total — when
    ``depth < blocks`` this is a sub-train (partial) hit: the prompt is a
    proper prefix of a longer published train, and the fetch imports only
    the first ``depth`` payload blocks."""
    key: str
    depth: int
    art_dir: str
    blocks: int = 0

    @property
    def partial(self) -> bool:
        return 0 < self.blocks != self.depth


@dataclass
class TrainState:
    """Folded view of one train across every store-journal file."""
    key: str
    blocks: int = 0
    bytes: int = 0
    length: int = 0
    host: str = ""                 # publisher
    put_t: float = 0.0
    last_use: float = 0.0          # LRU clock: newest put/touch/ref
    refs: int = 0                  # open ref - unref (in-flight fetches)
    hosts: Set[str] = field(default_factory=set)  # residency evidence
    evicted: bool = False          # newest put-vs-evict record is evict
    keys: List[str] = field(default_factory=list)  # full chain (hex)


class BlockStore:
    """One host's handle on the shared store directory.

    ``writer`` names this participant's journal file (one appender per
    file, the request-journal discipline — concurrent hosts never
    interleave bytes, a SIGKILL tears at worst the killer's own tail).
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, root: str, writer: str,
                 clock: Callable[[], float] = time.time):
        if "/" in writer or writer.startswith("."):
            raise ValueError(f"bad store writer name: {writer!r}")
        self.root = root
        self.writer = writer
        self.clock = clock
        self.puts = 0              # publish ordinal (chaos keying)
        self._seq = 0              # per-writer record counter (fold order)
        self._held: Set[tuple] = set()  # (key, owner) refs THIS handle holds
        os.makedirs(os.path.join(root, _TRAINS_DIR), exist_ok=True)
        os.makedirs(os.path.join(root, _JOURNAL_DIR), exist_ok=True)
        self._journal_path = os.path.join(root, _JOURNAL_DIR,
                                          f"{writer}.jsonl")

    # ------------------------------------------------------------ journal
    def _append(self, rec: Dict) -> None:
        rec = dict(rec, t=float(self.clock()), w=self.writer,
                   seq=self._seq, hlc=hlc.tick())
        self._seq += 1
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with open(self._journal_path, "a") as fh:
            fh.write(line)
            fh.flush()
            os.fsync(fh.fileno())

    # ------------------------------------------------------------ paths
    def train_dir(self, key: str) -> str:
        return os.path.join(self.root, _TRAINS_DIR, key)

    def has(self, key: str) -> bool:
        """A train is visible iff its manifest committed — the atomic
        rename in ``export_blocks`` makes this the torn-put filter."""
        return os.path.isfile(os.path.join(self.train_dir(key),
                                           BLOCK_MANIFEST_NAME))

    # ------------------------------------------------------------ lookup
    def match(self, keys: Sequence[bytes]) -> Optional[StoreHit]:
        """Deepest train covering a prefix of the chain-hash ladder
        ``keys`` (``chain_hashes`` output, one hash per full block), or
        None. Terminal hits first — a train keyed by ``keys[i]`` covers
        ``i+1`` blocks exactly. Failing that, SUB-TRAIN addressability:
        a published train whose per-block chain (its manifest ``keys``)
        starts with ``keys[:i+1]`` serves the prompt partially — chain
        hashes make position content-determined (``keys[i]`` can only sit
        at position ``i`` of any train), so matching one interior key at
        its own position proves the whole leading run matches. The fetch
        then imports only the covered prefix of the payload files."""
        for i in range(len(keys) - 1, -1, -1):
            key = keys[i].hex()
            if self.has(key):
                return StoreHit(key=key, depth=i + 1,
                                art_dir=self.train_dir(key),
                                blocks=i + 1)
        index = self.chain_index()
        for i in range(len(keys) - 1, -1, -1):
            hit = index.get(keys[i].hex())
            if hit is None:
                continue
            terminal, pos, total = hit
            if pos == i and self.has(terminal):
                return StoreHit(key=terminal, depth=i + 1,
                                art_dir=self.train_dir(terminal),
                                blocks=total)
        return None

    def chain_index(self) -> Dict[str, tuple]:
        """Interior chain key (hex) -> ``(terminal_key, position,
        train_blocks)`` across resident trains — the sub-train lookup
        surface. Built from the journaled per-block chains (``put``
        records publish their full ``keys`` list), falling back to the
        train manifest's ``meta.keys`` for trains published before the
        chain rode in the journal."""
        index: Dict[str, tuple] = {}
        for key, st in self.resident().items():
            chain = st.keys or self._manifest_keys(key)
            for pos, kh in enumerate(chain):
                index.setdefault(kh, (key, pos, len(chain)))
        return index

    def _manifest_keys(self, key: str) -> List[str]:
        try:
            with open(os.path.join(self.train_dir(key),
                                   BLOCK_MANIFEST_NAME)) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return []
        keys = manifest.get("meta", {}).get("keys", [])
        return [str(k) for k in keys]

    # ------------------------------------------------------------ publish
    def publish(self, cache, keys: Sequence[bytes],
                blocks: Sequence[int], *, length: int,
                meta: Optional[Dict] = None,
                on_put: Optional[Callable[[str, int], None]] = None,
                transport=None) -> Optional[Dict]:
        """Export pool rows ``blocks`` (the train's full prefix blocks, in
        order) as the train keyed by ``keys[-1]``. Dedup: an already-
        visible key publishes nothing and returns None. ``on_put`` is the
        chaos hook (``store_corrupt``, keyed by this handle's publish
        ordinal), called after the artifact commits and BEFORE the journal
        record — the same ordering the fleet's ship hook uses.
        ``transport`` routes the export through a KV transport lane
        (inference/transport.py) — the mem lane additionally pushes the
        train's device arrays so a same-process fetch lands without
        touching the artifact bytes; None is the plain fs export. The
        journal ``put`` record carries the train's full per-block chain,
        the sub-train lookup's index source. Returns the manifest, or
        None when deduped."""
        if len(blocks) != len(keys) or not keys:
            raise ValueError(
                f"train needs one key per block: {len(keys)} key(s) for "
                f"{len(blocks)} block(s)")
        key = keys[-1].hex()
        if self.has(key):
            return None
        art_dir = self.train_dir(key)
        if os.path.isdir(art_dir):
            # torn remains of a killed publisher: no manifest, so the key
            # was never visible — finish the death, then re-export
            shutil.rmtree(art_dir)
        chain = [k.hex() for k in keys]
        export = export_blocks if transport is None else transport.export
        manifest = export(
            cache, list(blocks), art_dir, length=int(length),
            meta=dict(meta or {}, kind="store", key=key, keys=chain))
        nbytes = sum(int(f["size"]) for f in manifest["files"].values())
        ordinal = self.puts
        self.puts += 1
        if on_put is not None:
            on_put(art_dir, ordinal)
        self._append({"kind": "put", "key": key, "blocks": len(blocks),
                      "bytes": nbytes, "length": int(length),
                      "host": self.writer, "keys": chain})
        return manifest

    # ------------------------------------------------------------ refcounts
    def acquire(self, key: str, owner: str) -> None:
        """Journal a fetch-in-flight reference: the sweeper skips
        refcounted trains, so the artifact cannot be evicted between
        ``match`` and the verify-import."""
        held = (key, owner)
        if held in self._held:
            raise ValueError(f"double acquire of train {key} by {owner}")
        self._held.add(held)
        self._append({"kind": "ref", "key": key, "owner": owner})

    def release(self, key: str, owner: str) -> None:
        """Drop a reference this handle holds; releasing one it does not
        hold is a refcount bug, raised exactly like the allocator's
        double-free."""
        held = (key, owner)
        if held not in self._held:
            raise ValueError(f"double release of train {key} by {owner}")
        self._held.remove(held)
        self._append({"kind": "unref", "key": key, "owner": owner})

    def touch(self, key: str) -> None:
        """LRU use marker (journaled): a successful fetch touches the
        train, and the toucher becomes residency evidence for the
        router's affinity map."""
        self._append({"kind": "touch", "key": key, "host": self.writer})

    # ------------------------------------------------------------ fold
    def _read_records(self) -> List[Dict]:
        recs: List[Dict] = []
        root = os.path.join(self.root, _JOURNAL_DIR)
        try:
            names = sorted(os.listdir(root))
        except FileNotFoundError:
            return recs
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            with open(os.path.join(root, name)) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail of a SIGKILLed writer
        recs.sort(key=lambda r: (float(r.get("t", 0.0)),
                                 str(r.get("w", "")),
                                 int(r.get("seq", 0))))
        return recs

    def fold(self) -> Dict[str, TrainState]:
        """Reduce every journal file to per-train state. Idempotent — a
        restarted sweeper folds to exactly the state the dead one saw. An
        ``unref`` that would drive a train's refcount negative raises:
        refs are the only thing standing between an importer and the
        sweeper, so an unbalanced pair is corruption, not noise."""
        states: Dict[str, TrainState] = {}
        for rec in self._read_records():
            # receive event: the folding reader's clock advances past
            # every journaled writer (missing stamps are a no-op)
            hlc.observe(rec.get("hlc"))
            key = rec.get("key")
            if not key:
                continue
            st = states.get(key)
            if st is None:
                st = states[key] = TrainState(key=key)
            kind = rec.get("kind")
            t = float(rec.get("t", 0.0))
            if kind == "put":
                st.blocks = int(rec.get("blocks", 0))
                st.bytes = int(rec.get("bytes", 0))
                st.length = int(rec.get("length", 0))
                st.host = str(rec.get("host", ""))
                st.put_t = t
                st.last_use = max(st.last_use, t)
                st.hosts.add(st.host)
                st.evicted = False  # re-publish after evict resurrects
                st.keys = [str(k) for k in rec.get("keys", []) or []]
            elif kind == "touch":
                st.last_use = max(st.last_use, t)
                if rec.get("host"):
                    st.hosts.add(str(rec["host"]))
            elif kind == "ref":
                st.refs += 1
                st.last_use = max(st.last_use, t)
            elif kind == "unref":
                st.refs -= 1
                if st.refs < 0:
                    raise ValueError(
                        f"store journal double release for train {key}: "
                        f"refcount went negative")
            elif kind == "evict":
                st.evicted = True
        return states

    def resident(self) -> Dict[str, TrainState]:
        """Folded trains that are actually fetchable: journaled, not
        evicted, manifest on disk."""
        return {k: st for k, st in self.fold().items()
                if not st.evicted and self.has(k)}

    def resident_bytes(self) -> int:
        return sum(st.bytes for st in self.resident().values())

    # ------------------------------------------------------------ affinity
    def affinity(self, keys: Sequence[bytes]) -> Dict[str, int]:
        """Per-host depth of the deepest resident matching train that
        host published or touched — the router's cache-affinity signal
        (SGLang-style: place where the longest prefix already resides)."""
        res = self.resident()
        depths: Dict[str, int] = {}
        for i, k in enumerate(keys):
            st = res.get(k.hex())
            if st is None:
                continue
            for host in st.hosts:
                depths[host] = max(depths.get(host, 0), i + 1)
        return depths

    # ------------------------------------------------------------ eviction
    def sweep(self, max_bytes: int) -> List[str]:
        """Fleet-global LRU: evict oldest-by-last-use unreferenced trains
        until resident bytes fit ``max_bytes``. Half-evicted directories
        (journaled ``evict``, directory still on disk — the sweeper died
        mid-rmtree) are finished WITHOUT new records, which is what makes
        a restart re-migrate nothing. Returns the evicted keys."""
        states = self.fold()
        for key, st in states.items():
            if st.evicted and os.path.isdir(self.train_dir(key)):
                shutil.rmtree(self.train_dir(key), ignore_errors=True)
        live = [st for st in states.values()
                if not st.evicted and self.has(st.key)]
        total = sum(st.bytes for st in live)
        evicted: List[str] = []
        for st in sorted(live, key=lambda s: (s.last_use, s.key)):
            if total <= max_bytes:
                break
            if st.refs > 0:
                continue  # an importer is mid-fetch; never pull its train
            self._append({"kind": "evict", "key": st.key})
            shutil.rmtree(self.train_dir(st.key), ignore_errors=True)
            total -= st.bytes
            evicted.append(st.key)
        return evicted


# ------------------------------------------------------------ sweeper loop
def sweep_leader(leases: Dict[str, object], host_id: str) -> bool:
    """Deterministic fleet sweeper election over the live heartbeat
    leases: the lexically-lowest LIVE host id sweeps, everyone else
    stands down. No extra coordination state — leadership follows lease
    liveness, so the death of the sweeping host hands the duty to the
    next survivor on its next interval, and a fenced zombie (its lease
    expired) stops sweeping by the same test that stops its journal
    writes."""
    live = sorted(h for h, lease in leases.items()
                  if getattr(lease, "live", False))
    return bool(live) and live[0] == host_id


def run_sweeper(store: "BlockStore", max_bytes: int, *, interval: float,
                stop: Callable[[], bool],
                leases: Optional[Callable[[], Dict[str, object]]] = None,
                host_id: str = "",
                on_evict: Optional[Callable[[List[str]], None]] = None
                ) -> int:
    """The fleet-lifecycle sweep daemon: every ``interval`` seconds, if
    this host is the sweep leader (or no lease surface is given — the
    single-host store), fold the journal and LRU-evict down to
    ``max_bytes``. Runs until ``stop()`` is truthy; the sleep is chopped
    so a drain signal is honored within ~50 ms. ``on_evict`` receives
    each round's evicted keys (the caller's audit seam). Returns the
    total trains evicted."""
    total = 0
    while not stop():
        if leases is None or sweep_leader(leases(), host_id):
            evicted = store.sweep(max_bytes)
            if evicted:
                total += len(evicted)
                if on_evict is not None:
                    on_evict(evicted)
        deadline = time.monotonic() + max(interval, 0.05)
        while not stop() and time.monotonic() < deadline:
            time.sleep(0.05)
    return total


def get_store_args(argv=None):
    p = argparse.ArgumentParser(
        description="Standalone store sweeper: fold the store journal and "
                    "LRU-evict unreferenced trains down to a byte budget.")
    p.add_argument("--store-dir", required=True,
                   help="shared BlockStore root directory")
    p.add_argument("--max-bytes", type=int, required=True,
                   help="resident-bytes budget to sweep down to")
    p.add_argument("--writer", default="sweeper",
                   help="journal writer name for evict records")
    p.add_argument("--interval", type=float, default=0.0,
                   help="seconds between sweep rounds: > 0 runs the "
                        "daemon loop until signaled (the fleet wires this "
                        "in-process instead, with lease-based leader "
                        "election); 0 = one shot and exit")
    p.add_argument("--max-run-seconds", type=float, default=0.0,
                   help="daemon mode safety timeout (0 = until signaled)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = get_store_args(argv)
    store = BlockStore(args.store_dir, args.writer)
    before = store.resident_bytes()
    if args.interval > 0:
        from ..ft.signals import SignalFlag
        flag = SignalFlag()
        flag.register()
        t0 = time.monotonic()

        def stop():
            return (flag.signum is not None
                    or (args.max_run_seconds
                        and time.monotonic() - t0 > args.max_run_seconds))

        n = run_sweeper(store, args.max_bytes, interval=args.interval,
                        stop=stop,
                        on_evict=lambda keys: print(
                            f"store sweep: {len(keys)} train(s) evicted"))
        print(f"store sweep daemon: {before} -> {store.resident_bytes()} "
              f"byte(s), {n} train(s) evicted")
        return 0
    evicted = store.sweep(args.max_bytes)
    print(f"store sweep: {before} -> {store.resident_bytes()} byte(s), "
          f"{len(evicted)} train(s) evicted")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
