"""Token sampling: greedy / temperature / top-k / top-p.

All pure functions of (logits, key, knobs) so the engine can fold them into
the jitted decode step; per-slot determinism comes from the key derivation
``fold_in(PRNGKey(request_seed), step)`` — restarting a request from its
prompt replays the identical key sequence, so sampled generations are
reproducible across engine restarts exactly like greedy ones
(tests/test_inference.py).

``temperature <= 0`` selects greedy argmax (the scheduler's default), so one
decode program serves mixed greedy/sampled slots without recompilation.

Speculative decoding (engine.py spec mode) adds two kernels on the same
filtered distributions:

- :func:`sample_token_with_probs` — the draft model's proposal step; it
  returns the token AND the exact post-filter distribution q it was drawn
  from (greedy: a one-hot), because the verify-side acceptance test needs
  q(d), not the raw logits.
- :func:`spec_accept` — the Leviathan/Chen accept/resample rule, vectorized
  over the k+1 verify positions. With one-hot greedy distributions the
  acceptance test ``u * q(d) < p(d)`` degenerates to exact argmax matching
  and the resample to the target argmax, so the single kernel serves both
  modes and greedy outputs stay BIT-identical to the non-speculative path.
- :func:`tree_accept` — the multi-branch generalization: one walk down a
  flattened draft TREE, greedy longest-accepted-path selection or
  SpecInfer-style recursive rejection per level, emitting the accepted
  path plus one resampled/bonus token.

:class:`AdaptiveK` is the one HOST-side piece here: the controller that
tunes the round width k from live acceptance, colocated with the accept
rule whose statistics drive it (the scheduler owns an instance when
serving opts in with ``--adaptive-spec-k``).
"""

from typing import Dict, Iterable, Optional

import jax
import jax.numpy as jnp


def _top_k_filter(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Keep the k highest logits, -inf the rest (static k: part of the
    compiled program, an engine-level knob rather than a per-request one)."""
    kth = jax.lax.top_k(logits, top_k)[0][-1]
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filter: keep the smallest prefix of the sorted distribution
    whose mass reaches ``top_p`` (always at least the argmax). ``top_p >= 1``
    keeps everything, so the replicated decode program needs no branch."""
    sorted_logits = jnp.sort(logits)[::-1]
    probs = jax.nn.softmax(sorted_logits)
    cum = jnp.cumsum(probs)
    # token i is kept iff the mass BEFORE it is < top_p (the crossing token
    # is included); monotone cum makes this a prefix
    keep = jnp.sum((cum - probs < top_p).astype(jnp.int32))
    cutoff = sorted_logits[jnp.maximum(keep - 1, 0)]
    return jnp.where(logits >= cutoff, logits, -jnp.inf)


def sample_token(logits: jnp.ndarray, key: jax.Array,
                 temperature: jnp.ndarray, top_p: jnp.ndarray,
                 top_k: int = 0) -> jnp.ndarray:
    """One next-token id (int32) from unnormalized ``logits`` (V,) fp32.

    temperature/top_p are traced per-slot scalars; top_k is static.
    Greedy (temperature <= 0) is computed unconditionally and selected with
    a ``where`` — both paths are cheap relative to the forward, and the
    single program keeps mixed-slot batches on one compiled decode step.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        scaled = _top_k_filter(scaled, top_k)
    scaled = _top_p_filter(scaled, top_p)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def slot_key(seed: jnp.ndarray, step: jnp.ndarray) -> jax.Array:
    """Per-slot, per-step PRNG key: request seed folded by decode step."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), step)


def sample_slot_tokens(logits: jnp.ndarray, seeds: jnp.ndarray,
                       steps: jnp.ndarray, temperature: jnp.ndarray,
                       top_p: jnp.ndarray, top_k: int = 0) -> jnp.ndarray:
    """Whole-batch sampling epilogue: (slots, V) fp32 logits -> (slots,)
    int32 tokens, each slot under its own ``slot_key(seed, step)`` stream.

    This is THE sampling epilogue, fused and unfused alike: the decode
    programs (engine.py ``_paged_decode_fn``/``_decode_fn``, the burst
    loop's micro-steps) trace it in-program so the dispatch ends in token
    ids, and the unfused path (``decode_logits`` + host-side sampling,
    the bench's baseline) calls the very same function on the synced
    logits. One definition, one PRNG schedule — which is why a fused
    single step's streams bit-match the host-sampled ones.
    """
    keys = jax.vmap(slot_key)(seeds, steps)
    return jax.vmap(sample_token, in_axes=(0, 0, 0, 0, None))(
        logits, keys, temperature, top_p, top_k)


def draft_key(seed: jnp.ndarray, step: jnp.ndarray) -> jax.Array:
    """Draft-proposal PRNG stream, disjoint from :func:`slot_key`'s so the
    draft model's sampling never aliases the target's (``step`` here is the
    flat draft micro-step counter ``round * (k + 1) + i``)."""
    return jax.random.fold_in(slot_key(seed, step), 0x5D)


def verify_key(seed: jnp.ndarray, round_: jnp.ndarray) -> jax.Array:
    """Accept/resample PRNG stream for one verify round, disjoint from both
    :func:`slot_key` and :func:`draft_key`."""
    return jax.random.fold_in(slot_key(seed, round_), 0x7E)


def tree_key(seed: jnp.ndarray, round_: jnp.ndarray) -> jax.Array:
    """Accept/resample PRNG stream for one TREE-verify round
    (:func:`tree_accept`), disjoint from :func:`slot_key`,
    :func:`draft_key` and :func:`verify_key` (fold constant 0x3B)."""
    return jax.random.fold_in(slot_key(seed, round_), 0x3B)


def sample_token_with_probs(logits: jnp.ndarray, key: jax.Array,
                            temperature: jnp.ndarray, top_p: jnp.ndarray,
                            top_k: int = 0):
    """Like :func:`sample_token` but also returns the post-filter
    distribution the token was drawn from: softmax of the temperature-scaled,
    top-k/top-p-filtered logits for sampled slots, an exact one-hot at the
    argmax for greedy slots. The speculative accept test is stated on these
    distributions — using raw-softmax q with filtered sampling would bias
    the acceptance ratio."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        scaled = _top_k_filter(scaled, top_k)
    scaled = _top_p_filter(scaled, top_p)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    tok = jnp.where(temperature > 0.0, sampled, greedy)
    probs = jnp.where(temperature > 0.0, jax.nn.softmax(scaled),
                      jax.nn.one_hot(greedy, v, dtype=jnp.float32))
    return tok, probs


def _filtered_probs(logits: jnp.ndarray, temperature: jnp.ndarray,
                    top_p: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Row-wise post-filter target distributions for (S, V) logits; greedy
    rows are exact one-hots (see :func:`sample_token_with_probs`)."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        scaled = jax.vmap(_top_k_filter, in_axes=(0, None))(scaled, top_k)
    scaled = jax.vmap(_top_p_filter, in_axes=(0, None))(scaled, top_p)
    return jnp.where(temperature > 0.0, jax.nn.softmax(scaled, axis=-1),
                     jax.nn.one_hot(greedy, v, dtype=jnp.float32))


def spec_accept(draft_tokens: jnp.ndarray, draft_probs: jnp.ndarray,
                target_logits: jnp.ndarray, key: jax.Array,
                temperature: jnp.ndarray, top_p: jnp.ndarray,
                top_k: int = 0):
    """Speculative accept/resample for ONE slot (the engine vmaps it).

    draft_tokens: (k,) int32 proposals d_1..d_k.
    draft_probs:  (k, V) fp32 — q_i, the distribution d_i was drawn from.
    target_logits: (k+1, V) fp32 — verify-pass logits; row i scores the
                  position AFTER d_i's prefix (row 0 = after the committed
                  context), so row i's filtered distribution p_i is the
                  target's next-token law at d_i's position and row k's is
                  the bonus position past a fully-accepted draft.

    Rule (Leviathan et al. 2023; Chen et al. 2023): accept d_i while
    ``u_i < p_i(d_i) / q_i(d_i)`` holds for the leading run (stated below
    multiplicatively as ``u_i * q_i(d_i) < p_i(d_i)`` — no divide-by-zero);
    at the first rejection emit one token from the residual
    ``norm(max(p_a - q_a, 0))``; on full acceptance emit the bonus token
    from p_k. The emitted prefix is distributed EXACTLY as k+1 sequential
    target samples. Greedy rows make both q and p one-hots: the test
    becomes exact argmax matching (u < 1 always, uniform is [0, 1)) and the
    residual collapses to the target argmax — selected via a ``where`` so
    greedy never consumes gumbel noise and stays bit-exact.

    Returns ``(out_tokens, accepted)``: out_tokens (k+1,) int32 holds the
    a = accepted accepted drafts then the resampled/bonus token at index a
    (tail entries past a are zeros the caller ignores).
    """
    k, v = draft_probs.shape
    greedy_toks = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)
    p = _filtered_probs(target_logits, temperature, top_p, top_k)  # (k+1, V)
    q_d = jnp.take_along_axis(draft_probs, draft_tokens[:, None], 1)[:, 0]
    p_d = jnp.take_along_axis(p[:k], draft_tokens[:, None], 1)[:, 0]
    u = jax.random.uniform(jax.random.fold_in(key, 0), (k,))
    accept = u * q_d < p_d
    a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))  # leading-run length
    # residual at the first rejected position (q past row k is zero, so a
    # full accept resolves to the bonus distribution p_k itself)
    q_pad = jnp.concatenate(
        [draft_probs, jnp.zeros((1, v), draft_probs.dtype)], axis=0)
    p_a, q_a = jnp.take(p, a, axis=0), jnp.take(q_pad, a, axis=0)
    resid = jnp.maximum(p_a - q_a, 0.0)
    # resid sums to zero only through numerics (p==q exactly); fall back to
    # p_a so the categorical below stays well-defined
    resid = jnp.where(resid.sum() > 0.0, resid, p_a)
    resampled = jax.random.categorical(
        jax.random.fold_in(key, 1),
        jnp.log(jnp.maximum(resid, 1e-38))).astype(jnp.int32)
    bonus = jnp.where(temperature > 0.0, resampled,
                      jnp.take(greedy_toks, a))
    idx = jnp.arange(k + 1, dtype=jnp.int32)
    d_pad = jnp.concatenate(
        [draft_tokens, jnp.zeros((1,), jnp.int32)], axis=0)
    out = jnp.where(idx < a, d_pad, 0).at[a].set(bonus)
    return out, a


def tree_accept(tree_tokens: jnp.ndarray, draft_probs: jnp.ndarray,
                target_logits: jnp.ndarray, key: jax.Array,
                temperature: jnp.ndarray, top_p: jnp.ndarray,
                child_matrix: jnp.ndarray, depth: int, top_k: int = 0):
    """Tree-speculative accept/resample for ONE slot (the engine vmaps it).

    The round's token tree is flattened to S rows in topological order:
    row 0 is the committed last token (the root — never itself accepted),
    rows 1..S-1 are draft proposals. The STATIC structure arrives as
    ``child_matrix`` (S, C) int32 — row i lists node i's children in
    proposal order, padded with -1 — and ``depth`` (python int), the tree's
    maximum proposal depth, which bounds the walk's unrolled length.

    tree_tokens:   (S,) int32 — row 0 the committed token, rest proposals.
    draft_probs:   (S, V) fp32 — q_i, the distribution node i's token was
                   drawn from (row 0 unused).
    target_logits: (S, V) fp32 — tree-verify logits; row i is the target's
                   next-token law AFTER node i's token given node i's
                   ancestor path (so row 0 scores the first proposal level
                   and an accepted leaf's row is the bonus position).

    Walk from the root, one tree level per step. Greedy slots take the
    longest ACCEPTED path: a child is accepted iff its token equals the
    target argmax at the current node, so the walk is exact argmax matching
    level by level and stays bit-identical to non-speculative decode.
    Sampled slots run SpecInfer-style recursive rejection (Miao et al.
    2023): children are tried in order with ``u * q_c(t_c) < p(t_c)``
    against the current residual p (initialized to the filtered target
    distribution at the node); each rejection folds that child out,
    ``p <- norm(max(p - q_c, 0))``, and if every child is rejected one
    token is emitted from the final residual — so the emitted path is
    distributed EXACTLY as sequential target samples, branches only adding
    acceptance chances. On full acceptance to ``depth`` the extra token is
    the bonus sample from the leaf's target distribution. Both modes share
    one walk; greedy is selected with ``where`` and never consumes noise.

    Returns ``(out_tokens, path_nodes, accepted)``: out_tokens (depth+1,)
    int32 — the a = accepted proposal tokens then the resampled/bonus
    token at index a (tail zeros); path_nodes (depth,) int32 — the
    accepted nodes' ROW indices in walk order (tail zeros), which is what
    the KV commit remap consumes.
    """
    s, v = draft_probs.shape
    c_max = child_matrix.shape[1]
    greedy_toks = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)
    p_rows = _filtered_probs(target_logits, temperature, top_p, top_k)
    cur = jnp.int32(0)
    alive = jnp.bool_(True)
    resid = p_rows[0]          # sampled-mode residual at the current node
    stop_resid = p_rows[0]     # residual captured where the walk died
    a = jnp.int32(0)
    path = jnp.zeros((depth,), jnp.int32)
    out = jnp.zeros((depth + 1,), jnp.int32)
    for lvl in range(depth):
        kids = jnp.take(child_matrix, cur, axis=0)              # (C,)
        kid_ok = kids >= 0
        safe_kids = jnp.maximum(kids, 0)
        kid_tok = jnp.take(tree_tokens, safe_kids)              # (C,)
        # greedy: first child proposing the target argmax at cur
        g = jnp.take(greedy_toks, cur)
        g_match = kid_ok & (kid_tok == g)
        g_has = jnp.any(g_match)
        g_next = jnp.take(safe_kids, jnp.argmax(g_match))
        # sampled: recursive rejection across the children, in order
        p_lvl = resid
        s_has = jnp.bool_(False)
        s_next = jnp.int32(0)
        for c in range(c_max):
            ok = kid_ok[c] & ~s_has
            t_c = kid_tok[c]
            q_c = jnp.take(draft_probs, safe_kids[c], axis=0)   # (V,)
            u = jax.random.uniform(
                jax.random.fold_in(key, lvl * c_max + c), ())
            acc_c = ok & (u * q_c[t_c] < p_lvl[t_c])
            s_next = jnp.where(acc_c, safe_kids[c], s_next)
            s_has = s_has | acc_c
            new_p = jnp.maximum(p_lvl - q_c, 0.0)
            tot = new_p.sum()
            new_p = jnp.where(tot > 0.0, new_p / tot, p_lvl)
            p_lvl = jnp.where(ok & ~acc_c, new_p, p_lvl)
        samp = temperature > 0.0
        acc = alive & jnp.where(samp, s_has, g_has)
        nxt = jnp.where(samp, s_next, g_next)
        path = path.at[lvl].set(jnp.where(acc, nxt, path[lvl]))
        out = out.at[lvl].set(
            jnp.where(acc, jnp.take(tree_tokens, nxt), out[lvl]))
        a = a + acc.astype(jnp.int32)
        stop_resid = jnp.where(alive & ~acc, p_lvl, stop_resid)
        cur = jnp.where(acc, nxt, cur)
        resid = jnp.where(acc, jnp.take(p_rows, nxt, axis=0), resid)
        alive = acc
    # survivor's bonus comes from the leaf's full distribution; a dead
    # walk emits from the residual at the level it died
    final_resid = jnp.where(alive, resid, stop_resid)
    resampled = jax.random.categorical(
        jax.random.fold_in(key, depth * c_max + 1),
        jnp.log(jnp.maximum(final_resid, 1e-38))).astype(jnp.int32)
    extra = jnp.where(temperature > 0.0, resampled,
                      jnp.take(greedy_toks, cur))
    out = out.at[a].set(extra)
    return out, path, a


class AdaptiveK:
    """Per-request adaptive round width for speculative decoding.

    Each request keeps an EMA of its observed acceptance fraction
    (accepted / proposed per verify round). Its target width is the
    expected accepted-run length of a geometric chain at that rate —
    ``a / (1 - a)`` — clamped to ``[1, k_max]`` and snapped UP to the
    engine's compiled ladder (powers of two plus ``k_max``, matching
    ``InferenceEngine._spec_pair``). The batched round runs at the MIN
    target over active requests: speculation is all-slots-at-once, so the
    least-accepting stream sets the width everyone pays for.

    A request with no evidence yet is OPTIMISTIC (``k_max``); a stale
    draft — e.g. the target was hot-swapped and the draft lags a publish —
    drags acceptance down, the controller walks k toward 1, and serving
    degrades gracefully toward plain decode instead of burning k rejected
    proposals per round. :meth:`reset` clears every estimate when a fresh
    draft is installed (deploy/reload.py), restoring optimism.
    """

    def __init__(self, k_max: int, decay: float = 0.75):
        if k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {k_max}")
        if not 0.0 <= decay < 1.0:
            raise ValueError(f"decay must be in [0, 1), got {decay}")
        self.k_max = int(k_max)
        self.decay = float(decay)
        rungs, r = [], 1
        while r < self.k_max:
            rungs.append(r)
            r *= 2
        rungs.append(self.k_max)
        self.rungs = tuple(rungs)
        self._rate: Dict[str, float] = {}

    def reset(self) -> None:
        """Forget every estimate (fresh draft installed)."""
        self._rate.clear()

    def forget(self, request_id: str) -> None:
        self._rate.pop(request_id, None)

    def observe(self, request_id: str, accepted: int, k: int) -> None:
        """Fold one verify round's ``accepted`` out of ``k`` proposals into
        the request's EMA."""
        if k <= 0:
            return
        x = min(max(float(accepted) / float(k), 0.0), 1.0)
        prev = self._rate.get(request_id)
        self._rate[request_id] = (x if prev is None
                                  else self.decay * prev
                                  + (1.0 - self.decay) * x)

    def acceptance(self, request_id: str) -> Optional[float]:
        return self._rate.get(request_id)

    def target_k(self, request_id: str) -> int:
        rate = self._rate.get(request_id)
        if rate is None:
            return self.k_max
        want = rate / max(1.0 - rate, 1e-6)
        want = min(max(want, 1.0), float(self.k_max))
        for r in self.rungs:
            if r >= want:
                return r
        return self.k_max

    def round_k(self, request_ids: Iterable[str]) -> int:
        """Width for one batched round: min target over active requests
        (``k_max`` when idle)."""
        targets = [self.target_k(i) for i in request_ids]
        return min(targets) if targets else self.k_max
