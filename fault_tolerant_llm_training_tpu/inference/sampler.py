"""Token sampling: greedy / temperature / top-k / top-p.

All pure functions of (logits, key, knobs) so the engine can fold them into
the jitted decode step; per-slot determinism comes from the key derivation
``fold_in(PRNGKey(request_seed), step)`` — restarting a request from its
prompt replays the identical key sequence, so sampled generations are
reproducible across engine restarts exactly like greedy ones
(tests/test_inference.py).

``temperature <= 0`` selects greedy argmax (the scheduler's default), so one
decode program serves mixed greedy/sampled slots without recompilation.
"""

import jax
import jax.numpy as jnp


def _top_k_filter(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Keep the k highest logits, -inf the rest (static k: part of the
    compiled program, an engine-level knob rather than a per-request one)."""
    kth = jax.lax.top_k(logits, top_k)[0][-1]
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filter: keep the smallest prefix of the sorted distribution
    whose mass reaches ``top_p`` (always at least the argmax). ``top_p >= 1``
    keeps everything, so the replicated decode program needs no branch."""
    sorted_logits = jnp.sort(logits)[::-1]
    probs = jax.nn.softmax(sorted_logits)
    cum = jnp.cumsum(probs)
    # token i is kept iff the mass BEFORE it is < top_p (the crossing token
    # is included); monotone cum makes this a prefix
    keep = jnp.sum((cum - probs < top_p).astype(jnp.int32))
    cutoff = sorted_logits[jnp.maximum(keep - 1, 0)]
    return jnp.where(logits >= cutoff, logits, -jnp.inf)


def sample_token(logits: jnp.ndarray, key: jax.Array,
                 temperature: jnp.ndarray, top_p: jnp.ndarray,
                 top_k: int = 0) -> jnp.ndarray:
    """One next-token id (int32) from unnormalized ``logits`` (V,) fp32.

    temperature/top_p are traced per-slot scalars; top_k is static.
    Greedy (temperature <= 0) is computed unconditionally and selected with
    a ``where`` — both paths are cheap relative to the forward, and the
    single program keeps mixed-slot batches on one compiled decode step.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        scaled = _top_k_filter(scaled, top_k)
    scaled = _top_p_filter(scaled, top_p)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def slot_key(seed: jnp.ndarray, step: jnp.ndarray) -> jax.Array:
    """Per-slot, per-step PRNG key: request seed folded by decode step."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), step)
