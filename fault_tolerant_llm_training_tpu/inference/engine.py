"""Jitted prefill/decode engine over the trained modules.

The engine owns the device state of a serving process: the (possibly
tensor-parallel) params, the KV cache (paged block pools by default,
``kv_layout="ring"`` for the legacy per-slot ring buffers kept for
equivalence testing), and two families of compiled programs —

- **prefill**: one request's prompt through ``Transformer.forward_with_cache``
  into a single cache slot (B=1, S=bucket), sampling the first generated
  token from the last prompt position. Prompts are right-padded to a static
  **bucket** length; the whole bucket set is AOT-compiled at engine build
  (``jit(...).lower(...).compile()``), so serving never hits a compile stall
  mid-traffic — the same discipline as the trainer's AOT train step. Under
  the paged layout prefill is **chunked**: a prompt longer than the largest
  bucket streams through it in fixed-size chunks at increasing offsets
  (Sarathi-Serve's chunked prefill), so the bucket set caps COMPILE COUNT,
  not prompt length — any prompt up to ``max_len`` is served, and the host
  loop can be interrupted cleanly between chunks for the drain lifecycle.
- **decode**: one token for ALL slots at once (B=slots, S=1, per-slot
  offsets = cache lengths). The cache is donated (``donate_argnums``), so
  XLA aliases the pools/ring buffers in place; the paged layout additionally
  takes the scheduler's (slots, blocks_per_slot) block tables as a plain
  host argument each call.

Checkpoints restore through the existing cross-topology
``checkpoint/manager.py`` path (:meth:`InferenceEngine.from_checkpoint`):
the abstract TrainState is rebuilt exactly as the trainer builds it, params
land sharded on the serving mesh, and scan-form trunks are converted to the
loop form (``models/llama.py unstack_layer_params``) — the cached forward
runs the loop trunk only.

Numerics: the cached path reuses the training projections, the same RoPE
table values at absolute positions, and an attention kernel mirroring
``xla_attention`` — cached decode logits bit-match the uncached forward
(tests/test_inference.py).
"""

import logging
import os
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import TransformerConfig
from ..models.llama import Transformer, unstack_layer_params
from ..parallel.mesh import use_mesh
from ..parallel.sharding import param_shardings
from .kv_cache import (
    KVCache,
    PagedKVCache,
    blocks_per_slot,
    cache_shardings,
    init_cache,
    init_paged_cache,
)
from .sampler import sample_token, slot_key

logger = logging.getLogger()

DEFAULT_COMPILE_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "fault_tolerant_llm_training_tpu",
    "xla-cache")


def enable_compilation_cache(cache_dir: str = DEFAULT_COMPILE_CACHE_DIR
                             ) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Engine builds AOT-compile a decode program plus one prefill program per
    bucket; cold that dominates small-run wall time (16.8 s of the tiny CPU
    bench), warm it is a disk read. No-ops (returns False) when ``cache_dir``
    is empty, when the user already configured a cache (the
    ``JAX_COMPILATION_CACHE_DIR`` env var / prior config.update wins), or on
    jax versions without the option. Min-compile-time/entry-size floors drop
    to 0 so even the tiny test programs cache.
    """
    if not cache_dir:
        return False
    try:
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return True  # already configured (env var or earlier call)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # pragma: no cover - ancient jax
        return False
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:  # pragma: no cover - knob absent on this jax
            pass
    return True


def default_prefill_buckets(max_len: int, smallest: int = 16
                            ) -> Sequence[int]:
    """Power-of-two bucket ladder up to ``max_len`` (always included): a
    prompt pays at most 2x its own length in prefill compute, for
    log2(max_len/smallest) compiled programs."""
    buckets, b = [], smallest
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def _abstract(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=getattr(a, "sharding", None)),
        tree)


class InferenceEngine:
    """Slot-granular prefill/decode over a trained ``Transformer``.

    ``params`` is the bare 'params' collection of the checkpoint (scan or
    loop form — scan is converted). Host-side slot bookkeeping (which slot
    belongs to which request) lives in the scheduler; the engine only moves
    tensors.
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int = 2,
                 max_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 top_k: int = 0, cache_dtype=None, mesh=None,
                 kv_layout: str = "paged", kv_block_size: int = 16,
                 kv_num_blocks: Optional[int] = None):
        if kv_layout not in ("paged", "ring"):
            raise ValueError(f"unknown kv_layout {kv_layout!r}")
        if cfg.layer_impl == "scan":
            params = unstack_layer_params(params, cfg.n_layers)
            cfg = cfg.replace(layer_impl="loop")
        # remat only pays under grad; serving is forward-only
        self.cfg = cfg = cfg.replace(remat=False)
        self.mesh = mesh
        self.slots = slots
        self.max_len = max_len or cfg.seq_len
        self.top_k = top_k
        self.kv_layout = kv_layout
        self.restored_step: Optional[int] = None
        buckets = tuple(sorted(set(prefill_buckets
                                   or default_prefill_buckets(self.max_len))))
        if buckets[-1] > self.max_len:
            raise ValueError(f"prefill bucket {buckets[-1]} exceeds "
                             f"max_len {self.max_len}")
        self.prefill_buckets = buckets
        if kv_layout == "paged":
            self.block_size = kv_block_size
            self.max_blocks_per_slot = blocks_per_slot(self.max_len,
                                                       kv_block_size)
            self.num_blocks = (kv_num_blocks
                               or slots * self.max_blocks_per_slot + 1)
        self.model = Transformer(cfg)

        with use_mesh(mesh):
            shardings = param_shardings(params, mesh)
            if shardings is not None:
                params = jax.device_put(params, shardings)
            self.params = jax.tree_util.tree_map(jnp.asarray, params)
            cache = self._init_cache(cache_dtype)
            cs = cache_shardings(cache, mesh)
            self.cache = (jax.device_put(cache, cs) if cs is not None
                          else cache)
            self._build_programs()

    def _init_cache(self, dtype=None):
        if self.kv_layout == "paged":
            return init_paged_cache(self.cfg, self.slots, self.max_len,
                                    self.block_size, self.num_blocks,
                                    dtype=dtype)
        return init_cache(self.cfg, self.slots, self.max_len, dtype=dtype)

    # --- compiled programs -------------------------------------------------

    def _prefill_fn(self, params, cache, tokens, slot, prompt_len,
                    temperature, top_p, seed):
        """(1, bucket) prompt into cache slot ``slot``; returns the updated
        cache and the first sampled token. Pad positions beyond
        ``prompt_len`` do get written to the cache, but ``lengths`` masks
        them out, and decode overwrites each position before attending."""
        ksl = tuple(jax.lax.dynamic_slice_in_dim(l, slot, 1, 0)
                    for l in cache.k)
        vsl = tuple(jax.lax.dynamic_slice_in_dim(l, slot, 1, 0)
                    for l in cache.v)
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens, ksl, vsl,
            jnp.zeros((1,), jnp.int32), method="forward_with_cache")
        k = tuple(jax.lax.dynamic_update_slice_in_dim(l, n, slot, 0)
                  for l, n in zip(cache.k, nk))
        v = tuple(jax.lax.dynamic_update_slice_in_dim(l, n, slot, 0)
                  for l, n in zip(cache.v, nv))
        lengths = jax.lax.dynamic_update_slice(cache.lengths,
                                               prompt_len[None], (slot,))
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], prompt_len - 1, 1, 0)[0].astype(jnp.float32)
        tok = sample_token(last, slot_key(seed, jnp.int32(0)),
                           temperature, top_p, self.top_k)
        return KVCache(k=k, v=v, lengths=lengths), tok

    def _decode_fn(self, params, cache, tokens, active, temperature, top_p,
                   seeds, steps):
        """One token for every slot: feed each slot's last token at its
        cache length, sample the next. Inactive slots still run (static
        shapes) but their lengths do not advance, so their repeated write
        lands on the same masked position and is overwritten at the next
        prefill."""
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens[:, None], cache.k, cache.v,
            cache.lengths, method="forward_with_cache")
        last = logits[:, 0].astype(jnp.float32)
        keys = jax.vmap(slot_key)(seeds, steps)
        toks = jax.vmap(sample_token, in_axes=(0, 0, 0, 0, None))(
            last, keys, temperature, top_p, self.top_k)
        lengths = cache.lengths + active.astype(jnp.int32)
        return KVCache(k=nk, v=nv, lengths=lengths), toks

    def _paged_prefill_fn(self, params, cache, block_row, tokens, slot,
                          chunk_start, chunk_len, temperature, top_p, seed):
        """One prefill CHUNK: (1, bucket) tokens at absolute positions
        ``chunk_start + [0, chunk_len)`` written through the slot's block
        ``block_row`` (blocks_per_slot,); pad positions past ``chunk_len``
        divert to null block 0 (unlike the ring path nothing may scribble
        past the slot's allocation). Returns the updated cache and a token
        sampled from the chunk's last real position — meaningful on the
        FINAL chunk (the host loop discards the rest: intermediate chunks'
        last logits predict tokens the prompt already contains)."""
        valid = (jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
                 < chunk_len)
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens, cache.k, cache.v, chunk_start[None],
            block_tables=block_row[None, :], write_valid=valid,
            method="forward_with_cache")
        lengths = jax.lax.dynamic_update_slice(
            cache.lengths, (chunk_start + chunk_len)[None], (slot,))
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], chunk_len - 1, 1, 0)[0].astype(jnp.float32)
        tok = sample_token(last, slot_key(seed, jnp.int32(0)),
                           temperature, top_p, self.top_k)
        return PagedKVCache(k=nk, v=nv, lengths=lengths), tok

    def _paged_decode_fn(self, params, cache, block_tables, tokens, active,
                         temperature, top_p, seeds, steps):
        """One token for every slot through the block tables; inactive
        slots still run (static shapes) but their write diverts to the
        null block and their lengths do not advance."""
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens[:, None], cache.k, cache.v,
            cache.lengths, block_tables=block_tables,
            write_valid=active[:, None], method="forward_with_cache")
        last = logits[:, 0].astype(jnp.float32)
        keys = jax.vmap(slot_key)(seeds, steps)
        toks = jax.vmap(sample_token, in_axes=(0, 0, 0, 0, None))(
            last, keys, temperature, top_p, self.top_k)
        lengths = cache.lengths + active.astype(jnp.int32)
        return PagedKVCache(k=nk, v=nv, lengths=lengths), toks

    def _build_programs(self):
        p_abs, c_abs = _abstract(self.params), _abstract(self.cache)
        scalar_i = jax.ShapeDtypeStruct((), jnp.int32)
        scalar_f = jax.ShapeDtypeStruct((), jnp.float32)
        slots_i = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        slots_f = jax.ShapeDtypeStruct((self.slots,), jnp.float32)
        slots_b = jax.ShapeDtypeStruct((self.slots,), jnp.bool_)
        self._prefill = {}
        if self.kv_layout == "paged":
            tables_abs = jax.ShapeDtypeStruct(
                (self.slots, self.max_blocks_per_slot), jnp.int32)
            row_abs = jax.ShapeDtypeStruct((self.max_blocks_per_slot,),
                                           jnp.int32)
            self._decode = jax.jit(
                self._paged_decode_fn, donate_argnums=(1,)).lower(
                p_abs, c_abs, tables_abs, slots_i, slots_b, slots_f,
                slots_f, slots_i, slots_i).compile()
            for b in self.prefill_buckets:
                tok_abs = jax.ShapeDtypeStruct((1, b), jnp.int32)
                self._prefill[b] = jax.jit(
                    self._paged_prefill_fn, donate_argnums=(1,)).lower(
                    p_abs, c_abs, row_abs, tok_abs, scalar_i, scalar_i,
                    scalar_i, scalar_f, scalar_f, scalar_i).compile()
            return
        self._decode = jax.jit(self._decode_fn, donate_argnums=(1,)).lower(
            p_abs, c_abs, slots_i, slots_b, slots_f, slots_f, slots_i,
            slots_i).compile()
        for b in self.prefill_buckets:
            tok_abs = jax.ShapeDtypeStruct((1, b), jnp.int32)
            self._prefill[b] = jax.jit(
                self._prefill_fn, donate_argnums=(1,)).lower(
                p_abs, c_abs, tok_abs, scalar_i, scalar_i, scalar_f,
                scalar_f, scalar_i).compile()

    # --- host API ----------------------------------------------------------

    def prefill(self, slot: int, token_ids, block_row=None,
                temperature: float = 0.0, top_p: float = 1.0, seed: int = 0,
                stop_check: Optional[Callable[[], bool]] = None,
                on_chunk: Optional[Callable[[], None]] = None
                ) -> Optional[int]:
        """Prompt into ``slot``; returns the first generated token id.

        Ring layout: the prompt must fit the largest bucket (one shot).
        Paged layout: ``block_row`` (blocks_per_slot,) is the slot's block
        table row from the scheduler's allocator, and prompts LONGER than
        the largest bucket stream through it in chunks of that bucket size
        (the last chunk picks its best-fit bucket). ``on_chunk`` fires after
        every finished chunk; between chunks ``stop_check`` is consulted —
        if it returns True the prefill stops cleanly AFTER the current chunk
        and returns None (caller frees the blocks and reports the request
        unserved: the drain-lifecycle contract for mid-prompt signals).
        """
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = ids.size
        if self.kv_layout != "paged":
            if not 0 < n <= self.prefill_buckets[-1]:
                raise ValueError(f"prompt length {n} outside "
                                 f"(0, {self.prefill_buckets[-1]}]")
            bucket = next(b for b in self.prefill_buckets if b >= n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = ids
            self.cache, tok = self._prefill[bucket](
                self.params, self.cache, padded, np.int32(slot), np.int32(n),
                np.float32(temperature), np.float32(top_p), np.int32(seed))
            return int(tok)
        if not 0 < n <= self.max_len:
            raise ValueError(f"prompt length {n} outside (0, {self.max_len}]")
        if block_row is None:
            raise ValueError("paged prefill requires the slot's block_row")
        row = np.asarray(block_row, np.int32).reshape(-1)
        if row.shape[0] != self.max_blocks_per_slot:
            raise ValueError(f"block_row has {row.shape[0]} entries, "
                             f"expected {self.max_blocks_per_slot}")
        chunk = self.prefill_buckets[-1]
        start, tok = 0, None
        while start < n:
            m = min(chunk, n - start)
            bucket = next(b for b in self.prefill_buckets if b >= m)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :m] = ids[start:start + m]
            self.cache, tok = self._prefill[bucket](
                self.params, self.cache, row, padded, np.int32(slot),
                np.int32(start), np.int32(m), np.float32(temperature),
                np.float32(top_p), np.int32(seed))
            start += m
            if on_chunk is not None:
                on_chunk()
            if start < n and stop_check is not None and stop_check():
                return None  # interrupted between chunks; request unserved
        return int(tok)

    def decode_step(self, tokens, active, temperature, top_p, seeds, steps,
                    block_tables=None) -> np.ndarray:
        """One decode iteration over all slots; host arrays in/out. The
        paged layout additionally takes the scheduler's (slots,
        blocks_per_slot) block tables."""
        if self.kv_layout == "paged":
            if block_tables is None:
                raise ValueError("paged decode requires block_tables")
            self.cache, toks = self._decode(
                self.params, self.cache,
                np.asarray(block_tables, np.int32),
                np.asarray(tokens, np.int32), np.asarray(active, bool),
                np.asarray(temperature, np.float32),
                np.asarray(top_p, np.float32),
                np.asarray(seeds, np.int32), np.asarray(steps, np.int32))
            return np.asarray(toks)
        self.cache, toks = self._decode(
            self.params, self.cache,
            np.asarray(tokens, np.int32), np.asarray(active, bool),
            np.asarray(temperature, np.float32),
            np.asarray(top_p, np.float32),
            np.asarray(seeds, np.int32), np.asarray(steps, np.int32))
        return np.asarray(toks)

    def reset(self) -> None:
        """Zero all slot lengths (the buffers' stale contents are masked)."""
        with use_mesh(self.mesh):
            cache = self._init_cache(dtype=self.cache.k[0].dtype)
            cs = cache_shardings(cache, self.mesh)
            self.cache = (jax.device_put(cache, cs) if cs is not None
                          else cache)

    # --- construction from a training checkpoint ---------------------------

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str, job_id: str,
                        cfg: TransformerConfig, *, step: Optional[int] = None,
                        mesh=None, **engine_kwargs) -> "InferenceEngine":
        """Restore a training checkpoint and build an engine on it.

        ``cfg`` must be the architecture the checkpoint was trained with
        (scan/loop form included — the abstract TrainState has to match the
        saved tree); the restore itself is the trainer's own cross-topology
        path, so a checkpoint written on any mesh loads onto this one. The
        optimizer state is restored alongside (the Composite item layout is
        fixed) and dropped.
        """
        from ..checkpoint.manager import CheckpointManager
        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import param_pspecs
        from ..training.state import TrainState
        from ..training.step import make_optimizer
        from jax.sharding import NamedSharding

        model = Transformer(cfg)
        # only the opt_state TREE matters (restored then dropped); any
        # schedule yields the same optax.adamw structure
        optimizer = make_optimizer(1e-4, 1)
        dummy = jnp.zeros((1, cfg.seq_len), jnp.int32)

        def init_fn(key):
            params = model.init(key, dummy)["params"]
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=optimizer.init(params))

        # Orbax needs target shardings; without a serving mesh, restore onto
        # a trivial single-device mesh (replicated specs, device 0).
        restore_mesh = mesh or make_mesh(dp=1, devices=jax.devices()[:1])
        with use_mesh(restore_mesh):
            abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
            specs = param_pspecs(abstract)
            abstract = jax.tree_util.tree_map(
                lambda a, s: jax.ShapeDtypeStruct(
                    a.shape, a.dtype,
                    sharding=NamedSharding(restore_mesh, s)),
                abstract, specs,
                is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
            mngr = CheckpointManager(checkpoint_path, job_id,
                                     enable_async=False)
            state, _data, restored_step = mngr.restore(abstract, step=step)
            mngr.close()
        logger.info("Model loaded from checkpoint")  # ref: train.py:58
        engine = cls(cfg, state.params, mesh=mesh, **engine_kwargs)
        engine.restored_step = restored_step
        return engine
