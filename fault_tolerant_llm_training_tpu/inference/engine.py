"""Jitted prefill/decode engine over the trained modules.

The engine owns the device state of a serving process: the (possibly
tensor-parallel) params, the KV cache (paged block pools by default,
``kv_layout="ring"`` for the legacy per-slot ring buffers kept for
equivalence testing), and two families of compiled programs —

- **prefill**: one request's prompt through ``Transformer.forward_with_cache``
  into a single cache slot (B=1, S=bucket), sampling the first generated
  token from the last prompt position. Prompts are right-padded to a static
  **bucket** length; the whole bucket set is AOT-compiled at engine build
  (``jit(...).lower(...).compile()``), so serving never hits a compile stall
  mid-traffic — the same discipline as the trainer's AOT train step. Under
  the paged layout prefill is **chunked**: a prompt longer than the largest
  bucket streams through it in fixed-size chunks at increasing offsets
  (Sarathi-Serve's chunked prefill), so the bucket set caps COMPILE COUNT,
  not prompt length — any prompt up to ``max_len`` is served, and the host
  loop can be interrupted cleanly between chunks for the drain lifecycle.
  The chunk loop runs every chunk at an explicit absolute offset, which is
  also what makes PREFIX-CACHE hits cheap: ``prefill(start_pos=k)`` simply
  starts the loop at k, attending to the shared blocks' committed KV
  through the block row without recomputing them
  (inference/prefix_cache.py; ``enable_prefix_cache``). The one device op
  sharing needs — copy-on-write before resuming inside a shared block —
  is its own tiny AOT program (``cow_copy``), donated like the rest.
- **decode**: one token for ALL slots at once (B=slots, S=1, per-slot
  offsets = cache lengths). The cache is donated (``donate_argnums``), so
  XLA aliases the pools/ring buffers in place; the paged layout additionally
  takes the scheduler's (slots, blocks_per_slot) block tables as a plain
  host argument each call.

**Speculative decoding** (``spec_k > 0``, paged layout only) adds a second
model lifecycle inside the engine: a small DRAFT model (its own params,
its own paged block pool, its own AOT programs) proposes k tokens per
round, and the target scores all k+1 candidate positions in ONE verify
pass instead of k+1 decode dispatches —

- **draft-k**: ONE compiled program runs the k+1 chained draft micro-steps
  in a ``lax.fori_loop`` (feed ``[t_last, d_1 .. d_k]`` at offsets
  ``L .. L+k``; the final iteration only back-fills d_k's KV so a fully
  accepted round leaves the draft cache aligned), returning the proposals
  AND the post-filter distributions they were drawn from as device arrays
  — the host never syncs mid-round, so a round costs two dispatches total.
- **verify-k**: ONE compiled program scores the k+1 candidate positions —
  as chained S=1 micro-steps on the decode program's exact op shapes (see
  ``_verify_fn`` for why the single (slots, k+1) chunk through
  :meth:`Transformer.verify_with_cache` is numerically equivalent but not
  bitwise-pinned) — then the vectorized accept/resample kernel
  (sampler.py ``spec_accept``). Acceptance commits the prefix by setting
  the cache length to ``offset + accepted + 1``; the rejected suffix
  needs no device rollback — its stale KV sits past the committed length,
  masked by attention and overwritten next round. Greedy acceptance is
  exact argmax matching, so greedy speculative streams are BIT-identical
  to the non-speculative path (tests/test_spec_decode.py); sampled slots
  use distribution-preserving rejection sampling against the same
  per-slot temperature/top-p/top-k.

**Tree speculative decoding** (``spec_tree``, on top of spec mode) widens
each round from a k-chain to a branching token TREE at the same verify
cost: the draft proposes a top-k fan-out at every depth of its chain (the
siblings are free — they are top-k reads of distributions the chain
already computed), the flattened tree is scored in ONE ancestor-masked
verify forward (``models/llama.py tree_verify_with_cache`` over
``ops/attention.py paged_tree_attention``), and the accept walk
(sampler.py ``tree_accept``) takes the longest accepted PATH — so a round
whose primary proposal is rejected can still commit a sibling instead of
falling back to plain decode. The winning path's KV is committed by a
device-side remap inside the slot's own blocks (kv_cache.py
``remap_paged_path``); rejected branches rot as stale bytes past the
committed length, exactly the linear rejected-suffix story — no allocator
traffic per round. Tree shapes (``TreeShape``/``parse_spec_tree``,
serve.py ``--spec-tree``) compile into a (draft, verify) program ladder
keyed by fan-out tuple (:meth:`InferenceEngine._tree_pair`), so an
adaptive controller can shrink the tree with live acceptance. Under
``spec_verify_impl="exact"`` a tree round scores only its PRIMARY chain
through the k+1 chained S=1 micro-steps — the PR-4 escape hatch that
keeps greedy tree-spec streams bit-identical to non-speculative decode —
while ``"chunk"`` is the full multi-branch forward.

Checkpoints restore through the existing cross-topology
``checkpoint/manager.py`` path (:meth:`InferenceEngine.from_checkpoint`):
the abstract TrainState is rebuilt exactly as the trainer builds it, params
land sharded on the serving mesh, and scan-form trunks are converted to the
loop form (``models/llama.py unstack_layer_params``) — the cached forward
runs the loop trunk only.

Numerics: the cached path reuses the training projections, the same RoPE
table values at absolute positions, and an attention kernel mirroring
``xla_attention`` — cached decode logits bit-match the uncached forward
(tests/test_inference.py).
"""

import functools
import logging
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import TransformerConfig
from ..models.llama import Transformer, unstack_layer_params
from ..parallel.mesh import use_mesh
from ..parallel.sharding import param_shardings
# Re-exported for backward compatibility: serve.py, scripts/decode_bench.py
# and tests imported these from here before the cache wiring moved to
# utils/ (so the trainer can use it without importing inference/).
from ..utils.compile_cache import (  # noqa: F401
    DEFAULT_COMPILE_CACHE_DIR,
    enable_compilation_cache,
)
from .kv_cache import (
    KVCache,
    PagedKVCache,
    blocks_per_slot,
    cache_shardings,
    copy_kv_block,
    export_blocks,
    import_block_batch,
    import_blocks,
    init_cache,
    init_paged_cache,
    remap_paged_path,
)
from .sampler import (
    draft_key,
    sample_slot_tokens,
    sample_token,
    sample_token_with_probs,
    slot_key,
    spec_accept,
    tree_accept,
    tree_key,
    verify_key,
)

logger = logging.getLogger()


def default_prefill_buckets(max_len: int, smallest: int = 16
                            ) -> Sequence[int]:
    """Power-of-two bucket ladder up to ``max_len`` (always included): a
    prompt pays at most 2x its own length in prefill compute, for
    log2(max_len/smallest) compiled programs."""
    buckets, b = [], smallest
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def _abstract(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=getattr(a, "sharding", None)),
        tree)


class TreeShape:
    """STATIC structure of one speculative token tree.

    ``fanouts`` (f_1 .. f_depth, each >= 1) gives the branch width at each
    proposal depth: level l's f_l nodes are the draft's top-f_l candidates
    after the PRIMARY (first) node of level l-1, so the tree is the draft's
    one k-chain plus sibling fan-outs hanging off it — the chain costs the
    draft exactly what linear speculation costs, and the siblings are free
    top-k reads of distributions the chain already computed. ``(1,) * k``
    is therefore the linear k-chain itself.

    Flattened layout (what every consumer indexes by): row 0 is the root
    (the committed last token), rows ``level_start[l] ..
    level_start[l] + f_l`` are level l+1's nodes in proposal order, primary
    first. Node i's KV is written at cache position ``offset + i``; its
    rope position is ``offset + depths[i]``. Derived arrays are numpy and
    baked into the compiled programs as constants:

    - ``parents`` (S,): row index of each node's parent, -1 for the root.
    - ``depths`` (S,): proposal depth, root 0.
    - ``child_matrix`` (S, C): row i's children padded with -1 — the
      accept walk's transition table (sampler.py ``tree_accept``).
    - ``anc_mask`` (S, S) bool: ``anc_mask[r, j]`` iff j is on r's root
      path (ancestors, self, root) — the verify attention rule
      (ops/attention.py ``paged_tree_attention``).
    - ``primary_rows`` (depth,): the primary chain's row per level — what
      the ``exact`` verify mode scores.
    """

    def __init__(self, fanouts: Sequence[int]):
        fanouts = tuple(int(f) for f in fanouts)
        if not fanouts or any(f < 1 for f in fanouts):
            raise ValueError(f"tree fan-outs must be >= 1 per level, got "
                             f"{fanouts}")
        self.fanouts = fanouts
        self.depth = len(fanouts)
        self.size = 1 + sum(fanouts)                 # S rows incl. root
        self.c_max = max(fanouts)
        starts, s0 = [], 1
        for f in fanouts:
            starts.append(s0)
            s0 += f
        self.level_start = tuple(starts)
        self.primary_rows = tuple(starts)
        parents = np.full((self.size,), -1, np.int32)
        depths = np.zeros((self.size,), np.int32)
        child = np.full((self.size, self.c_max), -1, np.int32)
        prev_primary = 0
        for lvl, f in enumerate(fanouts):
            s0 = starts[lvl]
            for j in range(f):
                parents[s0 + j] = prev_primary
                depths[s0 + j] = lvl + 1
                child[prev_primary, j] = s0 + j
            prev_primary = s0
        self.parents, self.depths, self.child_matrix = parents, depths, child
        anc = np.zeros((self.size, self.size), bool)
        for r in range(self.size):
            anc[r, 0] = True
            a = r
            while a >= 0:
                anc[r, a] = True
                a = int(parents[a])
        self.anc_mask = anc

    def shrink_to(self, budget: int) -> "TreeShape":
        """The largest sub-shape spending at most ``budget`` draft tokens
        (``sum(fanouts)``): trailing fan-outs shed width first, then whole
        levels — so an adaptive controller walking its k ladder down maps
        each rung to a deterministic smaller tree, and budget 1 is always
        the linear single-proposal round."""
        budget = max(1, int(budget))
        f = list(self.fanouts)
        while sum(f) > budget:
            for i in range(len(f) - 1, -1, -1):
                if f[i] > 1:
                    f[i] -= 1
                    break
            else:
                f.pop()
        f = tuple(f)
        return self if f == self.fanouts else TreeShape(f)

    def __repr__(self):
        return f"TreeShape({','.join(str(f) for f in self.fanouts)})"


def parse_spec_tree(spec) -> TreeShape:
    """``--spec-tree`` value into a :class:`TreeShape`: a ``"2,2,1"``-style
    comma list of per-depth fan-outs, a sequence of ints, or an already
    built shape (passed through)."""
    if isinstance(spec, TreeShape):
        return spec
    if isinstance(spec, str):
        try:
            spec = [int(p) for p in spec.replace(" ", "").split(",") if p]
        except ValueError:
            raise ValueError(f"bad --spec-tree {spec!r}: want a comma list "
                             f"of per-depth fan-outs, e.g. '2,2,1'")
    return TreeShape(spec)


class InferenceEngine:
    """Slot-granular prefill/decode over a trained ``Transformer``.

    ``params`` is the bare 'params' collection of the checkpoint (scan or
    loop form — scan is converted). Host-side slot bookkeeping (which slot
    belongs to which request) lives in the scheduler; the engine only moves
    tensors.
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int = 2,
                 max_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 top_k: int = 0, cache_dtype=None, mesh=None,
                 kv_layout: str = "paged", kv_block_size: int = 16,
                 kv_num_blocks: Optional[int] = None,
                 draft_cfg: Optional[TransformerConfig] = None,
                 draft_params=None, spec_k: int = 0,
                 draft_num_blocks: Optional[int] = None,
                 spec_verify_impl: str = "exact",
                 spec_tree=None,
                 prefix_cache: bool = True,
                 paged_kernel: str = "gather",
                 prefill_batch: int = 1,
                 kv_dtype: str = "bf16",
                 adapter_rank: int = 0,
                 adapter_num_pages: int = 0,
                 adapter_page_elems: int = 0):
        if kv_layout not in ("paged", "ring"):
            raise ValueError(f"unknown kv_layout {kv_layout!r}")
        if kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}: 'bf16' "
                             f"(plain pools) or 'int8' (quantized pools "
                             f"with per-(block, kv-head) fp32 scales — "
                             f"kv_cache.QuantPool)")
        if kv_dtype == "int8":
            if kv_layout != "paged":
                raise ValueError("kv_dtype='int8' requires the paged KV "
                                 "layout: the scale pool is per-block, and "
                                 "the ring path has no block granularity "
                                 "to hang scales on")
            if cache_dtype is not None and (jnp.dtype(cache_dtype)
                                            != jnp.dtype(jnp.int8)):
                raise ValueError(
                    f"kv_dtype='int8' conflicts with cache_dtype="
                    f"{jnp.dtype(cache_dtype).name!r}: pass one or the "
                    f"other")
            cache_dtype = jnp.int8
        elif cache_dtype is not None and (jnp.dtype(cache_dtype)
                                          == jnp.dtype(jnp.int8)):
            kv_dtype = "int8"  # dtype request IS the mode switch
            if kv_layout != "paged":
                raise ValueError("int8 cache_dtype requires the paged KV "
                                 "layout")
        self.kv_dtype = kv_dtype
        if paged_kernel not in ("gather", "pallas"):
            raise ValueError(
                f"unknown paged_kernel {paged_kernel!r}: 'gather' "
                f"(assemble blocks then run the ring kernel — the "
                f"bit-exact reference) or 'pallas' (read pool blocks in "
                f"place through the table, ops/paged_attention.py — equal "
                f"within fp32 accumulation tolerance)")
        if paged_kernel != "gather" and kv_layout != "paged":
            raise ValueError("paged_kernel selection requires the paged "
                             "KV layout")
        self.paged_kernel = paged_kernel
        if cfg.layer_impl == "scan":
            params = unstack_layer_params(params, cfg.n_layers)
            cfg = cfg.replace(layer_impl="loop")
        # remat only pays under grad; serving is forward-only
        self.cfg = cfg = cfg.replace(remat=False,
                                     paged_kernel=paged_kernel)
        self.mesh = mesh
        self.slots = slots
        self.max_len = max_len or cfg.seq_len
        self.top_k = top_k
        self.kv_layout = kv_layout
        self.restored_step: Optional[int] = None
        self.draft_restored_step: Optional[int] = None
        buckets = tuple(sorted(set(prefill_buckets
                                   or default_prefill_buckets(self.max_len))))
        if buckets[-1] > self.max_len:
            raise ValueError(f"prefill bucket {buckets[-1]} exceeds "
                             f"max_len {self.max_len}")
        self.prefill_buckets = buckets
        # Packed multi-request prefill (prefill_batch > 1): a second AOT
        # bucket ladder whose programs run P requests' next chunks in ONE
        # (P, bucket) dispatch — the scheduler's packed admission lane.
        self.prefill_batch = int(prefill_batch)
        if not 1 <= self.prefill_batch <= slots:
            raise ValueError(
                f"prefill_batch {prefill_batch} outside [1, slots={slots}]: "
                f"each packed row prefills into its own cache slot")
        if self.prefill_batch > 1 and kv_layout != "paged":
            raise ValueError("prefill_batch > 1 requires the paged KV "
                             "layout (each packed row writes through its "
                             "own block-table row)")
        if kv_layout == "paged":
            self.block_size = kv_block_size
            self.max_blocks_per_slot = blocks_per_slot(self.max_len,
                                                       kv_block_size)
            self.num_blocks = (kv_num_blocks
                               or slots * self.max_blocks_per_slot + 1)
        # Content-addressed prefix reuse (inference/prefix_cache.py): the
        # scheduler builds the radix tree only for engines that advertise
        # it. Paged-only — sharing is a property of the block indirection.
        self.enable_prefix_cache = bool(prefix_cache) and kv_layout == "paged"
        self.model = Transformer(cfg)

        # --- speculative decoding: second model lifecycle ------------------
        self.spec_k = int(spec_k)
        self.draft_cfg = None
        self.draft_model = None
        if self.spec_k:
            if kv_layout != "paged":
                raise ValueError("speculative decoding requires the paged "
                                 "KV layout (masked null-block writes are "
                                 "what make rejected-suffix rollback free)")
            if draft_cfg is None or draft_params is None:
                raise ValueError("spec_k > 0 requires draft_cfg and "
                                 "draft_params")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}: accept/resample compares the two "
                    f"models' distributions token-for-token")
            if not 1 <= self.spec_k < self.max_len:
                raise ValueError(f"spec_k {spec_k} outside [1, max_len)")
            if self.prefill_batch > 1:
                raise ValueError(
                    "prefill_batch > 1 and speculative decoding are "
                    "mutually exclusive: spec-mode prefill streams the "
                    "DRAFT pool sequentially after the target phase, and "
                    "packing that second lifecycle is a separate program "
                    "family")
            if spec_verify_impl not in ("exact", "chunk"):
                raise ValueError(
                    f"unknown spec_verify_impl {spec_verify_impl!r}: "
                    f"'exact' (k+1 chained S=1 micro-steps — greedy streams "
                    f"bit-identical to the non-speculative path by "
                    f"construction; the win is dispatch elimination, which "
                    f"pays on accelerators) or 'chunk' (one (slots, k+1) "
                    f"forward — additionally batches the verify FLOPs, but "
                    f"bf16 GEMM accumulation is shape-dependent and a "
                    f"one-ulp logit near-tie can flip an argmax vs the S=1 "
                    f"decode program)")
            self.spec_verify_impl = spec_verify_impl
            if draft_cfg.layer_impl == "scan":
                draft_params = unstack_layer_params(draft_params,
                                                    draft_cfg.n_layers)
                draft_cfg = draft_cfg.replace(layer_impl="loop")
            # the draft reads its pool through the same kernel: a spec
            # round's S=1 micro-steps are exactly the decode shapes the
            # in-place kernel serves
            self.draft_cfg = draft_cfg = draft_cfg.replace(
                remat=False, paged_kernel=self.paged_kernel)
            self.draft_num_blocks = (draft_num_blocks
                                     or slots * self.max_blocks_per_slot + 1)
            self.draft_model = Transformer(draft_cfg)
        elif draft_cfg is not None or draft_params is not None:
            raise ValueError("draft model given but spec_k == 0")

        # --- tree speculative decoding: branching rounds -------------------
        self.spec_tree: Optional[TreeShape] = None
        if spec_tree is not None:
            if not self.spec_k:
                raise ValueError("spec_tree requires speculative decoding "
                                 "(spec_k > 0 with a draft model): the tree "
                                 "is a widening of the spec round, not a "
                                 "third lifecycle")
            shape = parse_spec_tree(spec_tree)
            if shape.size >= self.max_len:
                raise ValueError(f"tree shape {shape} has {shape.size} rows "
                                 f">= max_len {self.max_len}: the verify "
                                 f"window must fit a slot")
            self.spec_tree = shape
            # refeed width: the max tokens one round can emit (depth
            # accepted + bonus). Fixed across the shrink ladder so every
            # rung's draft program shares one refeed layout, and doubles
            # as the draft-key stream stride (rungs never alias).
            self._tree_refeed = shape.depth + 1

        # --- multi-tenant LoRA adapter serving (inference/adapters.py) -----
        # A THIRD paged pool next to the target/draft KV pools: flat fp32
        # pages holding per-adapter low-rank factors, page 0 the reserved
        # null page. The fused programs take (pool, per-slot page rows,
        # per-slot scales) as trailing args ONLY when adapter_rank > 0, so
        # a no-adapter engine's programs are byte-identical to before; the
        # pool is passed per call (like params, never donated), which is
        # what makes page-in and hot-swap recompile-free.
        self.adapter_rank = int(adapter_rank)
        self.adapters = None
        self._adapter_layout = None
        self.adapter_pool = None
        if self.adapter_rank:
            if kv_layout != "paged":
                raise ValueError("adapter serving requires the paged KV "
                                 "layout (the adapter pool reuses the "
                                 "block-pool substrate)")
            if self.spec_k:
                raise ValueError(
                    "adapter serving and speculative decoding are mutually "
                    "exclusive: the draft model has no per-tenant factors, "
                    "so a draft proposal distribution would diverge from "
                    "every adapter's target and the verify pass would "
                    "reject its way back to plain decode")
            from .adapters import AdapterLayout, AdapterManager

            self._adapter_layout = AdapterLayout.from_cfg(
                cfg, self.adapter_rank,
                page_elems=adapter_page_elems or None)
            per = self._adapter_layout.pages_per_adapter
            # default pool: 4 resident adapters + the null page
            self.adapter_num_pages = int(adapter_num_pages) or 4 * per + 1
            self.adapter_pool = jnp.zeros(
                (self.adapter_num_pages, self._adapter_layout.page_elems),
                jnp.float32)
            self.adapters = AdapterManager(
                self._adapter_layout, self.adapter_num_pages,
                self._write_adapter_pages)
        elif adapter_num_pages or adapter_page_elems:
            raise ValueError("adapter pool sizing given but "
                             "adapter_rank == 0")

        with use_mesh(mesh):
            shardings = param_shardings(params, mesh)
            if shardings is not None:
                params = jax.device_put(params, shardings)
            self.params = jax.tree_util.tree_map(jnp.asarray, params)
            cache = self._init_cache(cache_dtype)
            cs = cache_shardings(cache, mesh)
            self.cache = (jax.device_put(cache, cs) if cs is not None
                          else cache)
            if self.spec_k:
                dsh = param_shardings(draft_params, mesh)
                if dsh is not None:
                    draft_params = jax.device_put(draft_params, dsh)
                self.draft_params = jax.tree_util.tree_map(jnp.asarray,
                                                           draft_params)
                dcache = self._init_draft_cache(cache_dtype)
                dcs = cache_shardings(dcache, mesh)
                self.draft_cache = (jax.device_put(dcache, dcs)
                                    if dcs is not None else dcache)
            self._build_programs()

    def _init_cache(self, dtype=None):
        if self.kv_layout == "paged":
            return init_paged_cache(self.cfg, self.slots, self.max_len,
                                    self.block_size, self.num_blocks,
                                    dtype=dtype)
        return init_cache(self.cfg, self.slots, self.max_len, dtype=dtype)

    def _init_draft_cache(self, dtype=None):
        return init_paged_cache(self.draft_cfg, self.slots, self.max_len,
                                self.block_size, self.draft_num_blocks,
                                dtype=dtype)

    # --- adapter pool (multi-tenant LoRA) ----------------------------------

    def _write_adapter_pages(self, pages, values) -> None:
        """Land one adapter's flattened factors in pool rows ``pages`` —
        the AdapterManager's device write. A host-side scatter outside any
        compiled program: the pool is a per-call input (never donated), so
        the next dispatch simply reads the new bytes — no recompile."""
        idx = np.asarray(pages, np.int32)
        self.adapter_pool = self.adapter_pool.at[idx].set(
            jnp.asarray(values, jnp.float32))

    def _adapter_operand(self, apool, arows, ascales):
        """Traced: gather each row's adapter pages from the pool in ONE
        table lookup (the scalar-prefetched-table trick the paged KV
        kernels use — rows of the null adapter hit zero page 0) and slice
        the flat bytes into per-layer LoRA factor tuples for
        ``forward_with_cache``. None when the engine has no adapters —
        the programs trace exactly as before."""
        if apool is None:
            return None
        flat = apool[arows].reshape(arows.shape[0], -1)
        layers = self._adapter_layout.slice_layers(flat)
        return [(a_q, b_q, a_v, b_v, ascales)
                for (a_q, b_q, a_v, b_v) in layers]

    def _null_adapter_args(self, batch: int):
        """All-null (base-only) host-side adapter rows/scales for
        ``batch`` rows — what the host API substitutes when the caller
        passes none on an adapter-enabled engine."""
        p = self._adapter_layout.pages_per_adapter
        return (np.zeros((batch, p), np.int32),
                np.zeros((batch,), np.float32))

    # --- compiled programs -------------------------------------------------

    def _prefill_fn(self, params, cache, tokens, slot, prompt_len,
                    temperature, top_p, seed):
        """(1, bucket) prompt into cache slot ``slot``; returns the updated
        cache and the first sampled token. Pad positions beyond
        ``prompt_len`` do get written to the cache, but ``lengths`` masks
        them out, and decode overwrites each position before attending."""
        ksl = tuple(jax.lax.dynamic_slice_in_dim(l, slot, 1, 0)
                    for l in cache.k)
        vsl = tuple(jax.lax.dynamic_slice_in_dim(l, slot, 1, 0)
                    for l in cache.v)
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens, ksl, vsl,
            jnp.zeros((1,), jnp.int32), method="forward_with_cache")
        k = tuple(jax.lax.dynamic_update_slice_in_dim(l, n, slot, 0)
                  for l, n in zip(cache.k, nk))
        v = tuple(jax.lax.dynamic_update_slice_in_dim(l, n, slot, 0)
                  for l, n in zip(cache.v, nv))
        lengths = jax.lax.dynamic_update_slice(cache.lengths,
                                               prompt_len[None], (slot,))
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], prompt_len - 1, 1, 0)[0].astype(jnp.float32)
        tok = sample_token(last, slot_key(seed, jnp.int32(0)),
                           temperature, top_p, self.top_k)
        return KVCache(k=k, v=v, lengths=lengths), tok

    def _decode_fn(self, params, cache, tokens, active, temperature, top_p,
                   seeds, steps):
        """One token for every slot: feed each slot's last token at its
        cache length, sample the next. Inactive slots still run (static
        shapes) but their lengths do not advance, so their repeated write
        lands on the same masked position and is overwritten at the next
        prefill."""
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens[:, None], cache.k, cache.v,
            cache.lengths, method="forward_with_cache")
        last = logits[:, 0].astype(jnp.float32)
        toks = sample_slot_tokens(last, seeds, steps, temperature, top_p,
                                  self.top_k)
        lengths = cache.lengths + active.astype(jnp.int32)
        return KVCache(k=nk, v=nv, lengths=lengths), toks

    def _paged_prefill_fn(self, model, params, cache, block_row, tokens,
                          slot, chunk_start, chunk_len, temperature, top_p,
                          seed, apool=None, arow=None, ascale=None):
        """One prefill CHUNK: (1, bucket) tokens at absolute positions
        ``chunk_start + [0, chunk_len)`` written through the slot's block
        ``block_row`` (blocks_per_slot,); pad positions past ``chunk_len``
        divert to null block 0 (unlike the ring path nothing may scribble
        past the slot's allocation). Returns the updated cache and a token
        sampled from the chunk's last real position — meaningful on the
        FINAL chunk (the host loop discards the rest: intermediate chunks'
        last logits predict tokens the prompt already contains).
        ``model`` is bound with functools.partial before jit — the same
        program body prefills the target and (spec mode) the draft."""
        valid = (jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
                 < chunk_len)
        adapter = (None if apool is None else self._adapter_operand(
            apool, arow[None, :], ascale[None]))
        logits, (nk, nv) = model.apply(
            {"params": params}, tokens, cache.k, cache.v, chunk_start[None],
            block_tables=block_row[None, :], write_valid=valid,
            adapter=adapter, method="forward_with_cache")
        lengths = jax.lax.dynamic_update_slice(
            cache.lengths, (chunk_start + chunk_len)[None], (slot,))
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], chunk_len - 1, 1, 0)[0].astype(jnp.float32)
        tok = sample_token(last, slot_key(seed, jnp.int32(0)),
                           temperature, top_p, self.top_k)
        return PagedKVCache(k=nk, v=nv, lengths=lengths), tok

    def _packed_prefill_fn(self, model, params, cache, block_rows, tokens,
                           slots, chunk_start, chunk_len, active,
                           temperature, top_p, seeds, apool=None,
                           arows=None, ascales=None):
        """P prefill CHUNKS in ONE dispatch: row i is request i's next
        (1, bucket) chunk at its OWN absolute offset ``chunk_start[i]``
        through its OWN block-table row — the batched sibling of
        ``_paged_prefill_fn``. Inactive pad rows (fewer than P requests
        share this round's bucket) run with all-False write_valid, so
        their writes divert to the null block and their lengths are left
        alone.

        Bit-exactness vs sequential B=1 prefill: the batch dim is a
        PARALLEL dim of every GEMM — each row's contraction shapes are
        exactly the (1, bucket) program's, unlike the S=1 -> S=k+1
        chunk-verify case where the contraction itself changes shape —
        and the per-row epilogue below is a static unroll whose ops
        (scalar length update, (V,) ``sample_token``) are the sequential
        program's exact shapes. Packed streams are therefore bit-identical
        to sequential prefill on the gather impl (asserted, not assumed:
        tests/test_paged_kv.py, the bench receipt)."""
        p_rows, bucket = tokens.shape
        valid = ((jnp.arange(bucket, dtype=jnp.int32)[None, :]
                  < chunk_len[:, None]) & active[:, None])
        logits, (nk, nv) = model.apply(
            {"params": params}, tokens, cache.k, cache.v, chunk_start,
            block_tables=block_rows, write_valid=valid,
            adapter=self._adapter_operand(apool, arows, ascales),
            method="forward_with_cache")
        lengths = cache.lengths
        toks = []
        for i in range(p_rows):
            lengths = jnp.where(
                active[i],
                jax.lax.dynamic_update_slice(
                    lengths, (chunk_start[i] + chunk_len[i])[None],
                    (slots[i],)),
                lengths)
            last = jax.lax.dynamic_slice_in_dim(
                logits[i], jnp.maximum(chunk_len[i] - 1, 0), 1,
                0)[0].astype(jnp.float32)
            toks.append(sample_token(last, slot_key(seeds[i], jnp.int32(0)),
                                     temperature[i], top_p[i], self.top_k))
        return PagedKVCache(k=nk, v=nv, lengths=lengths), jnp.stack(toks)

    def _paged_decode_fn(self, params, cache, block_tables, tokens, active,
                         temperature, top_p, seeds, steps, apool=None,
                         arows=None, ascales=None):
        """One token for every slot through the block tables; inactive
        slots still run (static shapes) but their write diverts to the
        null block and their lengths do not advance. The sampling
        epilogue (sampler.py ``sample_slot_tokens``) is traced INTO the
        program: logits -> temperature/top-k/top-p -> fold_in(seed, step)
        sample all run device-side, so one dispatch ends in token ids and
        the host syncs 4 bytes per slot instead of a (slots, V) logits
        plane (the unfused comparison point is :meth:`decode_logits`)."""
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens[:, None], cache.k, cache.v,
            cache.lengths, block_tables=block_tables,
            write_valid=active[:, None],
            adapter=self._adapter_operand(apool, arows, ascales),
            method="forward_with_cache")
        last = logits[:, 0].astype(jnp.float32)
        toks = sample_slot_tokens(last, seeds, steps, temperature, top_p,
                                  self.top_k)
        lengths = cache.lengths + active.astype(jnp.int32)
        return PagedKVCache(k=nk, v=nv, lengths=lengths), toks

    def _paged_logits_fn(self, params, cache, block_tables, tokens, active,
                         apool=None, arows=None, ascales=None):
        """UNFUSED decode step: the identical forward, but the program
        ends at the last-position fp32 logits — sampling is left to the
        host (which then pays a full (slots, V) sync plus a second
        dispatch for the sampling math). Kept as the bench's baseline so
        the fused epilogue's win is measured, not asserted; streams
        bit-match the fused path because both feed the same
        ``sample_slot_tokens`` (sampler.py)."""
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens[:, None], cache.k, cache.v,
            cache.lengths, block_tables=block_tables,
            write_valid=active[:, None],
            adapter=self._adapter_operand(apool, arows, ascales),
            method="forward_with_cache")
        last = logits[:, 0].astype(jnp.float32)
        lengths = cache.lengths + active.astype(jnp.int32)
        return PagedKVCache(k=nk, v=nv, lengths=lengths), last

    def _burst_decode_fn(self, n, params, cache, block_tables, tokens,
                         active, temperature, top_p, seeds, steps,
                         apool=None, arows=None, ascales=None):
        """A BURST of n chained decode micro-steps in ONE compiled program
        — the plain-decode sibling of the draft-k loop (``_draft_k_fn``):
        a ``lax.fori_loop`` whose body is one S=1 forward + the fused
        sampling epilogue, each iteration writing the fed token's KV
        through the block tables and feeding its sample to the next. The
        host pays ONE dispatch and ONE sync for n tokens instead of n of
        each.

        Bit-exactness: the body's op shapes are EXACTLY the single-step
        decode program's (S=1 forward, same epilogue), so greedy burst
        streams are bit-identical to n sequential ``decode_step`` calls
        by construction — the same structural argument as the 'exact'
        spec-verify mode (shape-dependent bf16 GEMM accumulation is why
        identical shapes matter). Sampled slots match too: micro-step i
        samples under ``slot_key(seed, steps + i)``, the key sequential
        decode would use at that step.

        EOS cannot stop the loop device-side (that would cost a sync per
        micro-step, the thing being amortized): a slot that hits EOS
        mid-burst keeps generating and the SCHEDULER truncates at banking
        (``_bank_burst``), exactly like a rejected spec suffix — the
        overshoot KV is stale pool content past the committed length,
        masked and later overwritten. ``n`` is partial-bound before jit
        (the ladder pattern of ``_compile_spec_pair``)."""
        b = self.slots
        offsets = cache.lengths
        toks0 = jnp.zeros((b, n), jnp.int32)
        valid = active[:, None]
        # rows/scales are loop-invariant: gather + slice once, reuse in
        # every micro-step (the same per-slot factors all burst long)
        adapter = self._adapter_operand(apool, arows, ascales)

        def body(i, carry):
            ck, cv, cur, toks = carry
            logits, (nk, nv) = self.model.apply(
                {"params": params}, cur[:, None], ck, cv, offsets + i,
                block_tables=block_tables, write_valid=valid,
                adapter=adapter, method="forward_with_cache")
            last = logits[:, 0].astype(jnp.float32)
            nxt = sample_slot_tokens(last, seeds, steps + i, temperature,
                                     top_p, self.top_k)
            toks = jax.lax.dynamic_update_slice_in_dim(
                toks, nxt[:, None], i, axis=1)
            return nk, nv, nxt, toks

        ck, cv, _cur, toks = jax.lax.fori_loop(
            0, n, body, (cache.k, cache.v, tokens, toks0))
        lengths = jnp.where(active, offsets + n, cache.lengths)
        return PagedKVCache(k=ck, v=cv, lengths=lengths), toks

    def _cow_fn(self, cache, src, dst):
        """Copy-on-write: duplicate pool block ``src`` into ``dst`` across
        every layer's K and V pools (kv_cache.py ``copy_kv_block``). Run
        once at admission when a full-prompt prefix-cache hit must resume
        prefill inside its final shared block — the copy is bitwise, so
        the resumed stream stays bit-identical to an uncached run. The
        cache is donated: XLA rewrites one block row per pool in place."""
        return PagedKVCache(
            k=tuple(copy_kv_block(p, src, dst) for p in cache.k),
            v=tuple(copy_kv_block(p, src, dst) for p in cache.v),
            lengths=cache.lengths)

    def _draft_k_fn(self, k, params, cache, block_tables, tokens, offsets,
                    active, temperature, top_p, seeds, rounds):
        """All k chained draft micro-steps in ONE compiled program.

        Feeds ``[t_last, d_1 .. d_k]`` at offsets ``offsets + [0, k]``
        through a ``lax.fori_loop`` (the body — one draft forward — is
        traced once, so compile time is O(1) in k and the host pays one
        dispatch for the whole chain). Iteration i writes the fed token's
        KV through the draft block tables and samples proposal d_{i+1} with
        its post-filter distribution; a final trailing forward back-fills
        d_k's KV (sampling discarded) so a FULLY accepted round leaves the
        draft cache covering every emitted token — without it the next
        round's offsets would skip d_k's missing entry. (Folding that
        back-fill into a width-2 first micro-step was tried and measured
        SLOWER: S > 1 leaves the single-position decode attention path, and
        the generic chunk path's full-pool gather costs more than the one
        extra S=1 forward it saves.) Offsets come from the HOST's
        committed-token count, not cache.lengths: rejected suffixes from
        earlier rounds are rolled back simply by feeding the correct lower
        offset, their stale KV masked and overwritten.

        Returns (cache, draft_tokens (B, k) int32, draft_probs (B, k, V)
        fp32) — consumed by the verify program device-to-device.

        ``k`` is bound with functools.partial before jit (like the prefill
        programs bind ``model``): the adaptive-k ladder compiles the same
        body at several round widths. The PRNG stream stride stays
        ``spec_k + 1`` (the maximum width) whatever ``k`` is, so rounds
        run at different widths never reuse a draft key.
        """
        b = self.slots
        v = self.draft_cfg.vocab_size
        toks0 = jnp.zeros((b, k), jnp.int32)
        probs0 = jnp.zeros((b, k, v), jnp.float32)
        valid = active[:, None]

        def micro_step(i, cur, ck, cv):
            logits, (nk, nv) = self.draft_model.apply(
                {"params": params}, cur[:, None], ck, cv, offsets + i,
                block_tables=block_tables, write_valid=valid,
                method="forward_with_cache")
            return logits[:, 0].astype(jnp.float32), nk, nv

        def body(i, carry):
            ck, cv, cur, toks, probs = carry
            last, ck, cv = micro_step(i, cur, ck, cv)
            keys = jax.vmap(draft_key)(seeds, rounds * (self.spec_k + 1) + i)
            nxt, p = jax.vmap(sample_token_with_probs,
                              in_axes=(0, 0, 0, 0, None))(
                last, keys, temperature, top_p, self.top_k)
            toks = jax.lax.dynamic_update_slice_in_dim(
                toks, nxt[:, None], i, axis=1)
            probs = jax.lax.dynamic_update_slice_in_dim(
                probs, p[:, None, :], i, axis=1)
            return ck, cv, nxt, toks, probs

        ck, cv, cur, toks, probs = jax.lax.fori_loop(
            0, k, body, (cache.k, cache.v, tokens, toks0, probs0))
        _, ck, cv = micro_step(jnp.int32(k), cur, ck, cv)  # d_k KV back-fill
        lengths = jnp.where(active, offsets + k + 1, cache.lengths)
        return PagedKVCache(k=ck, v=cv, lengths=lengths), toks, probs

    def _verify_fn(self, k, params, cache, block_tables, tokens,
                   draft_tokens, draft_probs, offsets, active, temperature,
                   top_p, seeds, rounds):
        """Score all k+1 candidate positions in ONE compiled program and
        accept/resample (sampler.py ``spec_accept``).

        Two implementations of the scoring, selected by
        ``spec_verify_impl`` (same math, different numerics/perf point):

        - ``"exact"`` (default): k+1 chained S=1 micro-steps in a
          ``lax.fori_loop`` — the exact forward the decode program runs.
          Identical op shapes compile to identical GEMM accumulation
          orders, so the greedy bit-exactness invariant is STRUCTURAL.
          The host pays one dispatch for the whole verify; eliminating
          the k+1 decode dispatches is the speculative win on
          accelerators (the target FLOPs themselves are not reduced).
        - ``"chunk"``: one (B, k+1) forward through
          ``verify_with_cache`` — additionally batches the verify FLOPs
          into one GEMM pass, the extra win visible even where dispatch
          is free (the CPU bench). But bf16 GEMMs accumulate in a
          shape-dependent order, and a one-ulp logit near-tie is enough
          to flip an argmax between the S=1 and S=k+1 programs (observed
          once in ~10k greedy positions on the CPU bench: top-2 logits
          2.65625 vs 2.640625, the two programs picking opposite
          winners) — greedy equivalence is exact argmax matching on the
          CHUNK's logits, bitwise-equal to the non-speculative stream
          only up to such ties.

        Commits the accepted prefix by setting lengths to ``offsets +
        accepted + 1``; the rejected suffix's KV is stale pool content
        past that length — masked, then overwritten next round. Inactive
        slots write into the null block and keep their lengths.

        ``k`` is partial-bound like the draft program's (adaptive-k
        ladder); ``verify_key`` streams are per-ROUND, so width never
        enters the key schedule."""
        b = self.slots
        v = self.cfg.vocab_size
        seq = jnp.concatenate([tokens[:, None], draft_tokens], axis=1)
        valid = active[:, None]
        if self.spec_verify_impl == "chunk":
            chunk, (nk, nv) = self.model.apply(
                {"params": params}, seq, cache.k, cache.v, offsets,
                block_tables=block_tables, write_valid=valid,
                method="verify_with_cache")
            logits = chunk.astype(jnp.float32)
        else:
            logits0 = jnp.zeros((b, k + 1, v), jnp.float32)

            def body(i, carry):
                ck, cv, logits = carry
                cur = jax.lax.dynamic_slice_in_dim(seq, i, 1, axis=1)
                step, (sk, sv) = self.model.apply(
                    {"params": params}, cur, ck, cv, offsets + i,
                    block_tables=block_tables, write_valid=valid,
                    method="forward_with_cache")
                logits = jax.lax.dynamic_update_slice_in_dim(
                    logits, step.astype(jnp.float32), i, axis=1)
                return sk, sv, logits

            nk, nv, logits = jax.lax.fori_loop(
                0, k + 1, body, (cache.k, cache.v, logits0))
        keys = jax.vmap(verify_key)(seeds, rounds)
        out, acc = jax.vmap(spec_accept, in_axes=(0, 0, 0, 0, 0, 0, None))(
            draft_tokens, draft_probs, logits, keys,
            temperature, top_p, self.top_k)
        lengths = jnp.where(active, offsets + acc + 1, cache.lengths)
        return PagedKVCache(k=nk, v=nv, lengths=lengths), out, acc

    def _tree_draft_fn(self, shape, params, cache, block_tables, refeed,
                       refeed_len, offsets, active, temperature, top_p,
                       seeds, rounds):
        """Propose one token TREE per slot in ONE compiled program.

        The draft runs its ordinary linear chain — one refeed chunk plus
        depth-1 chained S=1 micro-steps — and the tree's branches fall out
        for free: at each level the PRIMARY child is the chain's own
        sample/argmax (drawn from the post-filter distribution q_l, which
        becomes its accept-test q row), and the f_l - 1 SIBLINGS are the
        top logits excluding it. A sibling is a deterministic pick, so its
        honest proposal law is the point mass at its token — its q row is
        the exact one-hot, under which ``tree_accept``'s test
        ``u * q(t) < p(t)`` reduces to accept-with-probability-p(t) and
        the residual fold to removing t from p: a valid rejection step
        that only ADDS acceptance chances on top of the primary chain.

        The REFEED chunk replaces linear spec's first micro-step + d_k
        back-fill: ``refeed`` (B, R) holds the tokens the PREVIOUS round
        emitted (count ``refeed_len``, bonus token last), written at
        positions ``offsets - refeed_len + 1 .. offsets``. A tree round
        can commit tokens the draft chain never fed (an accepted sibling),
        so the draft cache's last window is re-derived from the committed
        truth every round — which also covers the fresh bonus token, hence
        no separate back-fill. Invariant: before the chunk the draft KV is
        correct up to ``offsets - refeed_len``; after it, up to
        ``offsets``; the micro-steps then write the primary chain at
        ``offsets + 1 ..`` (stale beyond the commit, overwritten by the
        next refeed). R and the draft-key stride are the BASE shape's
        ``depth + 1`` whatever rung is running, so ladder rungs share one
        refeed layout and never alias a key.

        Returns (cache, tree_tokens (B, S) — row 0 the root token — and
        draft_probs (B, S, V) — row 0 zeros, primary rows q_l, sibling
        rows one-hots)."""
        b = self.slots
        v = self.draft_cfg.vocab_size
        s = shape.size
        r_w = refeed.shape[1]
        base = offsets - refeed_len + 1
        valid = ((jnp.arange(r_w, dtype=jnp.int32)[None, :]
                  < refeed_len[:, None]) & active[:, None])
        logits, (ck, cv) = self.draft_model.apply(
            {"params": params}, refeed, cache.k, cache.v, base,
            block_tables=block_tables, write_valid=valid,
            method="forward_with_cache")
        last = jnp.take_along_axis(
            logits, (refeed_len - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        t_last = jnp.take_along_axis(refeed, (refeed_len - 1)[:, None],
                                     axis=1)[:, 0]
        tree_toks = jnp.zeros((b, s), jnp.int32).at[:, 0].set(t_last)
        probs = jnp.zeros((b, s, v), jnp.float32)
        for lvl, f in enumerate(shape.fanouts):      # static unroll
            keys = jax.vmap(draft_key)(
                seeds, rounds * self._tree_refeed + lvl)
            nxt, p = jax.vmap(sample_token_with_probs,
                              in_axes=(0, 0, 0, 0, None))(
                last, keys, temperature, top_p, self.top_k)
            s0 = shape.level_start[lvl]
            tree_toks = tree_toks.at[:, s0].set(nxt)
            probs = probs.at[:, s0, :].set(p)
            if f > 1:
                masked = last.at[jnp.arange(b), nxt].set(-jnp.inf)
                _, sib = jax.lax.top_k(masked, f - 1)
                sib = sib.astype(jnp.int32)
                tree_toks = tree_toks.at[:, s0 + 1:s0 + f].set(sib)
                probs = probs.at[:, s0 + 1:s0 + f, :].set(
                    jax.nn.one_hot(sib, v, dtype=jnp.float32))
            if lvl < shape.depth - 1:
                step, (ck, cv) = self.draft_model.apply(
                    {"params": params}, nxt[:, None], ck, cv,
                    offsets + lvl + 1, block_tables=block_tables,
                    write_valid=active[:, None],
                    method="forward_with_cache")
                last = step[:, 0].astype(jnp.float32)
        lengths = jnp.where(active, offsets + shape.depth, cache.lengths)
        return (PagedKVCache(k=ck, v=cv, lengths=lengths), tree_toks,
                probs)

    def _tree_verify_fn(self, shape, params, cache, block_tables,
                        tree_tokens, draft_probs, offsets, active,
                        temperature, top_p, seeds, rounds):
        """Score one flattened token tree per slot and commit the winning
        path, in ONE compiled program.

        ``"chunk"`` mode is the real tree: a single (B, S) ancestor-masked
        forward (``tree_verify_with_cache`` — node KV at ``offsets + row``,
        rope at ``offsets + depth(row)``) scores every branch at once, the
        vmapped accept walk (sampler.py ``tree_accept``) picks the longest
        accepted path under ``tree_key``, and the epilogue REMAPS the
        winners' KV rows from tree-window to committed positions inside the
        slot's own blocks (kv_cache.py ``remap_paged_path``) — losers rot
        as stale bytes past the committed length, so a round still costs
        zero allocator traffic.

        ``"exact"`` mode scores only the PRIMARY chain through the linear
        k+1 chained S=1 micro-steps (:meth:`_verify_fn`, which also does
        the accept under ``verify_key``): the chain's rows land at their
        committed positions directly, so no remap — and the op shapes
        being the decode program's keeps greedy tree-spec streams
        bit-identical to non-speculative decode, the escape hatch the
        multi-branch chunk forward (shape-dependent bf16 accumulation)
        cannot offer. Siblings are proposed but never scored there.

        Returns (cache, out (B, depth+1), accepted (B,), path (B, depth))
        — ``path`` is the accepted nodes' tree rows, what the scheduler's
        branch-utilization gauge reads."""
        b = self.slots
        depth = shape.depth
        if self.spec_verify_impl == "chunk":
            tpos = (offsets[:, None]
                    + jnp.asarray(shape.depths, jnp.int32)[None, :])
            anc = jnp.asarray(shape.anc_mask)
            cm = jnp.asarray(shape.child_matrix, jnp.int32)
            valid = jnp.broadcast_to(active[:, None], tree_tokens.shape)
            logits, (nk, nv) = self.model.apply(
                {"params": params}, tree_tokens, cache.k, cache.v, offsets,
                block_tables=block_tables, tree_positions=tpos,
                anc_mask=anc, write_valid=valid,
                method="tree_verify_with_cache")
            logits = logits.astype(jnp.float32)
            keys = jax.vmap(tree_key)(seeds, rounds)
            out, path, acc = jax.vmap(
                lambda tt, dp, tl, ky, te, tp_: tree_accept(
                    tt, dp, tl, ky, te, tp_, cm, depth, self.top_k))(
                tree_tokens, draft_probs, logits, keys, temperature, top_p)
            nk = tuple(remap_paged_path(p, block_tables, offsets, path, acc)
                       for p in nk)
            nv = tuple(remap_paged_path(p, block_tables, offsets, path, acc)
                       for p in nv)
            lengths = jnp.where(active, offsets + acc + 1, cache.lengths)
            return PagedKVCache(k=nk, v=nv, lengths=lengths), out, acc, path
        prim = list(shape.primary_rows)
        new_cache, out, acc = self._verify_fn(
            depth, params, cache, block_tables, tree_tokens[:, 0],
            tree_tokens[:, prim], draft_probs[:, prim], offsets, active,
            temperature, top_p, seeds, rounds)
        path = jnp.broadcast_to(
            jnp.asarray(prim, jnp.int32)[None, :], (b, depth))
        return new_cache, out, acc, path

    def _adapter_abstract(self, batch=None):
        """Abstract trailing adapter args for the paged programs — a
        ``(pool, rows (batch, P), scales (batch,))`` triple (batch
        defaults to slots) and the B=1 prefill variant ``(pool, row (P,),
        scalar scale)``. Both EMPTY tuples when the engine has no
        adapters, so no-adapter lowerings are unchanged."""
        if not self.adapter_rank:
            return (), ()
        b = self.slots if batch is None else batch
        pool_abs = jax.ShapeDtypeStruct(
            (self.adapter_num_pages, self._adapter_layout.page_elems),
            jnp.float32)
        per = self._adapter_layout.pages_per_adapter
        return ((pool_abs, jax.ShapeDtypeStruct((b, per), jnp.int32),
                 jax.ShapeDtypeStruct((b,), jnp.float32)),
                (pool_abs, jax.ShapeDtypeStruct((per,), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.float32)))

    def _build_programs(self):
        p_abs, c_abs = _abstract(self.params), _abstract(self.cache)
        scalar_i = jax.ShapeDtypeStruct((), jnp.int32)
        scalar_f = jax.ShapeDtypeStruct((), jnp.float32)
        slots_i = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        slots_f = jax.ShapeDtypeStruct((self.slots,), jnp.float32)
        slots_b = jax.ShapeDtypeStruct((self.slots,), jnp.bool_)
        self._prefill = {}
        if self.kv_layout == "paged":
            tables_abs = jax.ShapeDtypeStruct(
                (self.slots, self.max_blocks_per_slot), jnp.int32)
            row_abs = jax.ShapeDtypeStruct((self.max_blocks_per_slot,),
                                           jnp.int32)
            # adapter-enabled engines append (pool, page rows, scales) to
            # the paged programs; without adapters the arg tuples are
            # empty and the lowered programs are byte-identical to before
            ad_slots, ad_one = self._adapter_abstract()
            self._decode = jax.jit(
                self._paged_decode_fn, donate_argnums=(1,)).lower(
                p_abs, c_abs, tables_abs, slots_i, slots_b, slots_f,
                slots_f, slots_i, slots_i, *ad_slots).compile()
            self._decode_logits = jax.jit(
                self._paged_logits_fn, donate_argnums=(1,)).lower(
                p_abs, c_abs, tables_abs, slots_i, slots_b,
                *ad_slots).compile()
            # burst programs compile on first use (decode_burst(n) —
            # serving picks ONE n, so the ladder is usually one rung)
            self._burst_programs = {}
            self._cow = jax.jit(
                self._cow_fn, donate_argnums=(0,)).lower(
                c_abs, scalar_i, scalar_i).compile()
            for b in self.prefill_buckets:
                tok_abs = jax.ShapeDtypeStruct((1, b), jnp.int32)
                self._prefill[b] = jax.jit(
                    functools.partial(self._paged_prefill_fn, self.model),
                    donate_argnums=(1,)).lower(
                    p_abs, c_abs, row_abs, tok_abs, scalar_i, scalar_i,
                    scalar_i, scalar_f, scalar_f, scalar_i,
                    *ad_one).compile()
            self._packed_prefill = {}
            if self.prefill_batch > 1:
                p = self.prefill_batch
                rows_abs = jax.ShapeDtypeStruct(
                    (p, self.max_blocks_per_slot), jnp.int32)
                p_i = jax.ShapeDtypeStruct((p,), jnp.int32)
                p_f = jax.ShapeDtypeStruct((p,), jnp.float32)
                p_b = jax.ShapeDtypeStruct((p,), jnp.bool_)
                ad_pack = self._adapter_abstract(batch=p)[0]
                for b in self.prefill_buckets:
                    tok_abs = jax.ShapeDtypeStruct((p, b), jnp.int32)
                    self._packed_prefill[b] = jax.jit(
                        functools.partial(self._packed_prefill_fn,
                                          self.model),
                        donate_argnums=(1,)).lower(
                        p_abs, c_abs, rows_abs, tok_abs, p_i, p_i, p_i,
                        p_b, p_f, p_f, p_i, *ad_pack).compile()
            if self.spec_k:
                dp_abs = _abstract(self.draft_params)
                dc_abs = _abstract(self.draft_cache)
                self._spec_programs = {}
                self._draft_k, self._verify = self._spec_pair(self.spec_k)
                if self.spec_tree is not None:
                    self._tree_programs = {}
                    self._tree_draft, self._tree_verify = self._tree_pair(
                        self.spec_tree)
                self._draft_prefill = {}
                for b in self.prefill_buckets:
                    tok_abs = jax.ShapeDtypeStruct((1, b), jnp.int32)
                    self._draft_prefill[b] = jax.jit(
                        functools.partial(self._paged_prefill_fn,
                                          self.draft_model),
                        donate_argnums=(1,)).lower(
                        dp_abs, dc_abs, row_abs, tok_abs, scalar_i,
                        scalar_i, scalar_i, scalar_f, scalar_f,
                        scalar_i).compile()
            return
        self._decode = jax.jit(self._decode_fn, donate_argnums=(1,)).lower(
            p_abs, c_abs, slots_i, slots_b, slots_f, slots_f, slots_i,
            slots_i).compile()
        for b in self.prefill_buckets:
            tok_abs = jax.ShapeDtypeStruct((1, b), jnp.int32)
            self._prefill[b] = jax.jit(
                self._prefill_fn, donate_argnums=(1,)).lower(
                p_abs, c_abs, tok_abs, scalar_i, scalar_i, scalar_f,
                scalar_f, scalar_i).compile()

    def _compile_spec_pair(self, k: int):
        """AOT-compile one (draft-k, verify) program pair at round width
        ``k``. The k-value is bound with functools.partial (the draft/
        verify bodies are width-generic); everything else — shardings,
        donation, op shapes per micro-step — matches the default pair, so
        a ladder rung's greedy stream is bit-identical to running the
        default pair with the extra proposals rejected."""
        p_abs, c_abs = _abstract(self.params), _abstract(self.cache)
        dp_abs = _abstract(self.draft_params)
        dc_abs = _abstract(self.draft_cache)
        slots_i = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        slots_f = jax.ShapeDtypeStruct((self.slots,), jnp.float32)
        slots_b = jax.ShapeDtypeStruct((self.slots,), jnp.bool_)
        tables_abs = jax.ShapeDtypeStruct(
            (self.slots, self.max_blocks_per_slot), jnp.int32)
        dtoks_abs = jax.ShapeDtypeStruct((self.slots, k), jnp.int32)
        dprobs_abs = jax.ShapeDtypeStruct(
            (self.slots, k, self.cfg.vocab_size), jnp.float32)
        draft = jax.jit(
            functools.partial(self._draft_k_fn, k),
            donate_argnums=(1,)).lower(
            dp_abs, dc_abs, tables_abs, slots_i, slots_i, slots_b,
            slots_f, slots_f, slots_i, slots_i).compile()
        verify = jax.jit(
            functools.partial(self._verify_fn, k),
            donate_argnums=(1,)).lower(
            p_abs, c_abs, tables_abs, slots_i, dtoks_abs, dprobs_abs,
            slots_i, slots_b, slots_f, slots_f, slots_i, slots_i).compile()
        return draft, verify

    def _compile_tree_pair(self, shape: TreeShape):
        """AOT-compile one (tree-draft, tree-verify) program pair for
        ``shape``. The shape is bound with functools.partial — its derived
        arrays (depths, ancestor mask, child matrix) bake into the
        programs as constants; the refeed width stays the BASE shape's so
        every rung shares one host-side refeed layout."""
        p_abs, c_abs = _abstract(self.params), _abstract(self.cache)
        dp_abs = _abstract(self.draft_params)
        dc_abs = _abstract(self.draft_cache)
        slots_i = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        slots_f = jax.ShapeDtypeStruct((self.slots,), jnp.float32)
        slots_b = jax.ShapeDtypeStruct((self.slots,), jnp.bool_)
        tables_abs = jax.ShapeDtypeStruct(
            (self.slots, self.max_blocks_per_slot), jnp.int32)
        refeed_abs = jax.ShapeDtypeStruct(
            (self.slots, self._tree_refeed), jnp.int32)
        ttoks_abs = jax.ShapeDtypeStruct((self.slots, shape.size), jnp.int32)
        tprobs_abs = jax.ShapeDtypeStruct(
            (self.slots, shape.size, self.cfg.vocab_size), jnp.float32)
        draft = jax.jit(
            functools.partial(self._tree_draft_fn, shape),
            donate_argnums=(1,)).lower(
            dp_abs, dc_abs, tables_abs, refeed_abs, slots_i, slots_i,
            slots_b, slots_f, slots_f, slots_i, slots_i).compile()
        verify = jax.jit(
            functools.partial(self._tree_verify_fn, shape),
            donate_argnums=(1,)).lower(
            p_abs, c_abs, tables_abs, ttoks_abs, tprobs_abs, slots_i,
            slots_b, slots_f, slots_f, slots_i, slots_i).compile()
        return draft, verify

    def _tree_pair(self, shape: TreeShape):
        """The compiled (tree-draft, tree-verify) pair for ``shape``,
        compiling on first use — the tree sibling of :meth:`_spec_pair`.
        Only shrinkages of the configured base shape are legal (the
        adaptive ladder walks ``TreeShape.shrink_to``), so the ladder is
        finitely bounded and every rung fits the base refeed layout."""
        if self.spec_tree is None:
            raise ValueError("engine built without a tree shape "
                             "(spec_tree unset)")
        shape = parse_spec_tree(shape)
        if (shape.depth > self.spec_tree.depth
                or shape.size > self.spec_tree.size):
            raise ValueError(f"tree rung {shape} exceeds the configured "
                             f"base shape {self.spec_tree}")
        pair = self._tree_programs.get(shape.fanouts)
        if pair is None:
            pair = self._compile_tree_pair(shape)
            self._tree_programs[shape.fanouts] = pair
        return pair

    def _compile_burst(self, n: int):
        """AOT-compile the n-token burst decode program (``n`` bound with
        functools.partial like the spec ladder's width)."""
        p_abs, c_abs = _abstract(self.params), _abstract(self.cache)
        slots_i = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        slots_f = jax.ShapeDtypeStruct((self.slots,), jnp.float32)
        slots_b = jax.ShapeDtypeStruct((self.slots,), jnp.bool_)
        tables_abs = jax.ShapeDtypeStruct(
            (self.slots, self.max_blocks_per_slot), jnp.int32)
        return jax.jit(
            functools.partial(self._burst_decode_fn, n),
            donate_argnums=(1,)).lower(
            p_abs, c_abs, tables_abs, slots_i, slots_b, slots_f, slots_f,
            slots_i, slots_i, *self._adapter_abstract()[0]).compile()

    def _burst_program(self, n: int):
        """The compiled n-token burst program, compiling on first use.
        A serving process runs one configured burst width, so this is at
        most a couple of one-time compiles (the scheduler's final partial
        burst clamps n to the smallest remaining budget)."""
        if self.kv_layout != "paged":
            raise ValueError("burst decode requires the paged KV layout "
                             "(the loop writes KV through block tables)")
        n = int(n)
        if not 1 <= n <= self.max_len:
            raise ValueError(f"burst width {n} outside [1, {self.max_len}]")
        prog = self._burst_programs.get(n)
        if prog is None:
            prog = self._compile_burst(n)
            self._burst_programs[n] = prog
        return prog

    def _spec_pair(self, k: int):
        """The compiled (draft-k, verify) pair for round width ``k``,
        compiling on first use. The default width ``spec_k`` is compiled
        at engine build (never a stall); other rungs compile once when an
        adaptive-k controller first requests them — the controller's
        ladder is O(log spec_k) wide, so a serving process pays at most a
        handful of one-time compiles over its whole lifetime, each inside
        an admission pause."""
        k = int(k)
        if not 1 <= k <= self.spec_k:
            raise ValueError(f"spec round width {k} outside "
                             f"[1, {self.spec_k}]")
        pair = self._spec_programs.get(k)
        if pair is None:
            pair = self._compile_spec_pair(k)
            self._spec_programs[k] = pair
        return pair

    # --- host API ----------------------------------------------------------

    def _prepare_params(self, params, current, what: str):
        """Validate a replacement param tree against the serving one
        (same structure, shapes, dtypes — the AOT programs were lowered
        against ``current``'s abstract tree and would otherwise fail
        opaquely at dispatch), then shard it exactly as ``__init__``
        does."""
        cur_leaves, cur_def = jax.tree_util.tree_flatten(current)
        new_leaves, new_def = jax.tree_util.tree_flatten(params)
        if cur_def != new_def:
            raise ValueError(f"{what} reload: param tree structure does "
                             f"not match the serving model")
        for c, n in zip(cur_leaves, new_leaves):
            if c.shape != n.shape or c.dtype != n.dtype:
                raise ValueError(
                    f"{what} reload: param leaf {n.shape}/{n.dtype} does "
                    f"not match serving {c.shape}/{c.dtype}")
        with use_mesh(self.mesh):
            shardings = param_shardings(params, self.mesh)
            if shardings is not None:
                params = jax.device_put(params, shardings)
            return jax.tree_util.tree_map(jnp.asarray, params)

    def reload_params(self, params) -> None:
        """Hot-swap the TARGET params under the existing AOT programs.

        No re-compile: every program takes params per call and only the
        cache is donated, so installing a new (structurally identical)
        tree is one device_put. The caller (deploy/reload.py) owns the
        surrounding lifecycle — pausing admission, letting the in-flight
        decode round finish, flushing the prefix cache whose KV was
        computed under the old weights — and hands the tree over in LOOP
        form (the engine converted at build; scan-form checkpoints go
        through ``unstack_layer_params`` first, as the constructor did)."""
        self.params = self._prepare_params(params, self.params, "target")

    def reload_draft_params(self, params) -> None:
        """Hot-swap the DRAFT params (speculative decoding) in the same
        admission pause as :meth:`reload_params`. The draft cache's
        content becomes stale draft-KV of the OLD draft — harmless: each
        round re-addresses only the committed prefix, and in-flight
        slots' acceptance just dips until the new draft's KV dominates
        (the adaptive-k controller resets alongside)."""
        if not self.spec_k:
            raise ValueError("engine built without a draft model "
                             "(spec_k == 0)")
        self.draft_params = self._prepare_params(params, self.draft_params,
                                                 "draft")

    def cow_copy(self, src_block: int, dst_block: int) -> None:
        """Copy-on-write one pool block: ``src_block``'s K/V (all layers)
        into ``dst_block``. The scheduler calls this before remapping a
        slot's table away from a shared block it must write into (prefix
        cache, full-prompt hit); the shared original is never written."""
        if self.kv_layout != "paged":
            raise ValueError("copy-on-write requires the paged KV layout")
        self.cache = self._cow(self.cache, np.int32(src_block),
                               np.int32(dst_block))

    def export_slot_blocks(self, blocks, out_dir: str, *, slot: int,
                           meta=None) -> dict:
        """Serialize pool rows ``blocks`` (the slot's committed KV, in
        block-table order) into a checksummed artifact directory — the
        device side of spill and handoff. ``length`` is captured from the
        live cache so the restore resumes the decode position exactly.
        Returns the artifact manifest."""
        if self.kv_layout != "paged":
            raise ValueError("block export requires the paged KV layout")
        length = int(np.asarray(self.cache.lengths)[slot])
        return export_blocks(self.cache, blocks, out_dir,
                             length=length, meta=meta)

    def import_slot_blocks(self, art_dir: str, dest_blocks,
                           slot: int) -> dict:
        """Verify artifact ``art_dir`` (CRC of every payload BEFORE any
        device write) and scatter it into pool rows ``dest_blocks``, then
        restore ``slot``'s fill count from the manifest's recorded length.
        Raises ``KVBlockIntegrityError`` with the cache untouched on any
        mismatch. Returns the manifest."""
        if self.kv_layout != "paged":
            raise ValueError("block import requires the paged KV layout")
        cache, manifest = import_blocks(self.cache, art_dir, dest_blocks)
        self.cache = cache.replace(
            lengths=cache.lengths.at[slot].set(
                np.int32(manifest["length"])))
        return manifest

    def import_pool_blocks(self, art_dir: str, dest_blocks) -> dict:
        """Verify artifact ``art_dir`` and scatter it into pool rows
        ``dest_blocks`` WITHOUT touching any slot's fill count — the
        disaggregated decode import sets the length once, after every
        shipment is resident, via :meth:`set_slot_length`. Raises
        ``KVBlockIntegrityError`` with the cache untouched on any
        mismatch. Returns the manifest."""
        if self.kv_layout != "paged":
            raise ValueError("block import requires the paged KV layout")
        cache, manifest = import_blocks(self.cache, art_dir, dest_blocks)
        self.cache = cache
        return manifest

    def import_pool_block_batch(self, parts,
                                allow_partial: bool = False) -> list:
        """Verify every artifact in ``parts`` ((art_dir, dest_blocks)
        pairs) and land them all in ONE scatter per pool array, WITHOUT
        touching any slot's fill count — the disaggregated decode
        admission imports a request's whole shipment train as a single
        device write, then sets the length once via
        :meth:`set_slot_length`. Raises ``KVBlockIntegrityError`` with
        the cache untouched on any mismatch (verification of every
        payload precedes the first device write). Returns the manifests
        in ``parts`` order."""
        if self.kv_layout != "paged":
            raise ValueError("block import requires the paged KV layout")
        cache, manifests = import_block_batch(
            self.cache, parts, allow_partial=allow_partial)
        self.cache = cache
        return manifests

    def set_slot_length(self, slot: int, length: int) -> None:
        """Set ``slot``'s fill count directly (paged only) — the decode
        side of a disaggregated admission, after every shipment's blocks
        are resident, so the first decode round attends to the full
        committed prefix."""
        if self.kv_layout != "paged":
            raise ValueError("slot length set requires the paged KV layout")
        self.cache = self.cache.replace(
            lengths=self.cache.lengths.at[slot].set(np.int32(int(length))))

    def _adapter_call_args(self, rows, scales, batch=None):
        """Host-side trailing adapter args for the batched paged programs
        (empty tuple when the engine has no adapters). ``rows``/``scales``
        default to all-null (base-only) so adapter-enabled engines serve
        plain traffic without the caller carrying adapter state."""
        if not self.adapter_rank:
            if rows is not None or scales is not None:
                raise ValueError("adapter rows given but engine built "
                                 "without adapters (adapter_rank == 0)")
            return ()
        if rows is None or scales is None:
            rows, scales = self._null_adapter_args(
                self.slots if batch is None else batch)
        return (self.adapter_pool, np.asarray(rows, np.int32),
                np.asarray(scales, np.float32))

    def _prefill_adapter_args(self, row, scale):
        """Trailing adapter args for the B=1 prefill programs: one page
        row + one scalar scale (None -> the null adapter)."""
        if not self.adapter_rank:
            if row is not None:
                raise ValueError("adapter row given but engine built "
                                 "without adapters (adapter_rank == 0)")
            return ()
        per = self._adapter_layout.pages_per_adapter
        if row is None:
            row, scale = np.zeros((per,), np.int32), 0.0
        return (self.adapter_pool,
                np.asarray(row, np.int32).reshape(per),
                np.float32(scale))

    def _stream_chunks(self, draft: bool, row, ids, slot, temperature,
                       top_p, seed, stop_check, on_chunk, start_pos=0,
                       adapter_row=None, adapter_scale=0.0):
        """Stream ``ids`` through the paged prefill bucket programs of the
        target (or, spec mode, the draft) model, beginning at absolute
        position ``start_pos`` (0 = full prompt; a prefix-cache hit resumes
        at its first uncached position — the chunk loop already runs every
        chunk at an explicit offset, so resumption is just a nonzero start);
        returns the final chunk's sampled token, or None if ``stop_check``
        fired between chunks."""
        n = ids.size
        chunk = self.prefill_buckets[-1]
        start, tok = int(start_pos), None
        while start < n:
            m = min(chunk, n - start)
            bucket = next(b for b in self.prefill_buckets if b >= m)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :m] = ids[start:start + m]
            args = (row, padded, np.int32(slot), np.int32(start),
                    np.int32(m), np.float32(temperature), np.float32(top_p),
                    np.int32(seed))
            if draft:
                self.draft_cache, tok = self._draft_prefill[bucket](
                    self.draft_params, self.draft_cache, *args)
            else:
                self.cache, tok = self._prefill[bucket](
                    self.params, self.cache, *args,
                    *self._prefill_adapter_args(adapter_row, adapter_scale))
            start += m
            if on_chunk is not None:
                on_chunk()
            if start < n and stop_check is not None and stop_check():
                return None  # interrupted between chunks; request unserved
        return tok

    def prefill(self, slot: int, token_ids, block_row=None,
                draft_block_row=None, temperature: float = 0.0,
                top_p: float = 1.0, seed: int = 0,
                stop_check: Optional[Callable[[], bool]] = None,
                on_chunk: Optional[Callable[[], None]] = None,
                start_pos: int = 0,
                draft_start_pos: int = 0,
                adapter_row=None,
                adapter_scale: float = 0.0) -> Optional[int]:
        """Prompt into ``slot``; returns the first generated token id.

        Ring layout: the prompt must fit the largest bucket (one shot).
        Paged layout: ``block_row`` (blocks_per_slot,) is the slot's block
        table row from the scheduler's allocator, and prompts LONGER than
        the largest bucket stream through it in chunks of that bucket size
        (the last chunk picks its best-fit bucket). ``on_chunk`` fires after
        every finished chunk; between chunks ``stop_check`` is consulted —
        if it returns True the prefill stops cleanly AFTER the current chunk
        and returns None (caller frees the blocks and reports the request
        unserved: the drain-lifecycle contract for mid-prompt signals).

        ``start_pos`` (paged only) resumes the prompt at an absolute
        position: positions [0, start_pos) are NOT computed — the block
        row's leading entries must already hold their committed KV
        (prefix-cache hit blocks). The resumed chunks attend to those
        positions through the same block tables, and the chunk programs
        are the identical AOT bucket set a zero-offset prefill uses, so a
        cache-hit stream is bitwise the uncached stream.

        Spec mode additionally prefills the DRAFT cache through
        ``draft_block_row`` (its own pool's allocation) after the target
        phase — same chunking, same ``stop_check`` at every chunk boundary
        including the phase boundary, so a mid-prompt drain still frees
        BOTH pools and reports the request unserved. The draft phase's
        sampled token is discarded (the target's first token is the one
        emitted; the draft proposes only from round 1 on). The draft phase
        resumes at ``draft_start_pos`` under the same contract as the
        target's ``start_pos``: the scheduler keeps a DRAFT-pool mirror of
        the prefix cache fed the same insertions, so a shared system
        prompt skips the draft prefill compute too, and because the shared
        draft blocks hold the bytes a zero-offset draft prefill would have
        written, a cache-hit spec stream's proposals — and therefore the
        stream itself — are unchanged cache-on vs cache-off
        (tests/test_spec_decode.py asserts it).
        """
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = ids.size
        if start_pos and self.kv_layout != "paged":
            raise ValueError("start_pos requires the paged KV layout")
        if self.kv_layout != "paged":
            if not 0 < n <= self.prefill_buckets[-1]:
                raise ValueError(f"prompt length {n} outside "
                                 f"(0, {self.prefill_buckets[-1]}]")
            bucket = next(b for b in self.prefill_buckets if b >= n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = ids
            self.cache, tok = self._prefill[bucket](
                self.params, self.cache, padded, np.int32(slot), np.int32(n),
                np.float32(temperature), np.float32(top_p), np.int32(seed))
            return int(tok)
        if not 0 < n <= self.max_len:
            raise ValueError(f"prompt length {n} outside (0, {self.max_len}]")
        if block_row is None:
            raise ValueError("paged prefill requires the slot's block_row")
        row = np.asarray(block_row, np.int32).reshape(-1)
        if row.shape[0] != self.max_blocks_per_slot:
            raise ValueError(f"block_row has {row.shape[0]} entries, "
                             f"expected {self.max_blocks_per_slot}")
        if self.spec_k and draft_block_row is None:
            raise ValueError("spec-mode prefill requires draft_block_row")
        if not 0 <= start_pos < n:
            raise ValueError(f"start_pos {start_pos} outside [0, {n})")
        tok = self._stream_chunks(False, row, ids, slot, temperature, top_p,
                                  seed, stop_check, on_chunk,
                                  start_pos=start_pos,
                                  adapter_row=adapter_row,
                                  adapter_scale=adapter_scale)
        if tok is None:
            return None
        if self.spec_k:
            if stop_check is not None and stop_check():
                return None  # drain at the target/draft phase boundary
            drow = np.asarray(draft_block_row, np.int32).reshape(-1)
            if drow.shape[0] != self.max_blocks_per_slot:
                raise ValueError(
                    f"draft_block_row has {drow.shape[0]} entries, "
                    f"expected {self.max_blocks_per_slot}")
            if not 0 <= draft_start_pos <= n:
                raise ValueError(f"draft_start_pos {draft_start_pos} "
                                 f"outside [0, {n}]")
            if draft_start_pos == n:
                # Full-prompt draft hit. Unlike the target (which must
                # re-derive the LAST position's logits to sample the first
                # token, hence its COW resume at n-1), the draft phase
                # samples nothing — its only job is committed KV for
                # positions [0, n), and the shared blocks already hold it.
                # Nothing to compute: just commit the fill count.
                lengths = np.asarray(self.draft_cache.lengths).copy()
                lengths[slot] = n
                self.draft_cache = self.draft_cache.replace(
                    lengths=jnp.asarray(lengths))
            elif self._stream_chunks(True, drow, ids, slot, temperature,
                                     top_p, seed, stop_check, on_chunk,
                                     start_pos=draft_start_pos) is None:
                return None
        return int(tok)

    def prefill_packed(self, rows, bucket: int, adapter_rows=None,
                       adapter_scales=None):
        """ONE packed prefill round: each entry of ``rows`` is a
        ``(slot, chunk_ids, start, block_row, temperature, top_p, seed)``
        tuple — request ``slot``'s NEXT prompt chunk (``chunk_ids``, at
        most ``bucket`` tokens) at absolute position ``start`` through its
        ``block_row`` — and all of them run in one (P, bucket) dispatch
        (P = ``prefill_batch``; missing rows are inactive padding).

        The caller (the scheduler's packed admission lane) owns the chunk
        loop the sequential :meth:`prefill` runs internally: it computes
        each row's next chunk with the SAME best-fit bucket discipline
        ``_stream_chunks`` uses and groups rows by bucket, which is what
        keeps per-row chunk shapes — and therefore the streams, on the
        gather impl — bit-identical to sequential prefill. Returns one
        sampled token id per row; only a row whose chunk was its prompt's
        FINAL chunk has a meaningful token (the first generated token),
        exactly like the sequential chunk loop's intermediate discards.

        Prefix-cache divergent starts need nothing special here: a resumed
        row simply arrives with ``start`` > 0 and a block row whose leading
        entries are the shared blocks, as in sequential resumption."""
        if self.kv_layout != "paged":
            raise ValueError("packed prefill requires the paged KV layout")
        if self.prefill_batch < 2:
            raise ValueError("engine built without the packed prefill lane "
                             "(prefill_batch < 2)")
        bucket = int(bucket)
        if bucket not in self.prefill_buckets:
            raise ValueError(f"bucket {bucket} not in compiled set "
                             f"{self.prefill_buckets}")
        p = self.prefill_batch
        if not 1 <= len(rows) <= p:
            raise ValueError(f"{len(rows)} packed rows outside [1, {p}]")
        toks = np.zeros((p, bucket), np.int32)
        block_rows = np.zeros((p, self.max_blocks_per_slot), np.int32)
        slots = np.zeros((p,), np.int32)
        starts = np.zeros((p,), np.int32)
        lens = np.zeros((p,), np.int32)
        active = np.zeros((p,), bool)
        temp = np.zeros((p,), np.float32)
        tp = np.ones((p,), np.float32)
        seeds = np.zeros((p,), np.int32)
        for i, (slot, ids, start, row, temperature, top_p, seed) in \
                enumerate(rows):
            ids = np.asarray(ids, np.int32).reshape(-1)
            if not 0 < ids.size <= bucket:
                raise ValueError(f"packed row {i}: chunk length {ids.size} "
                                 f"outside (0, {bucket}]")
            row = np.asarray(row, np.int32).reshape(-1)
            if row.shape[0] != self.max_blocks_per_slot:
                raise ValueError(f"packed row {i}: block_row has "
                                 f"{row.shape[0]} entries, expected "
                                 f"{self.max_blocks_per_slot}")
            toks[i, :ids.size] = ids
            block_rows[i] = row
            slots[i] = slot
            starts[i] = start
            lens[i] = ids.size
            active[i] = True
            temp[i] = temperature
            tp[i] = top_p
            seeds[i] = seed
        ad = ()
        if self.adapter_rank:
            per = self._adapter_layout.pages_per_adapter
            a_rows = np.zeros((p, per), np.int32)
            a_scales = np.zeros((p,), np.float32)
            if adapter_rows is not None:
                for i, (r, s) in enumerate(zip(adapter_rows,
                                               adapter_scales)):
                    a_rows[i] = np.asarray(r, np.int32).reshape(per)
                    a_scales[i] = s
            ad = (self.adapter_pool, a_rows, a_scales)
        elif adapter_rows is not None:
            raise ValueError("adapter rows given but engine built "
                             "without adapters (adapter_rank == 0)")
        self.cache, out = self._packed_prefill[bucket](
            self.params, self.cache, block_rows, toks, slots, starts, lens,
            active, temp, tp, seeds, *ad)
        return [int(t) for t in np.asarray(out)[:len(rows)]]

    def decode_step(self, tokens, active, temperature, top_p, seeds, steps,
                    block_tables=None, adapter_rows=None,
                    adapter_scales=None) -> np.ndarray:
        """One decode iteration over all slots; host arrays in/out. The
        paged layout additionally takes the scheduler's (slots,
        blocks_per_slot) block tables, and adapter-enabled engines take
        each slot's adapter page row + scale (``adapter_rows`` (slots, P)
        / ``adapter_scales`` (slots,); None = all base-only)."""
        if self.kv_layout == "paged":
            if block_tables is None:
                raise ValueError("paged decode requires block_tables")
            self.cache, toks = self._decode(
                self.params, self.cache,
                np.asarray(block_tables, np.int32),
                np.asarray(tokens, np.int32), np.asarray(active, bool),
                np.asarray(temperature, np.float32),
                np.asarray(top_p, np.float32),
                np.asarray(seeds, np.int32), np.asarray(steps, np.int32),
                *self._adapter_call_args(adapter_rows, adapter_scales))
            return np.asarray(toks)
        self.cache, toks = self._decode(
            self.params, self.cache,
            np.asarray(tokens, np.int32), np.asarray(active, bool),
            np.asarray(temperature, np.float32),
            np.asarray(top_p, np.float32),
            np.asarray(seeds, np.int32), np.asarray(steps, np.int32))
        return np.asarray(toks)

    def decode_logits(self, tokens, active, block_tables=None,
                      adapter_rows=None, adapter_scales=None) -> np.ndarray:
        """UNFUSED decode iteration: run the forward, sync the (slots, V)
        fp32 logits to the host, sample nothing. The caller samples with
        sampler.py ``sample_slot_tokens`` — same function the fused
        programs trace — which is what pins the fused/unfused stream
        bit-match the bench asserts. Paged layout only (it exists as the
        fused epilogue's measured baseline)."""
        if self.kv_layout != "paged":
            raise ValueError("decode_logits requires the paged KV layout")
        if block_tables is None:
            raise ValueError("paged decode requires block_tables")
        self.cache, logits = self._decode_logits(
            self.params, self.cache, np.asarray(block_tables, np.int32),
            np.asarray(tokens, np.int32), np.asarray(active, bool),
            *self._adapter_call_args(adapter_rows, adapter_scales))
        return np.asarray(logits)

    def decode_burst(self, tokens, active, temperature, top_p, seeds, steps,
                     n, block_tables=None, adapter_rows=None,
                     adapter_scales=None) -> np.ndarray:
        """A burst of ``n`` decode iterations in ONE dispatch + ONE host
        sync; returns (slots, n) token ids. Greedy streams are bit-equal
        to ``n`` sequential :meth:`decode_step` calls and sampled slots
        share their PRNG schedule (``_burst_decode_fn`` documents why);
        EOS/budget truncation of the overshoot is the scheduler's job
        (``Scheduler._bank_burst``). ``n == 1`` runs the ordinary decode
        program — same math, no extra compile."""
        if self.kv_layout != "paged":
            raise ValueError("burst decode requires the paged KV layout")
        if block_tables is None:
            raise ValueError("paged decode requires block_tables")
        n = int(n)
        if n == 1:
            return self.decode_step(tokens, active, temperature, top_p,
                                    seeds, steps,
                                    block_tables=block_tables,
                                    adapter_rows=adapter_rows,
                                    adapter_scales=adapter_scales)[:, None]
        prog = self._burst_program(n)
        self.cache, toks = prog(
            self.params, self.cache, np.asarray(block_tables, np.int32),
            np.asarray(tokens, np.int32), np.asarray(active, bool),
            np.asarray(temperature, np.float32),
            np.asarray(top_p, np.float32),
            np.asarray(seeds, np.int32), np.asarray(steps, np.int32),
            *self._adapter_call_args(adapter_rows, adapter_scales))
        return np.asarray(toks)

    def spec_round(self, tokens, lengths, active, temperature, top_p, seeds,
                   rounds, block_tables=None, draft_block_tables=None,
                   k=None):
        """One speculative round over all slots: k draft proposals then one
        verify pass — two dispatches for up to k+1 emitted tokens.

        ``lengths`` (slots,) is each slot's COMMITTED KV count, i.e.
        ``prompt_len + emitted - 1`` (the last emitted token's KV is not yet
        written; the round writes it at ``lengths[s]`` first) — the host
        derives it from its own token bookkeeping, which is what makes
        rejected-suffix rollback free: stale device KV past the committed
        prefix is simply re-addressed. ``tokens`` is each slot's last
        emitted token, ``rounds`` its per-request round counter (PRNG
        stream index). Returns ``(out_tokens (slots, k+1), accepted
        (slots,))`` host arrays: slot s emitted ``accepted[s] + 1`` tokens,
        ``out_tokens[s, :accepted[s] + 1]`` (accepted draft prefix plus the
        verify pass's bonus/resampled token).

        ``k`` (default ``spec_k``) selects the round width from the
        compiled ladder (:meth:`_spec_pair`) — an adaptive-k controller
        shrinks it when live acceptance drops (e.g. a freshly hot-swapped
        target running against a stale draft) so a bad draft degrades
        toward plain decode instead of burning k rejected proposals per
        round. ``out_tokens`` is then (slots, k+1).
        """
        if not self.spec_k:
            raise ValueError("engine built without a draft model "
                             "(spec_k == 0)")
        if block_tables is None or draft_block_tables is None:
            raise ValueError("spec_round requires both pools' block tables")
        draft_prog, verify_prog = (
            (self._draft_k, self._verify) if k is None
            else self._spec_pair(k))
        toks = np.asarray(tokens, np.int32)
        lens = np.asarray(lengths, np.int32)
        act = np.asarray(active, bool)
        temp = np.asarray(temperature, np.float32)
        tp = np.asarray(top_p, np.float32)
        sd = np.asarray(seeds, np.int32)
        rd = np.asarray(rounds, np.int32)
        self.draft_cache, d_toks, d_probs = draft_prog(
            self.draft_params, self.draft_cache,
            np.asarray(draft_block_tables, np.int32), toks, lens, act, temp,
            tp, sd, rd)
        self.cache, out, acc = verify_prog(
            self.params, self.cache, np.asarray(block_tables, np.int32),
            toks, d_toks, d_probs, lens, act, temp, tp, sd, rd)
        return np.asarray(out), np.asarray(acc)

    def spec_tree_round(self, refeed, refeed_len, lengths, active,
                        temperature, top_p, seeds, rounds,
                        block_tables=None, draft_block_tables=None,
                        shape=None):
        """One TREE-speculative round over all slots: a branching draft
        then one ancestor-masked verify — still two dispatches, but up to
        ``depth + 1`` emitted tokens with extra acceptance chances at
        every level (an accepted sibling where linear spec would have
        rejected the whole suffix).

        ``lengths`` is the committed-KV convention of :meth:`spec_round`;
        ``refeed`` (slots, depth+1) / ``refeed_len`` carry the tokens the
        PREVIOUS round emitted per slot (first round: just the prefill
        token, len 1) — the draft rewrites their KV window before
        proposing, because a committed sibling is a token its chain never
        fed (``_tree_draft_fn`` documents the invariant). ``shape``
        (default the configured ``spec_tree``) selects the rung from the
        compiled ladder; an adaptive controller passes
        ``engine.spec_tree.shrink_to(k)``.

        Returns ``(out_tokens (slots, depth+1), accepted (slots,), path
        (slots, depth))`` host arrays: slot s emitted ``accepted[s] + 1``
        tokens; ``path[s, :accepted[s]]`` is the accepted nodes' tree rows
        (primary chain under ``exact`` verify), which is how the scheduler
        attributes acceptance to branches."""
        if self.spec_tree is None:
            raise ValueError("engine built without a tree shape "
                             "(spec_tree unset)")
        if block_tables is None or draft_block_tables is None:
            raise ValueError("spec_tree_round requires both pools' block "
                             "tables")
        shape = self.spec_tree if shape is None else parse_spec_tree(shape)
        draft_prog, verify_prog = self._tree_pair(shape)
        rf = np.zeros((self.slots, self._tree_refeed), np.int32)
        src = np.asarray(refeed, np.int32)
        rf[:, :src.shape[1]] = src[:, :self._tree_refeed]
        rl = np.clip(np.asarray(refeed_len, np.int32), 1, self._tree_refeed)
        lens = np.asarray(lengths, np.int32)
        act = np.asarray(active, bool)
        temp = np.asarray(temperature, np.float32)
        tp = np.asarray(top_p, np.float32)
        sd = np.asarray(seeds, np.int32)
        rd = np.asarray(rounds, np.int32)
        self.draft_cache, t_toks, t_probs = draft_prog(
            self.draft_params, self.draft_cache,
            np.asarray(draft_block_tables, np.int32), rf, rl, lens, act,
            temp, tp, sd, rd)
        self.cache, out, acc, path = verify_prog(
            self.params, self.cache, np.asarray(block_tables, np.int32),
            t_toks, t_probs, lens, act, temp, tp, sd, rd)
        return np.asarray(out), np.asarray(acc), np.asarray(path)

    def fork_slot(self, src_slot: int, dst_slot: int, length: int,
                  src_row, allocator):
        """COW-fork slot ``src_slot``'s first ``length`` committed tokens
        into ``dst_slot`` — the beam-search primitive over the paged
        substrate. Full shared blocks are NOT copied: ``dst``'s table row
        aliases them and the allocator refcount rises (``incref``), the
        same sharing contract the prefix cache uses; only the partial
        boundary block (``length % block_size != 0``) is duplicated
        device-side (:meth:`cow_copy`) into a freshly allocated block, so
        both beams can keep writing inside it without seeing each other.
        Returns ``dst``'s block row (np.int32, padded with 0), or None if
        the pool cannot supply the boundary block (caller's admission
        problem — nothing was acquired). The caller owns both slots'
        host bookkeeping and later frees each row through the uniform
        allocator path (shared blocks drop a ref, the private boundary
        block frees outright — tests/test_spec_decode.py pins the
        contract, double-free raise included)."""
        if self.kv_layout != "paged":
            raise ValueError("fork_slot requires the paged KV layout")
        if not (0 <= src_slot < self.slots and 0 <= dst_slot < self.slots
                and src_slot != dst_slot):
            raise ValueError("fork_slot: bad slot pair "
                             f"({src_slot}, {dst_slot})")
        if not 0 < length <= self.max_len:
            raise ValueError(f"fork length {length} outside (0, "
                             f"{self.max_len}]")
        row = np.asarray(src_row, np.int32).reshape(-1)
        if row.shape[0] != self.max_blocks_per_slot:
            raise ValueError(f"src_row has {row.shape[0]} entries, "
                             f"expected {self.max_blocks_per_slot}")
        n_full, rem = divmod(length, self.block_size)
        dst_row = np.zeros_like(row)
        fresh = None
        if rem:
            fresh = allocator.alloc(1)
            if fresh is None:
                return None
        for i in range(n_full):
            allocator.incref([int(row[i])])
            dst_row[i] = row[i]
        if rem:
            dst_row[n_full] = fresh[0]
            self.cow_copy(int(row[n_full]), int(fresh[0]))
        lengths = np.asarray(self.cache.lengths).copy()
        lengths[dst_slot] = length
        self.cache = self.cache.replace(lengths=jnp.asarray(lengths))
        return dst_row

    def reset(self) -> None:
        """Zero all slot lengths (the buffers' stale contents are masked).
        Any prefix cache built over the old pool contents dies with them —
        a scheduler is per-stream, so resetting the engine and building a
        fresh ``Scheduler`` (fresh radix tree) is the supported pattern."""
        with use_mesh(self.mesh):
            cache = self._init_cache(dtype=self.cache.k[0].dtype)
            cs = cache_shardings(cache, self.mesh)
            self.cache = (jax.device_put(cache, cs) if cs is not None
                          else cache)
            if self.spec_k:
                dcache = self._init_draft_cache(
                    dtype=self.draft_cache.k[0].dtype)
                dcs = cache_shardings(dcache, self.mesh)
                self.draft_cache = (jax.device_put(dcache, dcs)
                                    if dcs is not None else dcache)

    # --- construction from a training checkpoint ---------------------------

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str, job_id: str,
                        cfg: TransformerConfig, *, step: Optional[int] = None,
                        mesh=None, **engine_kwargs) -> "InferenceEngine":
        """Restore a training checkpoint and build an engine on it.

        ``cfg`` must be the architecture the checkpoint was trained with
        (scan/loop form included — the abstract TrainState has to match the
        saved tree); the restore itself is the trainer's own cross-topology
        path, so a checkpoint written on any mesh loads onto this one
        (:func:`restore_params`). ``engine_kwargs`` passes through to the
        constructor — including ``draft_cfg``/``draft_params``/``spec_k``
        for speculative decoding (serve.py restores the draft checkpoint
        through the same :func:`restore_params` path first).
        """
        params, restored_step = restore_params(checkpoint_path, job_id, cfg,
                                               step=step, mesh=mesh)
        logger.info("Model loaded from checkpoint")  # ref: train.py:58
        engine = cls(cfg, params, mesh=mesh, **engine_kwargs)
        engine.restored_step = restored_step
        return engine


def restore_params(checkpoint_path: str, job_id: str, cfg: TransformerConfig,
                   *, step: Optional[int] = None, mesh=None):
    """Restore ONLY the params collection of a training checkpoint.

    The abstract TrainState is rebuilt exactly as the trainer builds it
    (the saved tree must match, optimizer state included — restored
    alongside and dropped), so a checkpoint written on any training
    topology loads onto the serving mesh. Factored out of
    :meth:`InferenceEngine.from_checkpoint` so the speculative-decoding
    path can load a DRAFT model's checkpoint — any preset, its own
    training run — through the identical cross-topology machinery.
    Returns ``(params, restored_step)``.
    """
    from ..checkpoint.manager import CheckpointManager
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import param_pspecs
    from ..training.state import TrainState
    from ..training.step import make_optimizer
    from jax.sharding import NamedSharding

    model = Transformer(cfg)
    # only the opt_state TREE matters (restored then dropped); any
    # schedule yields the same optax.adamw structure
    optimizer = make_optimizer(1e-4, 1)
    dummy = jnp.zeros((1, cfg.seq_len), jnp.int32)

    def init_fn(key):
        params = model.init(key, dummy)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    # Orbax needs target shardings; without a serving mesh, restore onto
    # a trivial single-device mesh (replicated specs, device 0).
    restore_mesh = mesh or make_mesh(dp=1, devices=jax.devices()[:1])
    with use_mesh(restore_mesh):
        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        specs = param_pspecs(abstract)
        abstract = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=NamedSharding(restore_mesh, s)),
            abstract, specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        mngr = CheckpointManager(checkpoint_path, job_id,
                                 enable_async=False)
        state, _data, restored_step = mngr.restore(abstract, step=step)
        mngr.close()
    return state.params, restored_step
