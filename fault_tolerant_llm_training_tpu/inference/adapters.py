"""Paged multi-tenant LoRA adapter serving (S-LoRA / Punica style).

One base model serves K per-tenant LoRA adapters concurrently. The
adapter weights — per-layer low-rank (A, B) factors for the wq and wv
projections — live in a THIRD paged pool next to the target/draft KV
pools: a flat ``(num_pages, page_elems)`` fp32 array on device, carved
into fixed-size pages by the same :class:`~.scheduler.BlockAllocator`
the KV pools use (alloc / refcount / double-free raise — page 0 is the
reserved NULL page, all zeros, mirroring KV null block 0). Each resident
adapter owns ``pages_per_adapter`` pages holding its factors flattened
in a STATIC layout (:class:`AdapterLayout`), so the fused decode/prefill
programs can gather one slot's whole adapter with a single
``pool[page_rows]`` table lookup — the scalar-prefetched-table trick the
paged KV kernels use — and then slice per-layer factors at static
offsets. The LoRA contribution itself,

    ``y = Wx + B(Ax) * (alpha / r)``

is computed inside the existing batched dispatch with the batch as a
PARALLEL einsum dim and a per-slot ``jnp.where`` gate on the scale:
slots carrying the null adapter (scale 0) select the base activations
bitwise unchanged, and each slot's delta depends only on its own gathered
pages — which is what makes K-adapter concurrent streams bit-match K
sequential single-adapter runs (tests/test_adapter_serving.py).

Adapters ship as CRC-manifested ARTIFACTS (the checkpoint manifest
machinery: per-file size+CRC ``integrity.json``, tmp+rename commit), are
published through deploy/publish.py as a ``adapters`` sub-pointer in
``published.json``, and are verified BEFORE any pool write — a corrupt
artifact raises :class:`AdapterIntegrityError` with the pool untouched.
Cold adapters evict under pool pressure (LRU among records with no
active slots); a request naming an unresident adapter queues behind a
verified page-in at admission instead of crashing. Hot-swap pages the
new version in ALONGSIDE the old one: in-flight slots keep decoding
against their pinned pages (the allocator refcount holds them) and the
old version's pages free when the last such slot drains — no recompile,
no stream disturbance, same prefill-pause the PR 7 weight reload uses.
"""

import dataclasses
import json
import math
import os
import shutil
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..checkpoint.manager import (
    MANIFEST_NAME,
    _fsync_dir,
    verify_step_dir,
    write_manifest,
)

ADAPTER_META_NAME = "adapter.json"

#: factor file names inside an adapter artifact directory, in the flat
#: layout's per-layer order (A then B, q then v)
_FACTOR_FILES = ("a_q.npy", "b_q.npy", "a_v.npy", "b_v.npy")


class AdapterIntegrityError(RuntimeError):
    """An adapter artifact failed its verify-before-load sweep (size/CRC
    mismatch, missing manifest, geometry drift). Raised with the adapter
    pool — and the serving params — untouched."""


@dataclasses.dataclass(frozen=True)
class AdapterLayout:
    """STATIC flat layout of one adapter's factors in the paged pool.

    Per layer, in order: ``A_q`` (dim, r), ``B_q`` (r, n_heads*head_dim),
    ``A_v`` (dim, r), ``B_v`` (r, kv_heads*head_dim), each flattened
    C-order; layers concatenated; the whole vector zero-padded to
    ``pages_per_adapter * page_elems``. Both the host flatten
    (:meth:`flatten`) and the traced per-layer slicing
    (:meth:`slice_layers`) derive from the same offsets, so what the
    manager writes is exactly what the programs read."""

    n_layers: int
    dim: int
    n_q: int
    n_kv: int
    rank: int
    page_elems: int

    @classmethod
    def from_cfg(cls, cfg, rank: int,
                 page_elems: Optional[int] = None) -> "AdapterLayout":
        dh = cfg.head_dim
        pe = int(page_elems) if page_elems else int(cfg.dim * rank)
        return cls(n_layers=int(cfg.n_layers), dim=int(cfg.dim),
                   n_q=int(cfg.n_heads * dh), n_kv=int(cfg.kv_heads * dh),
                   rank=int(rank), page_elems=pe)

    @property
    def a_elems(self) -> int:
        return self.dim * self.rank

    @property
    def layer_elems(self) -> int:
        return (2 * self.a_elems + self.rank * self.n_q
                + self.rank * self.n_kv)

    @property
    def total_elems(self) -> int:
        return self.n_layers * self.layer_elems

    @property
    def pages_per_adapter(self) -> int:
        return max(1, math.ceil(self.total_elems / self.page_elems))

    @property
    def padded_elems(self) -> int:
        return self.pages_per_adapter * self.page_elems

    @property
    def adapter_bytes(self) -> int:
        """Device footprint of one resident adapter (fp32 pages)."""
        return self.padded_elems * 4

    def factor_shapes(self) -> Tuple[Tuple[int, ...], ...]:
        ln, d, r = self.n_layers, self.dim, self.rank
        return ((ln, d, r), (ln, r, self.n_q),
                (ln, d, r), (ln, r, self.n_kv))

    def flatten(self, a_q, b_q, a_v, b_v) -> np.ndarray:
        """Factors -> ``(pages_per_adapter, page_elems)`` fp32 pages."""
        arrs = (a_q, b_q, a_v, b_v)
        for arr, want in zip(arrs, self.factor_shapes()):
            if tuple(np.shape(arr)) != want:
                raise ValueError(
                    f"adapter factor shape {tuple(np.shape(arr))} does "
                    f"not match layout {want}")
        flat = np.zeros((self.padded_elems,), np.float32)
        off = 0
        for layer in range(self.n_layers):
            for arr in arrs:
                chunk = np.asarray(arr[layer], np.float32).reshape(-1)
                flat[off:off + chunk.size] = chunk
                off += chunk.size
        return flat.reshape(self.pages_per_adapter, self.page_elems)

    def slice_layers(self, flat):
        """Traced inverse of :meth:`flatten`: ``flat`` (B, padded_elems)
        -> one ``(A_q, B_q, A_v, B_v)`` tuple per layer, each factor
        carrying the leading batch dim. Static slices only — the whole
        per-slot gather is the single ``pool[rows]`` the caller ran."""
        b = flat.shape[0]
        d, r = self.dim, self.rank
        sizes = (d * r, r * self.n_q, d * r, r * self.n_kv)
        shapes = ((b, d, r), (b, r, self.n_q), (b, d, r), (b, r, self.n_kv))
        out = []
        off = 0
        for _ in range(self.n_layers):
            factors = []
            for size, shape in zip(sizes, shapes):
                factors.append(flat[:, off:off + size].reshape(shape))
                off += size
            out.append(tuple(factors))
        return out


def init_adapter_factors(layout: AdapterLayout, seed: int,
                         scale: float = 0.02):
    """Deterministic toy factors for tests/bench: seeded normal A, seeded
    normal B (real LoRA zero-inits B; non-zero here so every adapter
    visibly changes the stream)."""
    rng = np.random.default_rng(int(seed))
    return tuple(
        np.asarray(rng.normal(0.0, scale, size=shape), np.float32)
        for shape in layout.factor_shapes())


# --- artifact write / verified load ---------------------------------------


def write_adapter_artifact(root: str, name: str, step: int, factors, *,
                           rank: int, alpha: float) -> dict:
    """Commit one adapter version as a CRC-manifested artifact directory
    ``{root}/adapter_{name}/{step}`` (the ``write_weights_artifact``
    discipline: build in a ``.tmp`` sibling, write the integrity manifest
    last, rename into place). Returns the pointer's per-adapter
    sub-entry dict for ``published.json``'s ``adapters`` map."""
    from ..deploy.publish import manifest_digest

    root = os.path.abspath(root)
    final = os.path.join(root, f"adapter_{name}", str(int(step)))
    tmp = final + ".tmp"
    for d in (final, tmp):
        if os.path.isdir(d):
            shutil.rmtree(d)
    os.makedirs(tmp)
    nbytes = 0
    shapes = []
    for fname, arr in zip(_FACTOR_FILES, factors):
        arr = np.asarray(arr, np.float32)
        np.save(os.path.join(tmp, fname), arr)
        shapes.append(list(arr.shape))
        nbytes += arr.nbytes
    meta = {"version": 1, "name": str(name), "step": int(step),
            "rank": int(rank), "alpha": float(alpha),
            "nbytes": int(nbytes), "shapes": shapes}
    with open(os.path.join(tmp, ADAPTER_META_NAME), "w") as fh:
        json.dump(meta, fh)
        fh.flush()
        os.fsync(fh.fileno())
    write_manifest(tmp, int(step))
    os.rename(tmp, final)
    _fsync_dir(os.path.dirname(final))
    return {"name": str(name), "step": int(step),
            "path": os.path.relpath(final, root),
            "manifest_digest": manifest_digest(final),
            "rank": int(rank), "alpha": float(alpha)}


def load_adapter_artifact(art_dir: str):
    """Verify-then-load one adapter artifact: every manifest-listed file
    passes its size+CRC check BEFORE any byte is trusted (the checkpoint
    sweep, ``verify_step_dir``), then the meta and the four factor arrays
    are loaded and geometry-checked. Raises :class:`AdapterIntegrityError`
    on any mismatch — the caller's pool and params are untouched.
    Returns ``(meta, (a_q, b_q, a_v, b_v))``."""
    if not os.path.isfile(os.path.join(art_dir, MANIFEST_NAME)):
        raise AdapterIntegrityError(
            f"adapter artifact has no integrity manifest: {art_dir}")
    ok, detail = verify_step_dir(art_dir)
    if not ok:
        raise AdapterIntegrityError(
            f"adapter artifact failed integrity check ({art_dir}): "
            f"{detail}")
    try:
        with open(os.path.join(art_dir, ADAPTER_META_NAME)) as fh:
            meta = json.load(fh)
        factors = tuple(np.load(os.path.join(art_dir, f))
                        for f in _FACTOR_FILES)
    except (OSError, ValueError, KeyError) as e:
        raise AdapterIntegrityError(
            f"adapter artifact unreadable ({art_dir}): {e}")
    for arr, want in zip(factors, meta.get("shapes", [])):
        if list(arr.shape) != list(want):
            raise AdapterIntegrityError(
                f"adapter artifact geometry mismatch ({art_dir}): "
                f"{list(arr.shape)} != {list(want)}")
    return meta, factors


# --- residency manager ----------------------------------------------------


@dataclasses.dataclass
class _Record:
    """One resident adapter VERSION. ``pages`` carry one allocator ref
    held by the manager (dropped at evict/retire) plus one per active
    slot — the pages physically free only when both are gone, which is
    the whole hot-swap/drain story."""

    name: str
    step: int
    pages: List[int]
    row: np.ndarray
    scale: float
    active: set = dataclasses.field(default_factory=set)
    last_use: int = 0
    stale: bool = False


class AdapterManager:
    """Host-side residency/refcount bookkeeping over the paged adapter
    pool. The engine owns the device array and hands the manager a
    ``write_pages(pages, values)`` callback; the scheduler drives
    admission (``page_in``/``acquire``) and release (``release``)."""

    def __init__(self, layout: AdapterLayout, num_pages: int, write_pages):
        from .scheduler import BlockAllocator  # circular at module scope

        if num_pages < layout.pages_per_adapter + 1:
            raise ValueError(
                f"adapter pool of {num_pages} page(s) cannot hold one "
                f"adapter ({layout.pages_per_adapter} page(s) + null "
                f"page 0)")
        self.layout = layout
        self.num_pages = int(num_pages)
        self.allocator = BlockAllocator(int(num_pages))
        self._write_pages = write_pages
        self._paths: Dict[str, str] = {}
        self._current: Dict[str, _Record] = {}
        self._stale: List[_Record] = []
        self._slot_rec: Dict[int, _Record] = {}
        self._tick = 0
        self.pageins = 0
        self.evictions = 0
        self.served: set = set()

    # -- registration / residency --

    def register(self, name: str, art_dir: str) -> None:
        """Bind ``name`` to its (newest) published artifact directory —
        what a later page-in verifies and loads."""
        if not name:
            raise ValueError("the null adapter '' cannot be registered")
        self._paths[str(name)] = str(art_dir)

    def known(self, name: str) -> bool:
        return not name or name in self._paths or name in self._current

    def resident(self, name: str) -> bool:
        return not name or name in self._current

    def pages_needed(self, name: str) -> int:
        """Pages a page-in of ``name`` would consume right now (0 when
        resident or null) — the adapter half of the scheduler's combined
        KV+adapter admission footprint."""
        return 0 if self.resident(name) else self.layout.pages_per_adapter

    # -- page-in / eviction --

    def _load_record(self, name: str) -> Optional[_Record]:
        """Verify+load ``name``'s artifact and land it in freshly
        allocated pages (evicting cold adapters as needed). Returns the
        new record, or None if even eviction cannot free enough pages.
        Raises :class:`AdapterIntegrityError` / ``ValueError`` with the
        pool untouched on a bad artifact."""
        art = self._paths.get(name)
        if art is None:
            raise KeyError(f"unknown adapter {name!r}: not registered and "
                           f"not in the published pointer")
        meta, factors = load_adapter_artifact(art)
        if int(meta.get("rank", -1)) != self.layout.rank:
            raise ValueError(
                f"adapter {name!r} rank {meta.get('rank')} does not match "
                f"the engine's adapter_rank {self.layout.rank}")
        flat = self.layout.flatten(*factors)
        pages = self._alloc_with_eviction(self.layout.pages_per_adapter)
        if pages is None:
            return None
        self._write_pages(pages, flat)
        self.pageins += 1
        self._tick += 1
        scale = float(meta.get("alpha", self.layout.rank)) / self.layout.rank
        return _Record(name=name, step=int(meta.get("step", 0)),
                       pages=list(pages),
                       row=np.asarray(pages, np.int32),
                       scale=scale, last_use=self._tick)

    def _alloc_with_eviction(self, n: int) -> Optional[List[int]]:
        while True:
            pages = self.allocator.alloc(n)
            if pages is not None:
                return pages
            victim = None
            for rec in self._current.values():
                if rec.active:
                    continue
                if victim is None or rec.last_use < victim.last_use:
                    victim = rec
            if victim is None:
                return None
            self.evict(victim.name)

    def evict(self, name: str) -> bool:
        """Drop ``name`` from residency (LRU pressure path, or explicit).
        Refuses while any slot still decodes against it."""
        rec = self._current.get(name)
        if rec is None or rec.active:
            return False
        del self._current[name]
        self.allocator.free(rec.pages)
        self.evictions += 1
        return True

    def page_in(self, name: str) -> bool:
        """Make ``name`` resident (verified artifact -> pool pages).
        True if resident on return; False if the pool cannot hold it even
        after evicting every cold adapter — the caller leaves the request
        queued behind the page-in. Raises ``KeyError`` for an
        unregistered name and :class:`AdapterIntegrityError` for a
        corrupt artifact, both with the pool untouched."""
        if self.resident(name):
            return True
        rec = self._load_record(name)
        if rec is None:
            return False
        self._current[name] = rec
        return True

    # -- slot binding --

    def acquire(self, name: str, slot: int) -> Tuple[np.ndarray, float]:
        """Pin ``name``'s current version to ``slot`` (+1 allocator ref
        per page) and return ``(page_row, scale)`` for the slot's decode
        rows. The null adapter pins nothing and rows divert to null
        page 0 with scale 0 — the base-only gate."""
        if not name:
            return (np.zeros((self.layout.pages_per_adapter,), np.int32),
                    0.0)
        rec = self._current[name]
        self.allocator.incref(rec.pages)
        rec.active.add(int(slot))
        self._tick += 1
        rec.last_use = self._tick
        self._slot_rec[int(slot)] = rec
        self.served.add(name)
        return rec.row.copy(), rec.scale

    def release(self, slot: int) -> None:
        """Drop ``slot``'s pin. A STALE version (hot-swapped away) whose
        last slot just drained frees its pages here — the deferred half
        of :meth:`swap`."""
        rec = self._slot_rec.pop(int(slot), None)
        if rec is None:
            return
        rec.active.discard(int(slot))
        self.allocator.free(rec.pages)
        if rec.stale and not rec.active:
            self._stale.remove(rec)
            self.allocator.free(rec.pages)

    # -- hot swap --

    def swap(self, name: str, art_dir: Optional[str] = None) -> bool:
        """Hot-swap ``name`` to the artifact at ``art_dir`` (or its
        registered path). The new version is paged in ALONGSIDE the old
        one first — on any failure the old version keeps serving — then
        the old record either frees immediately (no active slots) or goes
        stale and frees when its last in-flight slot drains. Future
        admissions see the new version; in-flight slots are undisturbed.
        No-op (registration only) while ``name`` is not resident."""
        if art_dir is not None:
            self.register(name, art_dir)
        old = self._current.get(name)
        if old is None:
            return True
        rec = self._load_record(name)
        if rec is None:
            return False
        if old.active:
            old.stale = True
            self._stale.append(old)
        else:
            self.allocator.free(old.pages)
        self._current[name] = rec
        return True

    # -- accounting --

    def resident_pages(self) -> int:
        return (sum(len(r.pages) for r in self._current.values())
                + sum(len(r.pages) for r in self._stale))

    def resident_bytes(self) -> int:
        return self.resident_pages() * self.layout.page_elems * 4

    def active_slots(self) -> Dict[str, int]:
        """Active slot count per adapter name (stale versions fold into
        their name) — the ``adapter_slots_active{adapter=}`` gauge."""
        counts: Dict[str, int] = {}
        for rec in list(self._current.values()) + self._stale:
            if rec.active:
                counts[rec.name] = counts.get(rec.name, 0) + len(rec.active)
        return counts

    def stats(self) -> Dict[str, object]:
        return {
            "resident": sorted(self._current),
            "resident_pages": self.resident_pages(),
            "resident_bytes": self.resident_bytes(),
            "stale_versions": len(self._stale),
            "pageins": self.pageins,
            "evictions": self.evictions,
            "served": len(self.served),
            "active_slots": self.active_slots(),
            "free_pages": self.allocator.free_count,
        }
