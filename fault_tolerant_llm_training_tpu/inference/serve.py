"""Fault-tolerant serving lifecycle driver.

``python -m fault_tolerant_llm_training_tpu.inference.serve`` restores a
training checkpoint into the inference engine and drives the
continuous-batching scheduler, under the SAME signal discipline as training
(ft/signals.py): the POSIX handler only records SIGUSR1/SIGTERM; the serve
loop checks the flag between decode iterations and switches to drain mode —
admission stops, in-flight requests run to completion, queued requests are
reported unserved — then exits 0 with the ``[EXIT HANDLER]`` audit strings
(utils/logging.py), so the Slurm pre-warning -> drain -> resubmit pattern the
trainer uses for checkpoints applies unchanged to serving. Engine build
(compilation, Orbax restore) runs with signal delivery blocked
(``flag.deferred()``) for the same native-code EINTR reasons as train.py.

``--follow`` turns the one-shot batch driver into the serving half of the
CONTINUOUS DEPLOYMENT LOOP (deploy/): the process stays up after the
initial prompt set, tails ``--request-file`` for new requests (JSONL, one
request per appended line) and polls the trainer's ``published.json``
between decode iterations. Each new publish is verified BEFORE load and
hot-swapped into the running engine without dropping in-flight requests
(deploy/reload.py has the swap state machine); a corrupt publish is
rejected + audited and serving continues on current weights. The drain
lifecycle is unchanged — SIGUSR1/SIGTERM finishes active requests and
exits 0.
"""

import argparse
import json
import os
import sys
import time

from ..chaos import SERVE_FAULTS, ChaosInjector, parse_schedule
from ..checkpoint.manager import update_checkpoint_age_gauge
from ..data.tokenizer import load_tokenizer
from ..deploy.reload import HotReloader, PointerWatcher
from ..ft.signals import SignalFlag
from ..models.configs import get_config
from ..obs import events, reqtrace
from ..obs.prometheus import MetricsServer
from ..obs.registry import REGISTRY
from ..utils.config import JOBID
from ..utils.logging import (
    AUDIT_ADAPTER_SUMMARY_FMT,
    AUDIT_KV_QUANT_FMT,
    AUDIT_LATENCY_FMT,
    AUDIT_REQUEST_DONE_FMT,
    AUDIT_SERVE_COMPLETED,
    AUDIT_SERVE_DRAINED_FMT,
    AUDIT_SERVE_DRAINING_FMT,
    AUDIT_SERVE_PREFILL_FMT,
    AUDIT_SERVE_PREFIX_FMT,
    AUDIT_SERVE_READY_FMT,
    AUDIT_SERVE_START,
    AUDIT_SERVE_STEP_FMT,
    AUDIT_SERVE_TREE_SPEC_FMT,
    init_logger,
    logger,
)
from .engine import (
    DEFAULT_COMPILE_CACHE_DIR,
    InferenceEngine,
    enable_compilation_cache,
    restore_params,
)
from .kv_cache import bf16_block_bytes, block_bytes
from .kvstore import BlockStore
from .sampler import AdaptiveK
from .scheduler import Request, Scheduler
from .transport import make_transport, resolve_lane

_DEMO_PROMPT = "alpha bravo charlie delta echo"

_M_KV_BYTES_PER_BLOCK = REGISTRY.gauge(
    "kv_bytes_per_block",
    "Bytes one paged KV pool block costs in the selected storage dtype "
    "(every layer's K+V slices; int8 mode includes the scale rows)")
_M_KV_DTYPE = REGISTRY.gauge(
    "kv_dtype",
    "Paged KV pool storage dtype as an info label (kv_dtype{dtype=...} 1)")
_M_ENGINE_ROLE = REGISTRY.gauge(
    "engine_role",
    "Disaggregated serving role as an info label "
    "(engine_role{engine_role=...} 1); serve.py is always the colocated "
    "'both' — dedicated prefill/decode roles are fleet.py --role")
_M_KV_TRANSPORT = REGISTRY.gauge(
    "kv_transport_lane",
    "Resolved KV transport lane as an info label "
    "(kv_transport_lane{lane=...} 1): the lane this process exports "
    "block trains on after same-pod auto-detect")


class _RequestFollower:
    """Tail a JSONL request file (``--follow --request-file``).

    Each line appended by the driver is one request:
    ``{"id": "...", "prompt": "text", "max_new_tokens": 8, ...}`` —
    missing knobs fall back to the serve flags. Only COMPLETE lines
    (newline-terminated) are consumed, tracked by byte offset, so a
    driver caught mid-append never yields a torn request."""

    def __init__(self, path: str, tokenizer, args):
        self.path = path
        self.tokenizer = tokenizer
        self.args = args
        self.offset = 0
        self.count = 0

    def ingest(self, sched: Scheduler) -> int:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        if size <= self.offset:
            return 0
        with open(self.path, "rb") as fh:
            fh.seek(self.offset)
            data = fh.read()
        end = data.rfind(b"\n")
        if end < 0:
            return 0
        chunk = data[:end + 1]
        self.offset += len(chunk)
        n = 0
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                prompt = self.tokenizer.encode(str(d["prompt"]))
            except (ValueError, KeyError, TypeError):
                logger.warning(f"[SERVE] skipping malformed request line "
                               f"{line!r}")
                continue
            rid = str(d.get("id", f"file{self.count}"))
            self.count += 1
            # the driver may carry its own trace_id (a router intake that
            # this serve process replays); otherwise mint one here — the
            # span trail starts at whichever process saw the request first
            max_new = int(d.get("max_new_tokens", self.args.max_new_tokens))
            trace_id = (str(d.get("trace_id", "") or "")
                        or reqtrace.mint_trace_id(rid))
            reqtrace.emit(trace_id, rid, "intake",
                          prompt_tokens=len(prompt), max_new_tokens=max_new)
            sched.submit(Request(
                id=rid, prompt=prompt,
                max_new_tokens=max_new,
                temperature=float(d.get("temperature",
                                        self.args.temperature)),
                top_p=float(d.get("top_p", self.args.top_p)),
                seed=int(d.get("seed", self.args.seed + self.count)),
                trace_id=trace_id,
                adapter=str(d.get("adapter", "") or "")))
            n += 1
        return n


def get_serve_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="fault_tolerant_llm_training_tpu.inference.serve",
        description="Serve a training checkpoint with continuous batching "
                    "and signal-drained shutdown.")
    p.add_argument("--checkpoint-path", required=True,
                   help="directory passed to training's --checkpoint-path")
    p.add_argument("--checkpoint-job-id", required=True,
                   help="job id the checkpoint was written under "
                        "(checkpoint_{id}/ subdirectory)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--model", default="tiny",
                   help="model preset the checkpoint was trained with")
    p.add_argument("--vocab-size", type=int, default=0,
                   help="0 = take the tokenizer's vocab (training default)")
    p.add_argument("--tokenizer-name-or-path", default="byte")
    p.add_argument("--layer-impl", default="loop",
                   choices=("loop", "scan"),
                   help="trunk form the checkpoint was trained with "
                        "(scan checkpoints are converted for decoding)")
    p.add_argument("--slots", type=int, default=2,
                   help="concurrent decode slots (continuous batching)")
    p.add_argument("--max-len", type=int, default=0,
                   help="KV cache length per slot; 0 = model seq_len")
    p.add_argument("--prefill-buckets", default="",
                   help="comma-separated AOT prefill lengths "
                        "(default: power-of-two ladder); with the paged "
                        "layout, longer prompts stream through the largest "
                        "bucket in chunks instead of being rejected")
    p.add_argument("--kv-layout", default="paged",
                   choices=("paged", "ring"),
                   help="KV cache layout: block-paged pool admitted by "
                        "free-block count (default), or the legacy "
                        "max_len-per-slot ring buffers")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="positions per KV block (paged layout)")
    p.add_argument("--kv-dtype", default="bf16",
                   choices=("bf16", "int8"),
                   help="paged KV pool storage dtype: 'bf16' (plain "
                        "pools), or 'int8' — blocks stored quantized "
                        "with per-(block, kv-head) fp32 scales in a "
                        "parallel scale pool, dequantized inside the "
                        "attention kernels (fused into the block DMA "
                        "under --paged-kernel pallas). Roughly halves "
                        "bytes/block, so the same HBM budget holds ~2x "
                        "the blocks (see BENCH_kv_quant_cpu.json); "
                        "greedy argmax ties may flip vs bf16 — the "
                        "within-dtype bit-exactness contracts (exact "
                        "spec-verify, burst, spill/handoff) all still "
                        "hold")
    p.add_argument("--kv-num-blocks", type=int, default=0,
                   help="total KV pool blocks incl. the null block; 0 = "
                        "full reservation parity (slots * max_len worth). "
                        "Set LOWER to serve more slots at the same HBM, "
                        "admission queues on block exhaustion")
    p.add_argument("--spill-dir", default="",
                   help="spill tier for the paged KV pool: on block "
                        "exhaustion the scheduler preempts the coldest "
                        "request and parks its private blocks as a "
                        "checksummed host artifact under this directory "
                        "(inference/kv_cache.py), restoring them on demand "
                        "bit-exactly; '' = spill disabled (admission waits "
                        "on exhaustion instead)")
    p.add_argument("--kv-store-dir", default="",
                   help="fleet-global KV block store root "
                        "(inference/kvstore.py): publish finished "
                        "prefills' full-block KV trains as checksummed "
                        "content-addressed artifacts and fetch the "
                        "deepest published prefix before each local "
                        "prefill; '' = store disabled")
    p.add_argument("--kv-store-max-bytes", type=int, default=0,
                   help="store publish byte budget: when the folded "
                        "resident bytes exceed this, publishes are "
                        "skipped (kv_store_publish_skipped_total) until "
                        "a sweep gets back under; 0 = unbounded")
    p.add_argument("--kv-transport", default="fs", choices=("fs", "mem"),
                   help="KV block-train transport lane "
                        "(inference/transport.py): 'fs' moves CRC-"
                        "verified filesystem artifacts (the durable "
                        "form); 'mem' additionally pushes trains device-"
                        "to-device in-process and verifies manifest "
                        "METADATA only, degrading to fs (then committed-"
                        "prefix replay) on any mismatch. serve.py is one "
                        "process, so 'mem' always applies here")
    p.add_argument("--paged-kernel", default="gather",
                   choices=("gather", "pallas"),
                   help="paged attention kernel (paged layout): 'gather' "
                        "assembles each slot's blocks into a contiguous "
                        "view and runs the ring kernel on it — the "
                        "bit-exact reference; 'pallas' reads pool blocks "
                        "in place through the block table "
                        "(ops/paged_attention.py) — no gathered copy, "
                        "equal within fp32 accumulation tolerance")
    p.add_argument("--decode-burst", type=int, default=1,
                   help="tokens per decode dispatch (paged layout): n > 1 "
                        "runs an n-token fused burst program — one "
                        "dispatch + one host sync per n tokens, greedy "
                        "streams bit-identical to per-token decode. "
                        "Admission/EOS eviction and the drain/stop probes "
                        "land at burst boundaries (at most n-1 tokens "
                        "later); mutually exclusive with --spec-k")
    p.add_argument("--adaptive-burst", action="store_true",
                   help="scale the burst width DOWN under queue / pending-"
                        "prefill pressure (halving per waiting unit, floor "
                        "1) so long bursts never starve admission; the "
                        "existing per-slot budget clamp is unchanged. "
                        "Requires --decode-burst > 1")
    p.add_argument("--prefill-batch", type=int, default=1,
                   help="packed multi-request prefill (paged layout): P > 1 "
                        "packs up to P admitted requests' next prompt "
                        "chunks — each at its own absolute offset and "
                        "block-table row, prefix-cache resume offsets "
                        "included — into ONE (P, bucket) AOT dispatch per "
                        "scheduler step, interleaved with decode rounds "
                        "instead of draining admission one prompt at a "
                        "time. Streams stay bit-identical to sequential "
                        "prefill on the gather impl; mutually exclusive "
                        "with --spec-k")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable the content-addressed prefix cache "
                        "(paged layout): admissions sharing a committed "
                        "prompt prefix then re-run the full prefill "
                        "instead of pointing their block tables at the "
                        "shared blocks (copy-on-write on divergence)")
    p.add_argument("--compile-cache-dir",
                   default=None,
                   help="JAX persistent compilation cache directory "
                        "(default: ~/.cache/fault_tolerant_llm_training_tpu/"
                        "xla-cache; '' disables). Warm engine builds skip "
                        "the AOT prefill/decode compiles")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative decoding: draft proposes k tokens per "
                        "round, one verify pass scores all k+1 positions "
                        "(0 = off). Requires --draft-checkpoint-path and "
                        "the paged KV layout; greedy output is bit-exact "
                        "vs --spec-k 0")
    p.add_argument("--draft-checkpoint-path", default="",
                   help="training checkpoint directory of the DRAFT model")
    p.add_argument("--draft-checkpoint-job-id", default="",
                   help="job id the draft checkpoint was written under")
    p.add_argument("--draft-step", type=int, default=None,
                   help="draft checkpoint step (default: latest)")
    p.add_argument("--draft-preset", default="tiny",
                   help="model preset the draft checkpoint was trained "
                        "with (any models/configs.py preset; must share "
                        "the target's vocab)")
    p.add_argument("--draft-layer-impl", default="loop",
                   choices=("loop", "scan"))
    p.add_argument("--draft-kv-num-blocks", type=int, default=0,
                   help="draft KV pool blocks incl. the null block; 0 = "
                        "full reservation parity. The scheduler admits by "
                        "the COMBINED footprint across both pools")
    p.add_argument("--adapter-rank", type=int, default=0,
                   help="multi-tenant LoRA serving: low-rank adapter rank "
                        "r (0 = adapter serving off). Adapter A/B factors "
                        "page into a third block pool next to the KV "
                        "pools; every slot carries its adapter's page rows "
                        "into ONE fused decode dispatch, so slots serving "
                        "DIFFERENT adapters batch together. Adapter '' is "
                        "the null adapter — base-only, bit-identical to "
                        "--adapter-rank 0 output")
    p.add_argument("--adapter-pages", type=int, default=0,
                   help="adapter page pool size incl. the null page; 0 = "
                        "room for 4 adapters. Cold adapters evict under "
                        "pressure (refcounted, like KV blocks) and reload "
                        "CRC-verified from their published artifacts")
    p.add_argument("--adapter", action="append", default=[],
                   metavar="NAME=DIR", dest="adapters",
                   help="register a published adapter artifact at startup "
                        "(repeatable); requests name it via the 'adapter' "
                        "field of a --request-file line. Requires "
                        "--adapter-rank matching the artifact's rank")
    p.add_argument("--prompt-adapter", action="append", default=[],
                   metavar="NAME",
                   help="adapter for the i-th --prompt (repeatable, "
                        "positional; missing entries = '' base-only)")
    p.add_argument("--spec-verify-impl", default="exact",
                   choices=("exact", "chunk"),
                   help="verify-k scoring: 'exact' micro-steps k+1 S=1 "
                        "forwards in one program (greedy streams bit-match "
                        "the non-speculative path by construction); 'chunk' "
                        "runs one (slots, k+1) forward, batching the verify "
                        "FLOPs, but bf16 GEMM accumulation is shape-"
                        "dependent and a one-ulp near-tie can flip an "
                        "argmax vs the S=1 decode program")
    p.add_argument("--spec-tree", default="",
                   help="TREE speculative decoding: comma list of per-depth "
                        "branch fan-outs (e.g. '2,2,1') — the draft's "
                        "k-chain plus free top-k sibling fan-outs, all "
                        "scored by ONE ancestor-masked verify dispatch; an "
                        "accepted sibling rescues a round linear "
                        "speculation would have cut short. '' = linear "
                        "--spec-k rounds. Requires --spec-k; '1,1,...' "
                        "degenerates to the linear chain. With "
                        "--adaptive-spec-k the controller's budget picks a "
                        "sub-shape per round (TreeShape.shrink_to). Under "
                        "--spec-verify-impl exact only the primary chain "
                        "is scored (greedy streams bit-match --spec-k 0 by "
                        "construction); 'chunk' scores every branch")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prompt", action="append", default=[],
                   help="repeatable; each becomes one request")
    p.add_argument("--repeat", type=int, default=1,
                   help="submit the prompt set this many times (load gen)")
    p.add_argument("--no-eos", action="store_true",
                   help="ignore EOS; always decode max-new-tokens")
    p.add_argument("--log-frequency", type=int, default=8)
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve Prometheus /metrics on this port "
                        "(0 = disabled); TTFT, decode-step, slot occupancy")
    p.add_argument("--event-log", default="",
                   help="flight-recorder JSONL path ('' = disabled)")
    p.add_argument("--trace-log", default="",
                   help="request-span trail JSONL (obs/reqtrace.py); "
                        "defaults to trace_<name>.jsonl next to "
                        "--event-log ('' with no --event-log = disabled)")
    p.add_argument("--chaos", default="",
                   help="fault schedule keyed by decode iteration "
                        "('step=<N>:sigusr1' / 'step=<N>:sigterm'; "
                        "chaos/schedule.py grammar) — delivers a real "
                        "drain signal mid-decode; 'step=<N>:reload_signal' "
                        "(keyed by reload ordinal) lands a SIGUSR1 in the "
                        "middle of the Nth hot weight swap; "
                        "'step=<N>:spill_corrupt' (keyed by spill export "
                        "ordinal) flips a payload byte in the Nth spill "
                        "artifact — the restore must CRC-reject it and "
                        "replay")
    p.add_argument("--follow", action="store_true",
                   help="continuous-deployment mode: stay up after the "
                        "initial prompts, tail --request-file for new "
                        "requests and hot-reload each verified publish of "
                        "published.json (deploy/) without dropping "
                        "in-flight requests; SIGUSR1/SIGTERM still drains "
                        "and exits 0")
    p.add_argument("--poll-seconds", type=float, default=1.0,
                   help="published.json / request-file poll interval while "
                        "idle in --follow mode")
    p.add_argument("--request-file", default="",
                   help="JSONL file tailed for requests in --follow mode "
                        "(one {'id','prompt',...} object per line; "
                        "complete lines only)")
    p.add_argument("--journal-dir", default="",
                   help="request-journal directory (inference/journal.py): "
                        "a signal drain persists every unserved queued "
                        "request as a requeue record there, so a fleet "
                        "router (inference/router.py) can re-admit them on "
                        "another host instead of losing them ('' = off)")
    p.add_argument("--adaptive-spec-k", action="store_true",
                   help="tune the speculative round width per request from "
                        "live acceptance (sampler.AdaptiveK): a stale "
                        "draft — e.g. right after a target-only hot swap — "
                        "walks k toward 1 instead of burning --spec-k "
                        "rejected proposals per round")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = get_serve_args(argv)
    init_logger()
    flag = SignalFlag()
    flag.register()  # before engine build, like train.py
    # Chaos (chaos/): serving supports only the signal faults — a drain
    # delivered mid-decode. Parse errors (or non-serve faults) fail fast,
    # before the expensive engine build.
    chaos = None
    if args.chaos:
        chaos = ChaosInjector(
            parse_schedule(args.chaos, allowed=SERVE_FAULTS),
            seed=args.seed)
        logger.info(f"Chaos schedule | {chaos.describe()}")
    if args.event_log:
        events.configure(args.event_log, job=JOBID or "serve",
                         host=os.getpid())
    trace_log = args.trace_log or (
        reqtrace.derive_trace_path(args.event_log) if args.event_log
        else "")
    if trace_log:
        reqtrace.configure(trace_log, job=JOBID or "serve",
                           host=os.getpid())
    metrics_server = None
    if args.metrics_port:
        metrics_server = MetricsServer(port=args.metrics_port)
        port = metrics_server.start()
        logger.info(f"Metrics | serving /metrics on port {port}")
    events.emit_audit(logger, AUDIT_SERVE_START, "start")

    with flag.deferred():  # block delivery across compile + Orbax restore
        cache_dir = (DEFAULT_COMPILE_CACHE_DIR
                     if args.compile_cache_dir is None
                     else args.compile_cache_dir)
        if enable_compilation_cache(cache_dir):
            logger.info(f"Compilation cache | {cache_dir}")
        tokenizer = load_tokenizer(args.tokenizer_name_or_path)
        vocab = args.vocab_size or tokenizer.vocab_size
        cfg = get_config(args.model, vocab_size=vocab,
                         layer_impl=args.layer_impl)
        buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
                   if args.prefill_buckets else None)
        spec_kwargs = {}
        draft_step_restored = None
        draft_cfg = None
        if args.spec_k:
            if not (args.draft_checkpoint_path
                    and args.draft_checkpoint_job_id):
                raise SystemExit(
                    "--spec-k requires --draft-checkpoint-path and "
                    "--draft-checkpoint-job-id")
            draft_cfg = get_config(args.draft_preset, vocab_size=vocab,
                                   layer_impl=args.draft_layer_impl)
            # the draft loads through the SAME cross-topology restore path
            # as the target — any preset, its own training run
            draft_params, draft_step_restored = restore_params(
                args.draft_checkpoint_path, args.draft_checkpoint_job_id,
                draft_cfg, step=args.draft_step)
            spec_kwargs = dict(
                draft_cfg=draft_cfg, draft_params=draft_params,
                spec_k=args.spec_k,
                draft_num_blocks=args.draft_kv_num_blocks or None,
                spec_verify_impl=args.spec_verify_impl,
                spec_tree=args.spec_tree or None)
        elif args.spec_tree:
            raise SystemExit("--spec-tree requires --spec-k (the tree "
                             "widens the speculative rounds)")
        engine = InferenceEngine.from_checkpoint(
            args.checkpoint_path, args.checkpoint_job_id, cfg,
            step=args.step, slots=args.slots,
            max_len=args.max_len or None, prefill_buckets=buckets,
            top_k=args.top_k, kv_layout=args.kv_layout,
            kv_block_size=args.kv_block_size,
            kv_num_blocks=args.kv_num_blocks or None,
            prefix_cache=not args.no_prefix_cache,
            paged_kernel=args.paged_kernel,
            prefill_batch=args.prefill_batch,
            kv_dtype=args.kv_dtype,
            adapter_rank=args.adapter_rank,
            adapter_num_pages=args.adapter_pages,
            **spec_kwargs)
        if args.adapters:
            if not args.adapter_rank:
                raise SystemExit("--adapter requires --adapter-rank")
            for spec in args.adapters:
                name, sep, art_dir = spec.partition("=")
                if not (sep and name and art_dir):
                    raise SystemExit(f"--adapter expects NAME=DIR, "
                                     f"got {spec!r}")
                engine.adapters.register(name, art_dir)
                logger.info("Adapter registered | %s -> %s", name, art_dir)
        if args.kv_layout == "paged":
            # capacity surface for dashboards: bytes one block costs in
            # the selected storage dtype (scale rows included) and the
            # dtype itself as an info label — with kv_blocks_total these
            # give blocks-per-HBM-budget directly
            bpb = block_bytes(engine.cache)
            _M_KV_BYTES_PER_BLOCK.set(bpb)
            _M_KV_DTYPE.labels(dtype=engine.kv_dtype).set(1)
        _M_ENGINE_ROLE.labels(engine_role="both").set(1)
        if args.spec_k:
            engine.draft_restored_step = draft_step_restored
            logger.info(
                "Speculative decoding | draft=%s step=%s k=%d verify=%s "
                "tree=%s",
                args.draft_preset, draft_step_restored, args.spec_k,
                args.spec_verify_impl, args.spec_tree or "off")
        events.emit_audit(
            logger, AUDIT_SERVE_READY_FMT.format(
                model=args.model, step=engine.restored_step,
                slots=args.slots),
            "ready", step=engine.restored_step, slots=args.slots,
            model=args.model)
        # stop_check lets a chunked prefill see the signal BETWEEN chunks:
        # a mid-prompt SIGUSR1/SIGTERM finishes the current chunk, frees the
        # request's blocks and reports it unserved — exact drain, any
        # prompt length.
        adaptive = (AdaptiveK(args.spec_k)
                    if args.spec_k and args.adaptive_spec_k else None)
        # serve.py is one process: every import of its exports happens
        # here, so a requested mem lane always resolves to mem
        lane = resolve_lane(args.kv_transport, colocated=True)
        transport = make_transport(lane)
        _M_KV_TRANSPORT.labels(lane=lane).set(1)
        if lane != "fs":
            logger.info("KV transport: %s lane (fs artifacts remain the "
                        "durable fallback)", lane)
        sched = Scheduler(engine,
                          eos_token_id=(None if args.no_eos
                                        else tokenizer.eos_token_id),
                          stop_check=lambda: flag.signum is not None,
                          adaptive_k=adaptive,
                          decode_burst=args.decode_burst,
                          prefill_batch=args.prefill_batch,
                          adaptive_burst=args.adaptive_burst,
                          spill_dir=args.spill_dir or None,
                          on_spill=(chaos.on_spill if chaos is not None
                                    else None),
                          kv_store=(BlockStore(args.kv_store_dir,
                                               writer=f"serve_{os.getpid()}")
                                    if args.kv_store_dir else None),
                          transport=transport,
                          kv_store_max_bytes=args.kv_store_max_bytes)
        base_prompts = args.prompt or ([] if args.follow else [_DEMO_PROMPT])
        prompts = base_prompts * args.repeat
        for i, text in enumerate(prompts):
            rid = f"req{i}"
            prompt = tokenizer.encode(text)
            trace_id = reqtrace.mint_trace_id(rid)
            reqtrace.emit(trace_id, rid, "intake",
                          prompt_tokens=len(prompt),
                          max_new_tokens=args.max_new_tokens)
            j = i % len(base_prompts) if base_prompts else 0
            aname = (args.prompt_adapter[j]
                     if j < len(args.prompt_adapter) else "")
            sched.submit(Request(
                id=rid, prompt=prompt,
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, top_p=args.top_p,
                seed=args.seed + i, trace_id=trace_id,
                adapter=aname))
        watcher = reloader = follower = None
        if args.follow:
            watcher = PointerWatcher(args.checkpoint_path)
            reloader = HotReloader(engine, sched, cfg,
                                   args.checkpoint_path,
                                   draft_cfg=draft_cfg,
                                   adaptive_k=adaptive, chaos=chaos)
            if args.request_file:
                follower = _RequestFollower(args.request_file, tokenizer,
                                            args)
            # catch up to the startup pointer: if it names a different
            # step than we restored (e.g. the trainer published while the
            # engine compiled), swap before taking traffic; if it names
            # the serving step, the poll just primes the watcher's
            # seen-key so the same publish is never re-offered
            ptr0 = watcher.poll()
            if ptr0 is not None and ptr0.step != engine.restored_step:
                reloader.maybe_reload(ptr0)

    drained = False
    while sched.pending() or (args.follow and not drained):
        if args.follow and not drained:
            if follower is not None:
                follower.ingest(sched)
            if not sched.pending() and flag.signum is None:
                # idle follow tick: no requests in flight — absorb any
                # publish now, then wait for work or a signal
                if reloader.maybe_reload(watcher.poll()):
                    continue  # a swap may race a fresh publish; re-poll
                time.sleep(args.poll_seconds)
                continue
        if chaos is not None:
            # keyed by decode iteration: the signal lands here and the
            # flag check just below begins the drain lifecycle mid-decode
            chaos.on_serve_step(sched.iterations)
        update_checkpoint_age_gauge()
        # not admission_open: a chunked prefill may have seen the signal
        # first (scheduler stop_check) and closed admission itself — the
        # audit trail must still record the drain exactly once.
        if flag.signum is not None and not drained:
            events.emit_audit(
                logger, AUDIT_SERVE_DRAINING_FMT.format(
                    signum=flag.signum, active=len(sched.active)),
                "drain", phase="begin", signum=flag.signum,
                active=len(sched.active))
            sched.stop_admission()
            drained = True
        if reloader is not None and not drained:
            # between decode iterations — the in-flight round is finished,
            # so this is exactly the swap's prefill-pause point
            old_step = engine.restored_step
            t_swap = time.monotonic()
            if reloader.maybe_reload(watcher.poll()):
                # a swap stalled every in-flight request for its duration:
                # pin the pause on each active trace so a latency report
                # attributes the decode gap to the reload, not the model
                pause = time.monotonic() - t_swap
                for st in sched.active.values():
                    tid = getattr(st.request, "trace_id", "")
                    if tid:
                        reqtrace.emit(tid, st.request.id, "reload_pause",
                                      dur=pause, old=old_step,
                                      new=engine.restored_step)
        for c in sched.step():
            decoded = c.tokens[:-1] if (not args.no_eos and c.reason == "eos"
                                        ) else c.tokens
            events.emit_audit(
                logger, AUDIT_REQUEST_DONE_FMT.format(
                    id=c.request_id, reason=c.reason,
                    prompt_tokens=c.prompt_len, new_tokens=len(c.tokens),
                    ttft_ms=c.ttft_seconds * 1e3,
                    tps=c.decode_tokens_per_sec),
                "request_done", id=c.request_id, reason=c.reason,
                tokens=len(c.tokens), ttft_ms=c.ttft_seconds * 1e3)
            logger.info("Request %s output: %r", c.request_id,
                        tokenizer.decode(decoded))
            if args.spec_k:
                # drain-audit companion: how many of this request's tokens
                # the verifier emitted that the draft never proposed
                # (bonus/corrected) — with the proposal/accept counts this
                # reconciles the emitted stream exactly
                logger.info(
                    "Request %s spec: proposed=%d accepted=%d "
                    "emitted_not_proposed=%d", c.request_id,
                    c.spec_proposed, c.spec_accepted,
                    c.spec_emitted_not_proposed)
        if sched.iterations and sched.iterations % args.log_frequency == 0:
            events.emit_audit(
                logger, AUDIT_SERVE_STEP_FMT.format(
                    step=sched.iterations, active=len(sched.active),
                    queued=len(sched.queue), done=len(sched.completed)),
                "step", step=sched.iterations, active=len(sched.active),
                queued=len(sched.queue), done=len(sched.completed))

    if flag.signum is not None and not drained:
        # the signal was consumed inside a chunked prefill on the final
        # iteration — the loop exited before the top-of-loop check ran
        events.emit_audit(
            logger, AUDIT_SERVE_DRAINING_FMT.format(
                signum=flag.signum, active=len(sched.active)),
            "drain", phase="begin", signum=flag.signum,
            active=len(sched.active))
        drained = True

    m = sched.metrics()
    logger.info("Serving metrics: %d requests | %d tokens | "
                "%.1f tok/s (%.1f/slot) | decode p50 %.1f ms p95 %.1f ms",
                m["requests_completed"], m["tokens_generated"],
                m["tokens_per_sec"], m["tokens_per_sec_per_slot"],
                m["decode_p50_ms"], m["decode_p95_ms"])
    # the fused-decode win in the drain receipt: per-token decode reads
    # 1.00 dispatches/token; burst n amortizes toward 1/n
    logger.info("Decode dispatch metrics: burst=%d | %d dispatches | "
                "%d host syncs | %d decode tokens | "
                "%.3f dispatches/token | %.3f syncs/token",
                m["decode_burst"], m["decode_dispatches"],
                m["decode_host_syncs"], m["decode_tokens"],
                m["dispatches_per_token"], m["host_syncs_per_token"])
    if args.spec_k:
        logger.info(
            "Spec metrics: k=%d | %d rounds | %d drafted | %d accepted | "
            "acceptance %.3f", m["spec_k"], m["spec_rounds"],
            m["spec_draft_tokens"], m["spec_accepted_tokens"],
            m["spec_acceptance_rate"])
        if args.spec_tree:
            # tree-widening receipt in the drain summary: nodes scored per
            # verify dispatch, accepted tokens per round (the perf claim),
            # and how much of the acceptance came OFF the primary chain —
            # the rescue linear speculation cannot make
            events.emit_audit(
                logger, AUDIT_SERVE_TREE_SPEC_FMT.format(
                    shape=m["spec_tree"], rounds=m["spec_tree_rounds"],
                    nodes=m["spec_tree_nodes"],
                    per_round=m["spec_accepted_per_round"],
                    util=m["spec_tree_branch_utilization"]),
                "tree_spec", shape=m["spec_tree"],
                rounds=m["spec_tree_rounds"], nodes=m["spec_tree_nodes"],
                accepted_per_round=m["spec_accepted_per_round"],
                branch_utilization=m["spec_tree_branch_utilization"])
    if sched.prefill_batch > 1:
        # packed-lane occupancy in the drain receipt: how full the packed
        # prefill dispatches ran, and which kernel their paged reads took
        # (inplace under --paged-kernel pallas — no silent gather)
        events.emit_audit(
            logger, AUDIT_SERVE_PREFILL_FMT.format(
                rounds=m["prefill_packed_rounds"],
                rows=m["prefill_packed_rows"],
                occupancy=m["prefill_packed_occupancy"],
                inplace=m["prefill_inplace_chunks"],
                gather=m["prefill_gather_chunks"]),
            "packed_prefill", rounds=m["prefill_packed_rounds"],
            rows=m["prefill_packed_rows"],
            occupancy=m["prefill_packed_occupancy"],
            inplace_chunks=m["prefill_inplace_chunks"],
            gather_chunks=m["prefill_gather_chunks"])
    if engine.kv_layout == "paged":
        # the --kv-dtype receipt in the drain summary: storage dtype,
        # bytes one block costs (scale rows included), capacity ratio vs
        # the bf16 layout at the same geometry (bf16 reads 1.00)
        bpb = block_bytes(engine.cache)
        ratio = bf16_block_bytes(engine.cache) / bpb
        events.emit_audit(
            logger, AUDIT_KV_QUANT_FMT.format(
                dtype=engine.kv_dtype, bytes_per_block=bpb, ratio=ratio,
                blocks_total=engine.num_blocks),
            "kv_quant", dtype=engine.kv_dtype, bytes_per_block=bpb,
            ratio=ratio, blocks_total=engine.num_blocks)
    if sched.prefix_cache is not None:
        # hit rate rides the drain-summary audit trail: the receipt an
        # operator greps after a drain shows how much prefill the cache
        # absorbed, next to the request/token counts it absorbed it for
        events.emit_audit(
            logger, AUDIT_SERVE_PREFIX_FMT.format(
                lookups=m["prefix_lookups"], rate=m["prefix_hit_rate"],
                hit_tokens=m["prefix_hit_tokens"],
                cached=m["prefix_cached_blocks"],
                cow=m["prefix_cow_copies"], evictions=m["prefix_evictions"]),
            "prefix_cache", lookups=m["prefix_lookups"],
            hit_rate=m["prefix_hit_rate"],
            hit_tokens=m["prefix_hit_tokens"],
            cached_blocks=m["prefix_cached_blocks"],
            cow_copies=m["prefix_cow_copies"],
            evictions=m["prefix_evictions"])
    if sched.adapters is not None:
        # multi-tenant adapter receipt in the drain summary: how many
        # distinct adapters this process served, page-in/eviction churn
        # in the adapter pool, bytes still resident, and rejects (corrupt
        # or unregistered artifacts that never reached the pool)
        events.emit_audit(
            logger, AUDIT_ADAPTER_SUMMARY_FMT.format(
                served=m["adapters_served"],
                pageins=m["adapter_pageins"],
                evictions=m["adapter_evictions"],
                resident_bytes=m["adapter_pages_resident_bytes"],
                rejects=m["adapter_rejects"]),
            "adapter_summary", served=m["adapters_served"],
            pageins=m["adapter_pageins"],
            evictions=m["adapter_evictions"],
            resident_bytes=m["adapter_pages_resident_bytes"],
            rejects=m["adapter_rejects"])
    # Per-request latency audit: the drain summary's SLO receipt — TTFT
    # and TPOT per completed request, keyed by the trace id that joins
    # this process's spans to the router's (obs/reqtrace.py)
    for c in sched.completed:
        events.emit_audit(
            logger, AUDIT_LATENCY_FMT.format(
                id=c.request_id, trace=c.trace_id or "-",
                ttft_ms=c.ttft_seconds * 1e3,
                tpot_ms=c.tpot_seconds * 1e3,
                tokens=len(c.tokens), reason=c.reason),
            "latency", id=c.request_id, trace=c.trace_id,
            ttft=c.ttft_seconds, tpot=c.tpot_seconds,
            tokens=len(c.tokens), reason=c.reason)
    # leak guard: with the loop idle, every block must be free or
    # cache-held; violations audit once ([KV LEAK]) but keep the exit-0
    # contract (the strict mode is for tests, via Scheduler.run)
    sched.audit_block_leaks(strict=False)
    if drained:
        unserved = sched.unserved()
        if args.journal_dir and unserved:
            # zero-lost-requests half of the drain contract: what this
            # process will not serve, the journal keeps (params + committed
            # baseline) for a router to re-admit elsewhere
            from .journal import RequestJournal, persist_unserved

            journal = RequestJournal(args.journal_dir,
                                     writer=f"serve_{os.getpid()}")
            persist_unserved(journal, unserved,
                             reason=f"drain_sig{flag.signum}")
        events.emit_audit(
            logger, AUDIT_SERVE_DRAINED_FMT.format(
                completed=len(sched.completed), queued=len(sched.queue)),
            "drain", phase="end", completed=len(sched.completed),
            queued=len(sched.queue))
    if sched.enable_spill:
        # spilled requests were reported unserved above (committed
        # baseline in their requeue records); their artifacts are now
        # dead weight on the host tier
        sched.discard_spilled()
    events.emit_audit(logger, AUDIT_SERVE_COMPLETED, "complete")
    events.flush()
    reqtrace.flush()
    if metrics_server is not None:
        metrics_server.stop()
    # exit 0 always — same contract as training: the exit POLICY is in the
    # logs, not the return code (nonzero would trip Slurm requeue logic)
    sys.exit(0)


if __name__ == "__main__":
    main()
