"""Slot-based continuous batching (Orca-style, static-shape XLA flavor).

One decode program serves all slots every iteration; requests are admitted
into free slots *between* decode iterations (no stop-the-world batch
boundary, the Orca/vLLM scheduling insight) and evicted the moment they hit
EOS or their token budget — a freed slot is re-filled on the very next
iteration. All shapes stay static: "admission" is a prefill into one slot of
the fixed (slots, ...) cache, "eviction" is host bookkeeping plus the mask
bit in the decode step.

Under the engine's paged KV layout (the default) the scheduler also owns
the :class:`BlockAllocator`: admission is gated by FREE BLOCK COUNT —
``ceil((prompt + max_new_tokens) / block_size)`` blocks per request — not
just by a free slot, so a long-context cache no longer reserves ``max_len``
per slot and far more requests fit the same HBM; eviction frees the blocks
for the next admission. A request whose blocks aren't available yet simply
waits at the head of the queue (FIFO, no starvation) — exhaustion queues,
it never crashes.

When the engine was built with a draft model (``spec_k > 0``) the
scheduler runs SPECULATIVE rounds instead of single-token decode
iterations: each round emits 1..k+1 tokens per slot (engine.py
``spec_round``). The draft model has its own block pool, so the scheduler
owns a SECOND :class:`BlockAllocator` and block table; admission is gated
by the COMBINED draft+target footprint (both pools must cover the
request, or it waits at the head of the queue), and eviction/drain frees
both pools together. Acceptance statistics are exported per round
(``ftl_spec_*`` metrics) and per request (Completion spec fields).

The scheduler is also the drain point for the fault-tolerant serving
lifecycle: ``stop_admission()`` (serve.py calls it when a SIGUSR1/SIGTERM
flag fires) freezes the queue while active slots run to completion, so
in-flight requests finish and queued ones are reported unserved — the
serving analogue of the trainer's save-on-signal exit policy. Chunked
prefills consult ``stop_check`` between chunks, so a signal that lands
mid-prompt finishes the current chunk only, frees the request's blocks and
reports it unserved — the drain stays exact even for long prompts.
"""

import dataclasses
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs.registry import (
    SPEC_TOKEN_BUCKETS,
    MetricRegistry,
    default_registry,
)


class BlockAllocator:
    """Host-side free list over the paged cache's block pool.

    Block 0 is the reserved null/scratch block (inference/kv_cache.py):
    free block-table entries point at it and masked writes divert into it,
    so it is never handed out. ``free()`` refuses double-frees — an
    allocator bug corrupting two requests' caches should fail loudly, not
    silently cross-wire their KV.
    """

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # LIFO: reuse warm
        self._used: set = set()

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1  # block 0 reserved

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._used)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None if fewer than n are free (caller queues)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._used.update(blocks)
        return blocks

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b not in self._used:
                raise ValueError(f"double free of block {b}")
            self._used.remove(b)
            self._free.append(b)


@dataclasses.dataclass
class Request:
    id: str
    prompt: Sequence[int]          # token ids, BOS included by the caller
    max_new_tokens: int = 32
    temperature: float = 0.0       # <= 0 -> greedy
    top_p: float = 1.0
    seed: int = 0


@dataclasses.dataclass
class Completion:
    request_id: str
    prompt_len: int
    tokens: List[int]              # generated ids (EOS included if hit)
    reason: str                    # "eos" | "length"
    submitted_at: float
    first_token_at: float
    finished_at: float
    # Speculative-decoding accounting (zero in non-spec mode): draft tokens
    # proposed for this request, proposals the verify pass accepted, and
    # tokens EMITTED-NOT-PROPOSED — the verify pass's bonus/corrected
    # tokens, i.e. output the draft never suggested (the drain audit logs
    # these per request so an operator can see how much of a stream the
    # draft actually produced).
    spec_proposed: int = 0
    spec_accepted: int = 0
    spec_emitted_not_proposed: int = 0

    @property
    def ttft_seconds(self) -> float:
        """Time to first token (queue wait + prefill)."""
        return self.first_token_at - self.submitted_at

    @property
    def latency_seconds(self) -> float:
        return self.finished_at - self.submitted_at

    @property
    def decode_tokens_per_sec(self) -> float:
        decoded = len(self.tokens) - 1  # first token came from prefill
        dt = self.finished_at - self.first_token_at
        return decoded / dt if decoded > 0 and dt > 0 else 0.0


class _Slot:
    def __init__(self, request: Request, first_token: int,
                 submitted_at: float, now: float):
        self.request = request
        self.tokens = [first_token]
        self.steps = 1  # decode-step counter; prefill consumed step 0
        self.submitted_at = submitted_at
        self.first_token_at = now
        # spec-mode per-request accounting (see Completion)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_corrected = 0


class Scheduler:
    """Continuous-batching loop over an :class:`~.engine.InferenceEngine`."""

    def __init__(self, engine, eos_token_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricRegistry] = None,
                 stop_check: Optional[Callable[[], bool]] = None):
        self.engine = engine
        self.eos_token_id = eos_token_id
        self.clock = clock
        self.queue: deque = deque()        # (Request, submitted_at)
        self.active: Dict[int, _Slot] = {}  # slot index -> state
        self.completed: List[Completion] = []
        self.admission_open = True
        self.iterations = 0
        self.max_concurrent = 0
        self.step_seconds: List[float] = []  # decode-iteration wall times
        # Drain probe consulted BETWEEN prefill chunks (serve.py passes the
        # signal flag) so a mid-prompt SIGUSR1/SIGTERM aborts cleanly at a
        # chunk boundary; run(stop=...) installs its callable here too.
        self.stop_check = stop_check
        self.kv_layout = getattr(engine, "kv_layout", "ring")
        self.prefill_chunks = 0
        self.max_block_utilization = 0.0
        if self.kv_layout == "paged":
            self.allocator = BlockAllocator(engine.num_blocks)
            self.block_tables = np.zeros(
                (engine.slots, engine.max_blocks_per_slot), np.int32)
            self._slot_blocks: Dict[int, List[int]] = {}
        # Speculative mode: the draft model's pool gets its own allocator
        # and block table; admission requires BOTH footprints (below).
        self.spec_k = int(getattr(engine, "spec_k", 0) or 0)
        if self.spec_k:
            self.draft_allocator = BlockAllocator(engine.draft_num_blocks)
            self.draft_block_tables = np.zeros(
                (engine.slots, engine.max_blocks_per_slot), np.int32)
            self._slot_draft_blocks: Dict[int, List[int]] = {}
            self.spec_rounds = 0
            self.spec_draft_tokens = 0
            self.spec_accepted_tokens = 0
        # /metrics surface (obs/registry.py): serve.py --metrics-port scrapes
        # these live while the batching loop runs.
        r = registry or default_registry()
        self._m_ttft = r.histogram(
            "ftl_serve_ttft_seconds",
            "Time to first token (queue wait + prefill) per request")
        self._m_decode = r.histogram(
            "ftl_serve_decode_step_seconds",
            "Wall time of one batched decode iteration")
        self._m_tokens = r.counter("ftl_serve_tokens_generated_total",
                                   "Tokens generated across all requests")
        self._m_done = r.counter(
            "ftl_serve_requests_completed_total",
            "Requests completed, by finish reason (eos|length)")
        self._m_occupancy = r.gauge(
            "ftl_serve_slot_occupancy",
            "Active decode slots / total slots (0-1)")
        self._m_queue = r.gauge("ftl_serve_queue_depth",
                                "Requests waiting for a free slot")
        self._m_tps = r.gauge("ftl_serve_tokens_per_sec",
                              "Aggregate decode throughput (running)")
        self._m_blocks_free = r.gauge(
            "ftl_serve_kv_blocks_free",
            "Free KV cache blocks in the paged pool (block 0 excluded)")
        self._m_block_util = r.gauge(
            "ftl_serve_kv_block_utilization",
            "Allocated / usable KV cache blocks (0-1)")
        self._m_chunks = r.counter(
            "ftl_serve_prefill_chunks_total",
            "Prefill chunks executed (chunked long-prompt prefill)")
        self._m_spec_draft = r.counter(
            "ftl_spec_draft_tokens_total",
            "Draft-model tokens proposed (speculative decoding)")
        self._m_spec_accepted = r.counter(
            "ftl_spec_accepted_tokens_total",
            "Draft proposals accepted by the target verify pass")
        self._m_spec_rate = r.gauge(
            "ftl_spec_acceptance_rate",
            "Running accepted/proposed draft-token ratio (0-1)")
        self._m_spec_round_tokens = r.histogram(
            "ftl_spec_tokens_per_round",
            "Tokens banked per verify round (accepted prefix + bonus, "
            "after EOS/budget truncation)",
            buckets=SPEC_TOKEN_BUCKETS)
        if self.kv_layout == "paged":
            self._m_blocks_free.set(self.allocator.free_count)

    # --- queue management --------------------------------------------------

    def _blocks_needed(self, request: Request) -> int:
        bs = self.engine.block_size
        return -(-(len(request.prompt) + request.max_new_tokens) // bs)

    def submit(self, request: Request) -> None:
        if len(request.prompt) + request.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"request {request.id}: prompt {len(request.prompt)} + "
                f"max_new_tokens {request.max_new_tokens} exceeds the "
                f"cache max_len {self.engine.max_len}")
        if (self.kv_layout == "paged"
                and self._blocks_needed(request) > self.allocator.capacity):
            raise ValueError(
                f"request {request.id}: needs {self._blocks_needed(request)} "
                f"KV blocks but the pool only has "
                f"{self.allocator.capacity} usable blocks")
        if (self.spec_k and self._blocks_needed(request)
                > self.draft_allocator.capacity):
            raise ValueError(
                f"request {request.id}: needs {self._blocks_needed(request)} "
                f"DRAFT KV blocks but the draft pool only has "
                f"{self.draft_allocator.capacity} usable blocks")
        self.queue.append((request, self.clock()))

    def stop_admission(self) -> None:
        """Drain mode: active slots finish, the queue stays unserved."""
        self.admission_open = False

    def pending(self) -> bool:
        return bool(self.active or (self.queue and self.admission_open))

    def unserved(self) -> List[Request]:
        return [r for r, _ in self.queue]

    # --- one decode iteration ----------------------------------------------

    def _finish(self, slot: int, reason: str, done: List[Completion]) -> None:
        st = self.active.pop(slot)
        if self.kv_layout == "paged":
            blocks = self._slot_blocks.pop(slot, None)
            if blocks:
                self.allocator.free(blocks)
                self.block_tables[slot] = 0
        if self.spec_k:
            dblocks = self._slot_draft_blocks.pop(slot, None)
            if dblocks:
                self.draft_allocator.free(dblocks)
                self.draft_block_tables[slot] = 0
        c = Completion(request_id=st.request.id,
                       prompt_len=len(st.request.prompt),
                       tokens=list(st.tokens), reason=reason,
                       submitted_at=st.submitted_at,
                       first_token_at=st.first_token_at,
                       finished_at=self.clock(),
                       spec_proposed=st.spec_proposed,
                       spec_accepted=st.spec_accepted,
                       spec_emitted_not_proposed=st.spec_corrected)
        self.completed.append(c)
        done.append(c)
        self._m_ttft.observe(c.ttft_seconds)
        self._m_done.labels(reason=reason).inc()

    def _count_chunk(self) -> None:
        self.prefill_chunks += 1
        self._m_chunks.inc()

    def _drain_requested(self) -> bool:
        return self.stop_check is not None and bool(self.stop_check())

    def _admit(self, done: List[Completion]) -> None:
        free = [s for s in range(self.engine.slots) if s not in self.active]
        while free and self.queue:
            req, submitted_at = self.queue[0]
            blocks, dblocks = None, None
            if self.kv_layout == "paged":
                # admission is by free-BLOCK count, not free-slot count:
                # the head of the queue waits (FIFO, no starvation) until
                # eviction frees enough blocks for its actual need. Spec
                # mode admits by the COMBINED footprint — both pools must
                # cover the request, and a partial grab is rolled back so
                # a draft-pool shortage can't strand target blocks.
                blocks = self.allocator.alloc(self._blocks_needed(req))
                if blocks is None:
                    break
                if self.spec_k:
                    dblocks = self.draft_allocator.alloc(
                        self._blocks_needed(req))
                    if dblocks is None:
                        self.allocator.free(blocks)
                        break
            self.queue.popleft()
            slot = free.pop(0)
            if self.kv_layout == "paged":
                row = np.zeros((self.engine.max_blocks_per_slot,), np.int32)
                row[:len(blocks)] = blocks
                self.block_tables[slot] = row
                spec_kw = {}
                if self.spec_k:
                    drow = np.zeros((self.engine.max_blocks_per_slot,),
                                    np.int32)
                    drow[:len(dblocks)] = dblocks
                    self.draft_block_tables[slot] = drow
                    # only spec-mode engines need (or accept) the draft
                    # row — non-spec engine doubles keep the old signature
                    spec_kw["draft_block_row"] = drow
                first = self.engine.prefill(
                    slot, req.prompt, block_row=row,
                    temperature=req.temperature, top_p=req.top_p,
                    seed=req.seed, stop_check=self._drain_requested,
                    on_chunk=self._count_chunk, **spec_kw)
                if first is None:
                    # Drain fired mid-prompt: the engine finished the
                    # current chunk and stopped. Free the blocks (both
                    # pools in spec mode), put the request back at the head
                    # so it is REPORTED unserved, and close admission —
                    # the drain stays exact.
                    self.allocator.free(blocks)
                    self.block_tables[slot] = 0
                    if self.spec_k:
                        self.draft_allocator.free(dblocks)
                        self.draft_block_tables[slot] = 0
                    self.queue.appendleft((req, submitted_at))
                    self.stop_admission()
                    return
                self._slot_blocks[slot] = blocks
                if self.spec_k:
                    self._slot_draft_blocks[slot] = dblocks
            else:
                first = self.engine.prefill(slot, req.prompt,
                                            temperature=req.temperature,
                                            top_p=req.top_p, seed=req.seed)
            self.active[slot] = _Slot(req, first, submitted_at, self.clock())
            self.max_concurrent = max(self.max_concurrent, len(self.active))
            self._m_tokens.inc()  # the prefill's first token
            # a request can finish straight out of prefill
            if self.eos_token_id is not None and first == self.eos_token_id:
                self._finish(slot, "eos", done)
            elif req.max_new_tokens <= 1:
                self._finish(slot, "length", done)

    def step(self) -> List[Completion]:
        """Admit into free slots, run one decode iteration, evict finished
        requests. Returns the completions produced by this iteration."""
        done: List[Completion] = []
        if self.admission_open:
            self._admit(done)
        self._m_queue.set(len(self.queue))
        self._m_occupancy.set(len(self.active) / max(self.engine.slots, 1))
        if self.kv_layout == "paged":
            self._m_blocks_free.set(self.allocator.free_count)
            util = self.allocator.used_count / max(self.allocator.capacity, 1)
            self._m_block_util.set(util)
            self.max_block_utilization = max(self.max_block_utilization, util)
        if not self.active:
            return done
        slots = self.engine.slots
        tokens = np.zeros((slots,), np.int32)
        active = np.zeros((slots,), bool)
        temperature = np.zeros((slots,), np.float32)
        top_p = np.ones((slots,), np.float32)
        seeds = np.zeros((slots,), np.int32)
        steps = np.zeros((slots,), np.int32)
        for s, st in self.active.items():
            tokens[s] = st.tokens[-1]
            active[s] = True
            temperature[s] = st.request.temperature
            top_p[s] = st.request.top_p
            seeds[s] = st.request.seed
            steps[s] = st.steps
        t0 = self.clock()
        if self.spec_k:
            # Speculative round: lengths[s] is the slot's committed KV
            # count (prompt + emitted − 1 positions hold keys; the latest
            # emitted token is the round's input and is written by the
            # draft/verify programs themselves). steps doubles as the
            # round counter that derives the per-round PRNG streams.
            lengths = np.zeros((slots,), np.int32)
            for s, st in self.active.items():
                lengths[s] = len(st.request.prompt) + len(st.tokens) - 1
            out, acc = self.engine.spec_round(
                tokens, lengths, active, temperature, top_p, seeds, steps,
                block_tables=self.block_tables,
                draft_block_tables=self.draft_block_tables)
        elif self.kv_layout == "paged":
            next_tokens = self.engine.decode_step(
                tokens, active, temperature, top_p, seeds, steps,
                block_tables=self.block_tables)
        else:
            next_tokens = self.engine.decode_step(tokens, active, temperature,
                                                  top_p, seeds, steps)
        step_wall = self.clock() - t0
        self.step_seconds.append(step_wall)
        self._m_decode.observe(step_wall)
        wall = sum(self.step_seconds)
        if wall > 0:
            self._m_tps.set(self._m_tokens.value / wall)
        self.iterations += 1
        if self.spec_k:
            self._bank_spec(out, acc, done)
            return done
        for s in list(self.active):
            st = self.active[s]
            tok = int(next_tokens[s])
            st.tokens.append(tok)
            st.steps += 1
            self._m_tokens.inc()
            if self.eos_token_id is not None and tok == self.eos_token_id:
                self._finish(s, "eos", done)
            elif len(st.tokens) >= st.request.max_new_tokens:
                self._finish(s, "length", done)
        return done

    def _bank_spec(self, out: np.ndarray, acc: np.ndarray,
                   done: List[Completion]) -> None:
        """Bank one verify round's output: the accepted draft prefix plus
        the bonus/corrected token at position acc, truncated by EOS and by
        the request's max_new_tokens budget (truncation discards tokens the
        non-spec path would never have produced, keeping the emitted stream
        identical to sequential decoding)."""
        self.spec_rounds += 1
        n_active = len(self.active)
        self.spec_draft_tokens += self.spec_k * n_active
        self._m_spec_draft.inc(self.spec_k * n_active)
        round_accepted = 0
        for s in list(self.active):
            st = self.active[s]
            a = int(acc[s])
            st.steps += 1
            st.spec_proposed += self.spec_k
            st.spec_accepted += a
            round_accepted += a
            banked = 0
            finished = None
            for i in range(a + 1):
                tok = int(out[s, i])
                st.tokens.append(tok)
                banked += 1
                self._m_tokens.inc()
                if i == a:
                    # position acc is the verifier's own token (bonus on
                    # full accept, correction otherwise) — emitted without
                    # ever having been proposed by the draft.
                    st.spec_corrected += 1
                if self.eos_token_id is not None and tok == self.eos_token_id:
                    finished = "eos"
                    break
                if len(st.tokens) >= st.request.max_new_tokens:
                    finished = "length"
                    break
            self._m_spec_round_tokens.observe(banked)
            if finished:
                self._finish(s, finished, done)
        self.spec_accepted_tokens += round_accepted
        self._m_spec_accepted.inc(round_accepted)
        if self.spec_draft_tokens:
            self._m_spec_rate.set(
                self.spec_accepted_tokens / self.spec_draft_tokens)

    def run(self, stop: Optional[Callable[[], bool]] = None
            ) -> List[Completion]:
        """Drive until idle; ``stop()`` returning True switches to drain
        mode (finish active, leave the queue). Returns all completions."""
        if stop is not None and self.stop_check is None:
            self.stop_check = stop  # also probed between prefill chunks
        while self.pending():
            if stop is not None and self.admission_open and stop():
                self.stop_admission()
            self.step()
        return self.completed

    # --- aggregate metrics -------------------------------------------------

    def metrics(self) -> dict:
        lat = np.asarray(self.step_seconds or [0.0])
        generated = sum(len(c.tokens) for c in self.completed) + sum(
            len(st.tokens) for st in self.active.values())
        wall = float(lat.sum())
        tps = generated / wall if wall > 0 else 0.0
        self._m_tps.set(tps)
        out = {
            "iterations": self.iterations,
            "requests_completed": len(self.completed),
            "tokens_generated": int(generated),
            "max_concurrent": self.max_concurrent,
            "decode_p50_ms": float(np.percentile(lat, 50) * 1e3),
            "decode_p95_ms": float(np.percentile(lat, 95) * 1e3),
            "tokens_per_sec": tps,
            "tokens_per_sec_per_slot": tps / max(self.engine.slots, 1),
            "prefill_chunks": self.prefill_chunks,
        }
        if self.kv_layout == "paged":
            out["kv_blocks_total"] = self.allocator.capacity
            out["kv_blocks_free"] = self.allocator.free_count
            out["kv_block_utilization_peak"] = self.max_block_utilization
        if self.spec_k:
            out["spec_k"] = self.spec_k
            out["spec_rounds"] = self.spec_rounds
            out["spec_draft_tokens"] = self.spec_draft_tokens
            out["spec_accepted_tokens"] = self.spec_accepted_tokens
            out["spec_acceptance_rate"] = (
                self.spec_accepted_tokens / self.spec_draft_tokens
                if self.spec_draft_tokens else 0.0)
            out["draft_kv_blocks_total"] = self.draft_allocator.capacity
            out["draft_kv_blocks_free"] = self.draft_allocator.free_count
        return out
