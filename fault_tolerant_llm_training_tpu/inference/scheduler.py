"""Slot-based continuous batching (Orca-style, static-shape XLA flavor).

One decode program serves all slots every iteration; requests are admitted
into free slots *between* decode iterations (no stop-the-world batch
boundary, the Orca/vLLM scheduling insight) and evicted the moment they hit
EOS or their token budget — a freed slot is re-filled on the very next
iteration. All shapes stay static: "admission" is a prefill into one slot of
the fixed (slots, ...) cache, "eviction" is host bookkeeping plus the mask
bit in the decode step.

The scheduler is also the drain point for the fault-tolerant serving
lifecycle: ``stop_admission()`` (serve.py calls it when a SIGUSR1/SIGTERM
flag fires) freezes the queue while active slots run to completion, so
in-flight requests finish and queued ones are reported unserved — the
serving analogue of the trainer's save-on-signal exit policy.
"""

import dataclasses
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs.registry import MetricRegistry, default_registry


@dataclasses.dataclass
class Request:
    id: str
    prompt: Sequence[int]          # token ids, BOS included by the caller
    max_new_tokens: int = 32
    temperature: float = 0.0       # <= 0 -> greedy
    top_p: float = 1.0
    seed: int = 0


@dataclasses.dataclass
class Completion:
    request_id: str
    prompt_len: int
    tokens: List[int]              # generated ids (EOS included if hit)
    reason: str                    # "eos" | "length"
    submitted_at: float
    first_token_at: float
    finished_at: float

    @property
    def ttft_seconds(self) -> float:
        """Time to first token (queue wait + prefill)."""
        return self.first_token_at - self.submitted_at

    @property
    def latency_seconds(self) -> float:
        return self.finished_at - self.submitted_at

    @property
    def decode_tokens_per_sec(self) -> float:
        decoded = len(self.tokens) - 1  # first token came from prefill
        dt = self.finished_at - self.first_token_at
        return decoded / dt if decoded > 0 and dt > 0 else 0.0


class _Slot:
    def __init__(self, request: Request, first_token: int,
                 submitted_at: float, now: float):
        self.request = request
        self.tokens = [first_token]
        self.steps = 1  # decode-step counter; prefill consumed step 0
        self.submitted_at = submitted_at
        self.first_token_at = now


class Scheduler:
    """Continuous-batching loop over an :class:`~.engine.InferenceEngine`."""

    def __init__(self, engine, eos_token_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricRegistry] = None):
        self.engine = engine
        self.eos_token_id = eos_token_id
        self.clock = clock
        self.queue: deque = deque()        # (Request, submitted_at)
        self.active: Dict[int, _Slot] = {}  # slot index -> state
        self.completed: List[Completion] = []
        self.admission_open = True
        self.iterations = 0
        self.max_concurrent = 0
        self.step_seconds: List[float] = []  # decode-iteration wall times
        # /metrics surface (obs/registry.py): serve.py --metrics-port scrapes
        # these live while the batching loop runs.
        r = registry or default_registry()
        self._m_ttft = r.histogram(
            "ftl_serve_ttft_seconds",
            "Time to first token (queue wait + prefill) per request")
        self._m_decode = r.histogram(
            "ftl_serve_decode_step_seconds",
            "Wall time of one batched decode iteration")
        self._m_tokens = r.counter("ftl_serve_tokens_generated_total",
                                   "Tokens generated across all requests")
        self._m_done = r.counter(
            "ftl_serve_requests_completed_total",
            "Requests completed, by finish reason (eos|length)")
        self._m_occupancy = r.gauge(
            "ftl_serve_slot_occupancy",
            "Active decode slots / total slots (0-1)")
        self._m_queue = r.gauge("ftl_serve_queue_depth",
                                "Requests waiting for a free slot")
        self._m_tps = r.gauge("ftl_serve_tokens_per_sec",
                              "Aggregate decode throughput (running)")

    # --- queue management --------------------------------------------------

    def submit(self, request: Request) -> None:
        if len(request.prompt) + request.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"request {request.id}: prompt {len(request.prompt)} + "
                f"max_new_tokens {request.max_new_tokens} exceeds the "
                f"cache max_len {self.engine.max_len}")
        self.queue.append((request, self.clock()))

    def stop_admission(self) -> None:
        """Drain mode: active slots finish, the queue stays unserved."""
        self.admission_open = False

    def pending(self) -> bool:
        return bool(self.active or (self.queue and self.admission_open))

    def unserved(self) -> List[Request]:
        return [r for r, _ in self.queue]

    # --- one decode iteration ----------------------------------------------

    def _finish(self, slot: int, reason: str, done: List[Completion]) -> None:
        st = self.active.pop(slot)
        c = Completion(request_id=st.request.id,
                       prompt_len=len(st.request.prompt),
                       tokens=list(st.tokens), reason=reason,
                       submitted_at=st.submitted_at,
                       first_token_at=st.first_token_at,
                       finished_at=self.clock())
        self.completed.append(c)
        done.append(c)
        self._m_ttft.observe(c.ttft_seconds)
        self._m_done.labels(reason=reason).inc()

    def _admit(self, done: List[Completion]) -> None:
        free = [s for s in range(self.engine.slots) if s not in self.active]
        while free and self.queue:
            req, submitted_at = self.queue.popleft()
            slot = free.pop(0)
            first = self.engine.prefill(slot, req.prompt,
                                        temperature=req.temperature,
                                        top_p=req.top_p, seed=req.seed)
            self.active[slot] = _Slot(req, first, submitted_at, self.clock())
            self.max_concurrent = max(self.max_concurrent, len(self.active))
            self._m_tokens.inc()  # the prefill's first token
            # a request can finish straight out of prefill
            if self.eos_token_id is not None and first == self.eos_token_id:
                self._finish(slot, "eos", done)
            elif req.max_new_tokens <= 1:
                self._finish(slot, "length", done)

    def step(self) -> List[Completion]:
        """Admit into free slots, run one decode iteration, evict finished
        requests. Returns the completions produced by this iteration."""
        done: List[Completion] = []
        if self.admission_open:
            self._admit(done)
        self._m_queue.set(len(self.queue))
        self._m_occupancy.set(len(self.active) / max(self.engine.slots, 1))
        if not self.active:
            return done
        slots = self.engine.slots
        tokens = np.zeros((slots,), np.int32)
        active = np.zeros((slots,), bool)
        temperature = np.zeros((slots,), np.float32)
        top_p = np.ones((slots,), np.float32)
        seeds = np.zeros((slots,), np.int32)
        steps = np.zeros((slots,), np.int32)
        for s, st in self.active.items():
            tokens[s] = st.tokens[-1]
            active[s] = True
            temperature[s] = st.request.temperature
            top_p[s] = st.request.top_p
            seeds[s] = st.request.seed
            steps[s] = st.steps
        t0 = self.clock()
        next_tokens = self.engine.decode_step(tokens, active, temperature,
                                              top_p, seeds, steps)
        step_wall = self.clock() - t0
        self.step_seconds.append(step_wall)
        self._m_decode.observe(step_wall)
        wall = sum(self.step_seconds)
        if wall > 0:
            self._m_tps.set(self._m_tokens.value / wall)
        self.iterations += 1
        for s in list(self.active):
            st = self.active[s]
            tok = int(next_tokens[s])
            st.tokens.append(tok)
            st.steps += 1
            self._m_tokens.inc()
            if self.eos_token_id is not None and tok == self.eos_token_id:
                self._finish(s, "eos", done)
            elif len(st.tokens) >= st.request.max_new_tokens:
                self._finish(s, "length", done)
        return done

    def run(self, stop: Optional[Callable[[], bool]] = None
            ) -> List[Completion]:
        """Drive until idle; ``stop()`` returning True switches to drain
        mode (finish active, leave the queue). Returns all completions."""
        while self.pending():
            if stop is not None and self.admission_open and stop():
                self.stop_admission()
            self.step()
        return self.completed

    # --- aggregate metrics -------------------------------------------------

    def metrics(self) -> dict:
        lat = np.asarray(self.step_seconds or [0.0])
        generated = sum(len(c.tokens) for c in self.completed) + sum(
            len(st.tokens) for st in self.active.values())
        wall = float(lat.sum())
        tps = generated / wall if wall > 0 else 0.0
        self._m_tps.set(tps)
        return {
            "iterations": self.iterations,
            "requests_completed": len(self.completed),
            "tokens_generated": int(generated),
            "max_concurrent": self.max_concurrent,
            "decode_p50_ms": float(np.percentile(lat, 50) * 1e3),
            "decode_p95_ms": float(np.percentile(lat, 95) * 1e3),
            "tokens_per_sec": tps,
            "tokens_per_sec_per_slot": tps / max(self.engine.slots, 1),
        }
