"""Slot-based continuous batching (Orca-style, static-shape XLA flavor).

One decode program serves all slots every iteration; requests are admitted
into free slots *between* decode iterations (no stop-the-world batch
boundary, the Orca/vLLM scheduling insight) and evicted the moment they hit
EOS or their token budget — a freed slot is re-filled on the very next
iteration. All shapes stay static: "admission" is a prefill into one slot of
the fixed (slots, ...) cache, "eviction" is host bookkeeping plus the mask
bit in the decode step.

Under the engine's paged KV layout (the default) the scheduler also owns
the :class:`BlockAllocator`: admission is gated by FREE BLOCK COUNT —
``ceil((prompt + max_new_tokens) / block_size)`` blocks per request — not
just by a free slot, so a long-context cache no longer reserves ``max_len``
per slot and far more requests fit the same HBM; eviction frees the blocks
for the next admission. A request whose blocks aren't available yet simply
waits at the head of the queue (FIFO, no starvation) — exhaustion queues,
it never crashes.

With the engine's prefix cache enabled (``enable_prefix_cache``, the paged
default) admission first walks the content-addressed radix tree
(inference/prefix_cache.py): blocks covering a cached prompt prefix are
attached to the slot's table at ZERO allocation cost (a refcount each) and
prefill resumes at the first divergent block through the existing chunked
path — a fully-shared prompt skips all but its last position. A full-prompt
hit still needs that last position's logits, so the final shared block is
COPY-ON-WRITE duplicated (engine.cow_copy) into a private block before
prefill resumes inside it; shared blocks are never written. Under pool
pressure, admission evicts LRU cached prefixes no live slot references
before making the head of the queue wait. The DRAFT pool (speculative
mode) runs a MIRROR of the same scheme: a second radix tree over the
draft allocator, fed the same insertions at the same block boundaries, so
a shared system prompt skips the draft prefill compute too — with tree
speculation refeeding the draft every round, draft prefill is no longer a
negligible fraction of admission cost. The mirror is strictly cheaper
than the target's cache in one way: a FULL-prompt draft hit needs no
copy-on-write resume at all (the draft phase samples nothing — covering
every prompt position means there is nothing left to compute), so the
draft phase is skipped outright. Admission still gates on the COMBINED
footprint, and a shortage on either side rolls back BOTH pools' acquired
references; decode/spec rounds only ever write at positions >=
prompt_len, which live in the slot's private blocks, so sharing never
constrains them. Cache-hit spec streams are bit-identical to cache-off
(shared draft blocks hold the bytes a zero-offset draft prefill would
have written — tests/test_spec_decode.py asserts it).

With ``prefill_batch > 1`` (engine built to match) admission switches to
the PACKED prefill lane: allocation keeps the exact sequential front-half
(prefix acquire-first, COW, rollback), but prompts then stream through
per-step packed ROUNDS — up to ``prefill_batch`` pending rows' next
chunks, grouped on the head row's best-fit bucket, in ONE (P, bucket)
dispatch — interleaved with the decode rounds instead of draining the
queue one prompt at a time. Prefill work between two decode rounds is
bounded by P * bucket tokens (Sarathi-style stall-free batching), and
per-row chunk shapes match the sequential loop exactly, so packed streams
stay bit-identical to sequential prefill on the gather impl.

When the engine was built with a draft model (``spec_k > 0``) the
scheduler runs SPECULATIVE rounds instead of single-token decode
iterations: each round emits 1..k+1 tokens per slot (engine.py
``spec_round``). The draft model has its own block pool, so the scheduler
owns a SECOND :class:`BlockAllocator` and block table; admission is gated
by the COMBINED draft+target footprint (both pools must cover the
request, or it waits at the head of the queue), and eviction/drain frees
both pools together. Acceptance statistics are exported per round
(``ftl_spec_*`` metrics) and per request (Completion spec fields).

With a TREE shape on top (``engine.spec_tree``) every speculative round
is a tree round (engine.py ``spec_tree_round``): the scheduler feeds the
round the tokens the PREVIOUS round banked for the slot (the refeed
window — a committed sibling is a token the draft chain never fed), picks
the round's shape from the adaptive controller's budget via
``TreeShape.shrink_to`` when one is installed, and attributes acceptance
per node row — ``spec_tree_nodes_total``, the ``spec_accepted_path_len``
histogram and the branch-utilization gauge (accepted tokens taken OFF the
primary chain) come from the returned path. Banking keeps the linear
rounds' truncation contract, so EOS/budget eviction and the drain
lifecycle are unchanged; a mid-stream drain frees branch scratch with the
slot's ordinary allocation (tree rows live inside it), leaving the leak
guard clean.

The scheduler is also the drain point for the fault-tolerant serving
lifecycle: ``stop_admission()`` (serve.py calls it when a SIGUSR1/SIGTERM
flag fires) freezes the queue while active slots run to completion, so
in-flight requests finish and queued ones are reported unserved — the
serving analogue of the trainer's save-on-signal exit policy. Chunked
prefills consult ``stop_check`` between chunks, so a signal that lands
mid-prompt finishes the current chunk only, frees the request's blocks and
reports it unserved — the drain stays exact even for long prompts.

Disaggregated roles (DistServe/Splitwise): ``role="prefill"`` keeps both
prefill lanes but exports every committed chunk's full blocks as an
incremental checksummed shipment (kv_cache.export_blocks) and finishes the
request with reason ``"prefill"`` — its decode belongs to a decode-role
peer. ``role="decode"`` admits such requests by importing the shipments
(CRC + journal agreement verified BEFORE any device write, prefix-cache
deduped) and resumes decode bit-exactly at the committed offset; any
verification failure degrades to the committed-prefix replay, which the
decode engine can always run because its prefill path is intact — that IS
the fallback ladder. ``role="both"`` (default) is the colocated engine.
"""

import dataclasses
import logging
import os
import shutil
import tempfile
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import events, reqtrace
from ..obs.registry import (
    SPEC_TOKEN_BUCKETS,
    MetricRegistry,
    default_registry,
)
from ..utils.logging import (
    AUDIT_ADAPTER_FMT,
    AUDIT_DISAGG_SHIP_FMT,
    AUDIT_HANDOFF_FMT,
    AUDIT_KV_LEAK_FMT,
    AUDIT_KV_STORE_FMT,
    AUDIT_KV_TIER_FMT,
    AUDIT_KV_XPORT_FMT,
)
from .kv_cache import (
    BLOCK_MANIFEST_NAME,
    KVBlockIntegrityError,
    artifact_bytes,
    block_bytes,
    export_blocks,
    verify_block_artifact,
)
from .prefix_cache import PrefixCache, chain_hashes
from .transport import FsTransport

logger = logging.getLogger()


class BlockAllocator:
    """Host-side REFCOUNTED free list over the paged cache's block pool.

    Block 0 is the reserved null/scratch block (inference/kv_cache.py):
    free block-table entries point at it and masked writes divert into it,
    so it is never handed out. Blocks are born at refcount 1 (``alloc``);
    prefix sharing takes extra references (``incref``: the cache's own hold
    on an inserted block, and each additional slot admitted onto a cached
    prefix — inference/prefix_cache.py documents the full ownership
    protocol). ``free()`` DECREMENTS; a block returns to the free list only
    when its last holder drops it. Releasing a block that has no live
    reference still raises — an allocator bug corrupting two requests'
    caches should fail loudly, not silently cross-wire their KV.
    """

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # LIFO: reuse warm
        self._ref: Dict[int, int] = {}  # block -> live reference count

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1  # block 0 reserved

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._ref)

    @property
    def shared_count(self) -> int:
        """Blocks with more than one live reference (prefix sharing)."""
        return sum(1 for c in self._ref.values() if c > 1)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks at refcount 1, or None if fewer than n are free
        (caller queues or evicts cached prefixes)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._ref[b] = 1
        return blocks

    def incref(self, blocks: Sequence[int]) -> None:
        """One extra reference per block (must be live)."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(f"incref of unallocated block {b}")
            self._ref[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; the last drop frees the block."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)


@dataclasses.dataclass
class Request:
    id: str
    prompt: Sequence[int]          # token ids, BOS included by the caller
    max_new_tokens: int = 32
    temperature: float = 0.0       # <= 0 -> greedy
    top_p: float = 1.0
    seed: int = 0
    # Migrated-replay prefix (fleet journal): tokens a previous owner
    # already committed for this request. When non-empty, admission
    # prefills prompt + committed[:-1] (re-deriving the KV the dead host
    # held — a prefix-cache hit makes this cheap), banks the committed
    # list as already-generated output, and resumes decode at step
    # len(committed) so the fold_in(seed, step) PRNG continues the SAME
    # stream the original host was producing. committed counts toward
    # max_new_tokens; an empty tuple is a normal fresh request.
    committed: Sequence[int] = ()
    # Span-trail key (obs/reqtrace.py), minted at intake and carried
    # through the journal so a migrated request's trace joins across
    # hosts. Empty string = tracing off for this request.
    trace_id: str = ""
    # Tenant LoRA adapter this request decodes under (adapters.py).
    # "" = the null adapter: base-model-only, bit-identical to an
    # engine without adapter serving. A registered-but-unresident name
    # queues the request behind a verified page-in at admission.
    adapter: str = ""


@dataclasses.dataclass
class Completion:
    request_id: str
    prompt_len: int
    tokens: List[int]              # generated ids (EOS included if hit)
    reason: str                    # "eos" | "length"
    submitted_at: float
    first_token_at: float
    finished_at: float
    # Speculative-decoding accounting (zero in non-spec mode): draft tokens
    # proposed for this request, proposals the verify pass accepted, and
    # tokens EMITTED-NOT-PROPOSED — the verify pass's bonus/corrected
    # tokens, i.e. output the draft never suggested (the drain audit logs
    # these per request so an operator can see how much of a stream the
    # draft actually produced).
    spec_proposed: int = 0
    spec_accepted: int = 0
    spec_emitted_not_proposed: int = 0
    trace_id: str = ""

    @property
    def ttft_seconds(self) -> float:
        """Time to first token (queue wait + prefill)."""
        return self.first_token_at - self.submitted_at

    @property
    def latency_seconds(self) -> float:
        return self.finished_at - self.submitted_at

    @property
    def tpot_seconds(self) -> float:
        """Time per output token AFTER the first (the first token is
        prefill's and is priced by TTFT — the DistServe/Splitwise
        split). 0.0 for single-token requests."""
        decoded = len(self.tokens) - 1
        dt = self.finished_at - self.first_token_at
        return dt / decoded if decoded > 0 and dt > 0 else 0.0

    @property
    def decode_tokens_per_sec(self) -> float:
        decoded = len(self.tokens) - 1  # first token came from prefill
        dt = self.finished_at - self.first_token_at
        return decoded / dt if decoded > 0 and dt > 0 else 0.0


@dataclasses.dataclass
class _PendingPrefill:
    """One admitted-but-not-yet-prefilled request in the PACKED prefill
    lane (``prefill_batch > 1``): its slot and blocks are already owned
    (allocation, prefix-cache references and the full-hit COW all happened
    at admission, exactly as in the sequential lane), but the prompt
    streams chunk-by-chunk through ``Scheduler._prefill_round`` — packed
    with other rows into one dispatch per round — instead of draining
    in one blocking ``engine.prefill`` call."""
    request: Request
    submitted_at: float
    slot: int
    row: np.ndarray         # full padded block-table row
    blocks: List[int]       # every block to free exactly once on abort
    start_pos: int          # prefix-resume offset (0 = no cache hit)
    pos: int                # next absolute position to prefill
    eff: Sequence[int]      # effective prefill prompt (replay appends the
                            # committed prefix; == request.prompt otherwise)


class _Slot:
    def __init__(self, request: Request, first_token: int,
                 submitted_at: float, now: float):
        self.request = request
        committed = list(getattr(request, "committed", ()) or ())
        if committed:
            # Migrated replay: the committed prefix is already-generated
            # output (banked here, not re-emitted), and the replay prefill's
            # sampled token was discarded by the caller — the next decode
            # feeds committed[-1] and folds (seed, len(committed)), the
            # exact step the previous owner would have run next.
            self.tokens = committed
            self.steps = len(committed)
        else:
            self.tokens = [first_token]
            self.steps = 1  # decode-step counter; prefill consumed step 0
        self.submitted_at = submitted_at
        self.first_token_at = now
        # tree-spec refeed window: the tokens banked by the LAST round
        # (prefill counts as round 0 with just the first token) — the
        # next tree round rewrites their draft KV before proposing
        self.emitted = [self.tokens[-1]]
        # spec-mode per-request accounting (see Completion)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_corrected = 0


@dataclasses.dataclass
class _SpilledRequest:
    """A preempted request parked in the host spill tier: its PRIVATE
    blocks live as a checksummed artifact on disk, its shared prefix-cache
    blocks were released (the cache's own reference keeps them warm), and
    everything needed to resume the stream bit-exactly — tokens, step
    index, refeed window, timestamps — is preserved host-side. fold_in
    (seed, step) is stateless in the step index, so the restored slot's
    next decode folds exactly the key the preempted slot would have."""

    request: Request
    submitted_at: float
    first_token_at: float
    tokens: List[int]
    steps: int
    emitted: List[int]
    shared_tokens: List[int]     # token ids covered by released shared blocks
    private_positions: List[int]  # block-table positions of exported blocks
    blocks_total: int            # full row size to re-allocate on restore
    artifact_dir: str
    bytes: int


class Scheduler:
    """Continuous-batching loop over an :class:`~.engine.InferenceEngine`."""

    def __init__(self, engine, eos_token_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricRegistry] = None,
                 stop_check: Optional[Callable[[], bool]] = None,
                 adaptive_k=None, decode_burst: int = 1,
                 prefill_batch: int = 1, adaptive_burst: bool = False,
                 enable_spill: bool = False,
                 spill_dir: Optional[str] = None,
                 on_spill: Optional[Callable[[str, int], None]] = None,
                 role: str = "both",
                 ship_dir: Optional[str] = None,
                 on_ship: Optional[Callable] = None,
                 on_prefill_chunk: Optional[Callable[[int], None]] = None,
                 kv_store=None,
                 on_store_put: Optional[Callable[[str, int], None]] = None,
                 transport=None,
                 pacing: Optional[Callable[[], Optional[int]]] = None,
                 kv_store_max_bytes: int = 0):
        self.engine = engine
        self.eos_token_id = eos_token_id
        self.clock = clock
        self.queue: deque = deque()        # (Request, submitted_at)
        self.active: Dict[int, _Slot] = {}  # slot index -> state
        self.completed: List[Completion] = []
        self.admission_open = True
        self.iterations = 0
        self.max_concurrent = 0
        self.step_seconds: List[float] = []  # decode-iteration wall times
        # Drain probe consulted BETWEEN prefill chunks (serve.py passes the
        # signal flag) so a mid-prompt SIGUSR1/SIGTERM aborts cleanly at a
        # chunk boundary; run(stop=...) installs its callable here too.
        self.stop_check = stop_check
        self.kv_layout = getattr(engine, "kv_layout", "ring")
        self.prefill_chunks = 0
        self.max_block_utilization = 0.0
        if self.kv_layout == "paged":
            self.allocator = BlockAllocator(engine.num_blocks)
            self.block_tables = np.zeros(
                (engine.slots, engine.max_blocks_per_slot), np.int32)
            self._slot_blocks: Dict[int, List[int]] = {}
        # Spill tier (module docstring): on pool exhaustion, preempt the
        # coldest active request into a host-side checksummed artifact
        # instead of making the head of the queue wait. A plain directory
        # is the tier in both configs — ``spill_dir`` names a persistent
        # location, ``enable_spill`` alone uses a process-private tmpdir
        # (the "host RAM" tier: same code path, kernel page cache holds
        # the bytes).
        self.enable_spill = bool(enable_spill or spill_dir)
        self._spill_dir_arg = spill_dir
        self._spill_root: Optional[str] = None
        self._spilled: Dict[str, _SpilledRequest] = {}
        self._spill_order: List[str] = []      # FIFO restore order
        self._on_spill = on_spill
        self.spill_exports = 0                 # artifact ordinal (chaos key)
        self.spill_restores = 0
        self.spill_rejects = 0
        # Handoff import-admission (fleet.py): request id -> verified
        # artifact dir; _admit imports the shipped blocks instead of
        # replay-prefilling, falling back to replay on any failure.
        self._handoff_artifacts: Dict[str, str] = {}
        self.handoff_imports = 0
        self.handoff_rejects = 0
        # Disaggregated prefill/decode (DistServe/Splitwise split over the
        # checksummed artifact path). role="prefill": admissions run the
        # ordinary prefill lanes but every committed chunk is EXPORTED as
        # an incremental block shipment (``on_ship`` fires per artifact —
        # fleet.py journals it) and the request finishes with reason
        # "prefill" instead of entering decode. role="decode": submit()
        # accepts the journaled shipment list and admission IMPORTS the
        # shipped blocks — prefix-cache-deduped — instead of replay-
        # prefilling; any verification failure degrades to the bit-exact
        # committed-prefix replay (the full prefill path stays available,
        # which IS the fallback ladder). role="both" is the colocated
        # engine, unchanged.
        self.role = str(role)
        if self.role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r} "
                             f"(want both|prefill|decode)")
        if self.role != "both" and self.kv_layout != "paged":
            raise ValueError("prefill/decode roles require the paged KV "
                             "layout (shipments are block artifacts)")
        if self.role != "both" and int(getattr(engine, "spec_k", 0) or 0):
            raise ValueError("prefill/decode roles do not support "
                             "speculative decoding (the draft pool's "
                             "blocks are not shipped)")
        self._ship_dir_arg = ship_dir
        self._ship_root_path: Optional[str] = None
        self._on_ship = on_ship
        self._on_prefill_chunk = on_prefill_chunk
        # request id -> {"shipped": blocks exported, "seq": next artifact}
        self._ship_state: Dict[str, dict] = {}
        self._ship_req_gen: Dict[str, int] = {}  # assignment generation
        # request id -> (journaled shipment records, generation) — the
        # decode-side admission input (fleet.py feeds it from the journal's
        # "decode" record)
        self._shipments: Dict[str, tuple] = {}
        self.ship_exports = 0                  # artifact ordinal (chaos key)
        self.ship_imports = 0
        self.ship_rejects = 0
        # Fleet-global KV store (inference/kvstore.py BlockStore): after a
        # prefill commits, the prompt's full prefix blocks PUBLISH as a
        # content-addressed train; at admission, a store train deeper than
        # the local prefix-cache hit is FETCHED through the batched
        # verify-before-first-device-write import. Any CRC reject or miss
        # degrades to local chunked prefill — corruption costs recompute,
        # never correctness. ``on_store_put`` is the chaos hook
        # (store_corrupt), threaded into BlockStore.publish.
        self.kv_store = kv_store
        self._on_store_put = on_store_put
        self.store_publishes = 0
        self.store_fetches = 0
        self.store_fetch_blocks = 0
        self.store_rejects = 0
        # Pluggable KV transport (inference/transport.py): every block
        # train this scheduler exports (shipments, store publishes) or
        # imports (shipment admission, store fetches) moves through ONE
        # transport object. FsTransport (default) is the existing
        # filesystem artifact path verbatim; MemTransport adds the
        # same-pod device-push lane with metadata-only verification and
        # the mem -> fs -> replay fallback ladder.
        self.transport = transport if transport is not None else FsTransport()
        # Prefill-admission pacing (ROADMAP item 2's control plane): a
        # prefill-role engine consults ``pacing()`` — the decode fleet's
        # free-block count, derived from the heartbeat leases — before
        # admitting a new prompt, and defers admission (queue intact)
        # while the decode pool cannot land the blocks the prompt's
        # shipments will carry. None (or a pacing() of None — no decode
        # peers visible yet) never stalls: the ladder degrades to the
        # unpaced behavior rather than deadlocking a booting fleet.
        self.pacing = pacing
        self.prefill_paced = 0
        self._paced_logged: set = set()
        # Publish backpressure (the sweeper daemon's other half): skip
        # store publishes while the folded resident bytes exceed the
        # byte budget, so publishers stop racing the LRU sweep. 0 = no
        # budget (publish always).
        self.kv_store_max_bytes = int(kv_store_max_bytes or 0)
        self.store_publish_skipped = 0
        self.store_partial_hits = 0
        self.lane_fallbacks = 0
        self.mem_lane_imports = 0
        if self.kv_store is not None and self.kv_layout != "paged":
            raise ValueError("the fleet KV store requires the paged KV "
                             "layout (trains are block artifacts)")
        if self.enable_spill and self.kv_layout != "paged":
            raise ValueError("the spill tier requires the paged KV layout")
        if self.enable_spill and int(getattr(engine, "spec_k", 0) or 0):
            raise ValueError("the spill tier does not support speculative "
                             "decoding (the draft pool's blocks are "
                             "derivable scratch, not committed state)")
        # Speculative mode: the draft model's pool gets its own allocator
        # and block table; admission requires BOTH footprints (below).
        self.spec_k = int(getattr(engine, "spec_k", 0) or 0)
        # Multi-token fused decode (engine.decode_burst): each step() runs
        # ONE n-token burst program — 1 dispatch + 1 host sync for n
        # tokens. Admission, EOS eviction, and the serve loop's
        # stop/drain probes all happen BETWEEN bursts (a burst is inside
        # one step() call, and the drain contract only ever promised
        # iteration-boundary checks), so the signal-drain audit sequence
        # is unchanged — a drain just lands at the next burst boundary,
        # at most n-1 tokens later than per-token decode would.
        self.decode_burst = int(decode_burst)
        if self.decode_burst < 1:
            raise ValueError(f"decode_burst {decode_burst} must be >= 1")
        if self.decode_burst > 1:
            if self.kv_layout != "paged":
                raise ValueError("decode_burst > 1 requires the paged KV "
                                 "layout")
            if self.spec_k:
                raise ValueError(
                    "decode_burst > 1 and speculative decoding are "
                    "mutually exclusive: a spec round already amortizes "
                    "dispatches over k+1 tokens")
            if not hasattr(engine, "decode_burst"):
                raise ValueError("engine does not implement decode_burst")
        # Burst-aware adaptive n: under queue / pending-prefill pressure
        # each step() scales the burst width DOWN (halving per waiting
        # unit, floor 1) before the existing per-slot budget clamp, so a
        # long burst never starves admission while the queue piles up —
        # idle-queue steps still run the full configured width. The
        # engine's compile-on-first-use burst ladder absorbs the handful
        # of distinct widths this produces.
        self.adaptive_burst = bool(adaptive_burst)
        if self.adaptive_burst and self.decode_burst < 2:
            raise ValueError("adaptive_burst requires decode_burst > 1 "
                             "(there is no width to scale down)")
        # Packed multi-request prefill (engine.prefill_packed): admission
        # allocates slots/blocks as usual but ENQUEUES the prompt instead
        # of streaming it to completion; each step() then dispatches ONE
        # packed round — up to prefill_batch pending rows' next chunks in
        # one (P, bucket) program — before the decode round, so prefill
        # work between decode rounds is bounded by P * bucket tokens
        # (Sarathi-style stall-free mixed batching) instead of a whole
        # prompt per admission.
        self.prefill_batch = int(prefill_batch)
        self._pending_prefill: deque = deque()  # _PendingPrefill rows
        if self.prefill_batch < 1:
            raise ValueError(f"prefill_batch {prefill_batch} must be >= 1")
        if self.prefill_batch > 1:
            if self.kv_layout != "paged":
                raise ValueError("prefill_batch > 1 requires the paged KV "
                                 "layout")
            if self.spec_k:
                raise ValueError(
                    "prefill_batch > 1 and speculative decoding are "
                    "mutually exclusive (the draft prefill lifecycle is "
                    "sequential; engine.py enforces the same)")
            if not hasattr(engine, "prefill_packed"):
                raise ValueError("engine does not implement prefill_packed")
            if getattr(engine, "prefill_batch", 1) != self.prefill_batch:
                raise ValueError(
                    f"scheduler prefill_batch {self.prefill_batch} != "
                    f"engine prefill_batch "
                    f"{getattr(engine, 'prefill_batch', 1)}: the packed "
                    f"programs were compiled at the engine's width")
        self.prefill_packed_rounds = 0
        self.prefill_packed_rows = 0
        self.prefill_inplace_chunks = 0
        self.prefill_gather_chunks = 0
        # Multi-tenant LoRA adapter serving (adapters.py): engines built
        # with adapter_rank > 0 carry an AdapterManager; the scheduler
        # keeps one adapter page row + scale per slot (the decode
        # dispatch's gather operands) and accounts the COMBINED
        # KV+adapter footprint at admission — a request naming an
        # unresident adapter waits at the head of the queue until a
        # verified page-in lands it (never crashes the loop).
        self.adapters = getattr(engine, "adapters", None)
        if self.adapters is not None:
            per = self.adapters.layout.pages_per_adapter
            self._adapter_rows = np.zeros((engine.slots, per), np.int32)
            self._adapter_scales = np.zeros((engine.slots,), np.float32)
            self._slot_adapter: Dict[int, str] = {}
            self.adapter_waits = 0
            self.adapter_rejects = 0
            self._adapter_pageins_seen = 0
            self._adapter_evictions_seen = 0
        # Dispatch/sync accounting (the fused-decode win in receipts):
        # how many device programs were launched and how many host syncs
        # were paid for the decode tokens generated.
        self.decode_dispatches = 0
        self.decode_host_syncs = 0
        self.decode_tokens = 0
        # Optional sampler.AdaptiveK controller: when present, every spec
        # round runs at its chosen width (min per-request target) instead
        # of the engine's fixed spec_k — serve.py --adaptive-spec-k.
        self.adaptive_k = adaptive_k if self.spec_k else None
        if self.spec_k:
            self.draft_allocator = BlockAllocator(engine.draft_num_blocks)
            self.draft_block_tables = np.zeros(
                (engine.slots, engine.max_blocks_per_slot), np.int32)
            self._slot_draft_blocks: Dict[int, List[int]] = {}
            self.spec_rounds = 0
            self.spec_draft_tokens = 0
            self.spec_accepted_tokens = 0
        # Tree speculation (engine.spec_tree): every spec round becomes a
        # tree round; acceptance is attributed per node row (module
        # docstring) so branch utilization is observable.
        self.spec_tree = (getattr(engine, "spec_tree", None)
                          if self.spec_k else None)
        if self.spec_tree is not None:
            self.spec_tree_rounds = 0
            self.spec_tree_nodes = 0
            self.spec_tree_accepted = 0
            self.spec_tree_off_primary = 0
        # /metrics surface (obs/registry.py): serve.py --metrics-port scrapes
        # these live while the batching loop runs.
        r = registry or default_registry()
        self._m_ttft = r.histogram(
            "ftl_serve_ttft_seconds",
            "Time to first token (queue wait + prefill) per request")
        self._m_tpot = r.histogram(
            "ftl_serve_tpot_seconds",
            "Time per output token after the first (decode-loop latency "
            "per token, per request)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
        self._m_decode = r.histogram(
            "ftl_serve_decode_step_seconds",
            "Wall time of one batched decode iteration")
        self._m_tokens = r.counter("ftl_serve_tokens_generated_total",
                                   "Tokens generated across all requests")
        self._m_done = r.counter(
            "ftl_serve_requests_completed_total",
            "Requests completed, by finish reason (eos|length)")
        self._m_occupancy = r.gauge(
            "ftl_serve_slot_occupancy",
            "Active decode slots / total slots (0-1)")
        self._m_queue = r.gauge("ftl_serve_queue_depth",
                                "Requests waiting for a free slot")
        self._m_tps = r.gauge("ftl_serve_tokens_per_sec",
                              "Aggregate decode throughput (running)")
        self._m_blocks_free = r.gauge(
            "ftl_serve_kv_blocks_free",
            "Free KV cache blocks in the paged pool (block 0 excluded)")
        self._m_blocks_total = r.gauge(
            "ftl_serve_kv_blocks_total",
            "Usable KV cache blocks in the paged pool (capacity; the "
            "federation aggregator rolls free/total up per engine role)")
        self._m_block_util = r.gauge(
            "ftl_serve_kv_block_utilization",
            "Allocated / usable KV cache blocks (0-1)")
        self._m_chunks = r.counter(
            "ftl_serve_prefill_chunks_total",
            "Prefill chunks executed (chunked long-prompt prefill)")
        self._m_prefill_batch = r.histogram(
            "prefill_batch_size",
            "Requests packed per packed-prefill dispatch (packed lane "
            "only; capacity is --prefill-batch)",
            buckets=SPEC_TOKEN_BUCKETS)
        self._m_prefill_inplace = r.counter(
            "prefill_inplace_total",
            "Prefill chunks dispatched through the in-place Pallas paged "
            "kernel (--paged-kernel pallas, S>1 chunk grid)")
        self._m_prefill_gather = r.counter(
            "prefill_gather_total",
            "Prefill chunks dispatched through the gather-then-ring "
            "reference kernel (--paged-kernel gather)")
        self._m_spec_draft = r.counter(
            "ftl_spec_draft_tokens_total",
            "Draft-model tokens proposed (speculative decoding)")
        self._m_spec_accepted = r.counter(
            "ftl_spec_accepted_tokens_total",
            "Draft proposals accepted by the target verify pass")
        self._m_spec_rate = r.gauge(
            "ftl_spec_acceptance_rate",
            "Running accepted/proposed draft-token ratio (0-1)")
        self._m_spec_round_k = r.gauge(
            "ftl_spec_round_k",
            "Draft proposals per speculative round (adaptive-k controller "
            "output; fixed spec_k without one)")
        self._m_spec_round_tokens = r.histogram(
            "ftl_spec_tokens_per_round",
            "Tokens banked per verify round (accepted prefix + bonus, "
            "after EOS/budget truncation)",
            buckets=SPEC_TOKEN_BUCKETS)
        self._m_tree_nodes = r.counter(
            "spec_tree_nodes_total",
            "Tree nodes scored by tree-verify dispatches (root included; "
            "active slots x shape size per round)")
        self._m_tree_path_len = r.histogram(
            "spec_accepted_path_len",
            "Accepted path length per slot per tree-verify round "
            "(0..depth, before EOS/budget truncation)",
            buckets=SPEC_TOKEN_BUCKETS)
        self._m_tree_branch_util = r.gauge(
            "spec_tree_branch_utilization",
            "Accepted tokens taken OFF the primary draft chain / accepted "
            "tokens (0-1, running; 0 under the exact verify mode)")
        self._m_dispatches = r.counter(
            "decode_dispatches_total",
            "Device programs launched for decode (burst counts 1 per "
            "burst; a spec round counts its draft + verify pair)")
        self._m_host_syncs = r.counter(
            "decode_host_syncs_total",
            "Host round-trips paid for decode results (one per "
            "device->host token/logit transfer)")
        self._m_burst_tokens = r.histogram(
            "decode_burst_tokens",
            "Tokens banked per active slot per decode dispatch (after "
            "EOS/budget truncation; 1 for per-token decode)",
            buckets=SPEC_TOKEN_BUCKETS)
        self._m_prefix_hit_rate = r.gauge(
            "kv_prefix_hit_rate",
            "Prompt tokens served from the prefix cache / prompt tokens "
            "admitted (0-1, running)")
        self._m_blocks_shared = r.gauge(
            "kv_blocks_shared",
            "KV pool blocks with more than one live reference "
            "(prefix sharing)")
        self._m_prefix_evictions = r.counter(
            "prefix_evictions_total",
            "Cached prefix blocks evicted under pool pressure (LRU, "
            "refcount-0 only)")
        self._m_blocks_spilled = r.gauge(
            "kv_blocks_spilled",
            "KV blocks currently parked in the host spill tier "
            "(checksummed artifacts; restored on demand)")
        self._m_spill_bytes = r.gauge(
            "kv_spill_bytes",
            "Payload bytes currently held by the host spill tier")
        self._m_spill_restores = r.counter(
            "kv_spill_restore_total",
            "Spilled requests restored to device blocks (CRC-verified "
            "import + prefix-cache re-acquire)")
        self._m_handoff_shipped = r.counter(
            "handoff_blocks_shipped_total",
            "KV blocks moved through checksummed handoff artifacts "
            "(exported at drain or imported on a survivor)")
        self._m_handoff_rejected = r.counter(
            "handoff_crc_rejected_total",
            "Handoff artifacts rejected by CRC/size/geometry verification "
            "(the request falls back to committed-prefix replay)")
        self._m_ship_exports = r.counter(
            "disagg_shipments_exported_total",
            "Incremental KV block shipments exported by a prefill-role "
            "engine (one checksummed artifact per committed chunk group)")
        self._m_ship_imports = r.counter(
            "disagg_shipments_imported_total",
            "Block shipments CRC-verified and imported by a decode-role "
            "engine (prefix-cache-deduped shipments count as imported)")
        self._m_ship_rejected = r.counter(
            "disagg_shipments_rejected_total",
            "Shipment admissions rejected by CRC/metadata/coverage "
            "verification (the request falls back to committed-prefix "
            "replay on the decode engine)")
        self._m_store_hits = r.counter(
            "kv_store_hits_total",
            "Admissions that landed a fleet-store prefix train instead of "
            "prefilling it (verified cross-host fetches)")
        self._m_store_fetch_blocks = r.counter(
            "kv_store_fetch_blocks_total",
            "KV blocks imported from fleet-store trains (CRC-verified "
            "before the first device write)")
        self._m_store_rejected = r.counter(
            "kv_store_crc_rejected_total",
            "Fleet-store fetches rejected by CRC/metadata verification "
            "(the request falls back to local chunked prefill)")
        self._m_store_bytes = r.gauge(
            "kv_store_bytes",
            "Resident payload bytes in the fleet-global KV store "
            "(journal-folded, as of this host's last publish/fetch)")
        self._m_store_hit_depth = r.histogram(
            "kv_store_hit_depth",
            "Blocks imported per fleet-store hit (train depth at the "
            "admitting host)",
            buckets=SPEC_TOKEN_BUCKETS)
        self._m_store_publishes = r.counter(
            "kv_store_publish_total",
            "Committed prefix trains published to the fleet store "
            "(deduped re-publishes of an identical chain hash excluded)")
        self._m_xport_bytes = r.counter(
            "kv_transport_bytes_total",
            "KV block-train payload bytes moved through the pluggable "
            "transport, by lane: fs counts artifact writes and "
            "CRC-verified imports, mem counts device-to-device pushes "
            "and metadata-verified landings")
        self._m_store_partial = r.counter(
            "kv_store_partial_hits_total",
            "Fleet-store fetches that landed a PREFIX of a longer "
            "published train (sub-train addressability): only the "
            "covered blocks import, the rest chunk-prefills locally")
        self._m_store_skipped = r.counter(
            "kv_store_publish_skipped_total",
            "Store publishes skipped under byte-budget backpressure "
            "(folded resident bytes over --kv-store-max-bytes; the "
            "sweeper daemon owns getting back under)")
        self._m_paced = r.counter(
            "prefill_paced_total",
            "Prefill admissions deferred because the decode fleet's "
            "free-block gauges (heartbeat leases) could not land the "
            "prompt's shipments (ROADMAP item 2 pacing loop)")
        self._m_lane_fallbacks = r.counter(
            "kv_transport_lane_fallbacks_total",
            "Block-train imports that degraded from the mem lane to the "
            "fs artifact (fabric miss or metadata digest mismatch)")
        self._m_adapter_slots = r.gauge(
            "adapter_slots_active",
            "Decode slots currently pinned to each LoRA adapter "
            "(labelled by adapter; the null adapter is unlabelled base "
            "traffic and is not counted)")
        self._m_adapter_resident_bytes = r.gauge(
            "adapter_pages_resident_bytes",
            "LoRA factor bytes resident in the paged adapter pool "
            "(stale hot-swapped versions included until their last "
            "in-flight slot drains)")
        self._m_adapter_pageins = r.counter(
            "adapter_pagein_total",
            "Adapter artifacts CRC-verified and paged into the adapter "
            "pool (hot-swap loads included)")
        self._m_adapter_evictions = r.counter(
            "adapter_evictions_total",
            "Cold adapters evicted from the adapter pool under page "
            "pressure (refcount-0 residents only, LRU order)")
        # Content-addressed prefix reuse: only engines that OPT IN get the
        # cache (InferenceEngine sets enable_prefix_cache in paged mode;
        # test doubles without the attribute keep plain allocation).
        self.prefix_cache: Optional[PrefixCache] = None
        self.prefix_cow_copies = 0
        self.prefill_seconds = 0.0
        self._leak_audited = False
        if (self.kv_layout == "paged"
                and getattr(engine, "enable_prefix_cache", False)):
            self.prefix_cache = PrefixCache(
                self.allocator, engine.block_size,
                evictions_counter=self._m_prefix_evictions)
        # DRAFT-pool mirror (module docstring): same radix scheme over the
        # draft allocator, fed the same insertions, so shared prompts skip
        # draft prefill too. Full-prompt draft hits skip the phase outright
        # (no COW — the draft samples nothing at prefill).
        self.draft_prefix_cache: Optional[PrefixCache] = None
        if self.spec_k and self.prefix_cache is not None:
            self.draft_prefix_cache = PrefixCache(
                self.draft_allocator, engine.block_size,
                evictions_counter=self._m_prefix_evictions)
        if self.kv_layout == "paged":
            self._m_blocks_free.set(self.allocator.free_count)
            self._m_blocks_total.set(self.allocator.capacity)

    # --- queue management --------------------------------------------------

    def _blocks_needed(self, request: Request) -> int:
        # replay-invariant: committed tokens live inside the same
        # prompt + max_new_tokens budget the original admission sized
        bs = self.engine.block_size
        return -(-(len(request.prompt) + request.max_new_tokens) // bs)

    @staticmethod
    def _effective_prompt(request: Request) -> Sequence[int]:
        """What prefill actually processes: a migrated replay re-derives
        the dead host's KV by prefilling the prompt PLUS all but the last
        committed token (the last one is the next decode's input, exactly
        where the original stream stood)."""
        committed = list(getattr(request, "committed", ()) or ())
        if committed:
            return list(request.prompt) + committed[:-1]
        return request.prompt

    def _check_replay(self, request: Request, first) -> None:
        """Replay-integrity check. The replay prefill re-samples a token
        from the last committed position's logits; that sample is
        discarded (the committed list is the truth), but where sampling
        is PRNG-free (greedy) or the fold index coincides (a 1-token
        replay re-folds (seed, 0) exactly as the original prefill did) it
        must BIT-MATCH the journaled token — a mismatch means the journal
        and the model disagree and the migration must not proceed."""
        committed = list(request.committed)
        if first is None or not committed:
            return
        if request.temperature <= 0 or len(committed) == 1:
            if int(first) != int(committed[-1]):
                raise RuntimeError(
                    f"request {request.id}: replay re-derived token "
                    f"{int(first)} but the journal committed "
                    f"{int(committed[-1])} — journal/model divergence")

    def submit(self, request: Request,
               handoff_artifact: Optional[str] = None,
               handoff_gen: int = 0,
               shipments: Optional[Sequence[dict]] = None,
               ship_gen: int = 0) -> None:
        committed = list(getattr(request, "committed", ()) or ())
        if shipments and self.role == "prefill":
            raise ValueError(
                f"request {request.id}: a prefill-role engine cannot "
                f"accept block shipments (it only exports them)")
        if self.role == "prefill":
            # generation the shipments will be journaled under (audit)
            self._ship_req_gen[request.id] = int(ship_gen)
        if handoff_artifact and committed:
            # Block-shipment admission: _admit imports the artifact's
            # committed blocks instead of replay-prefilling; any
            # verification failure falls back to the replay path below.
            self._handoff_artifacts[request.id] = (handoff_artifact,
                                                   int(handoff_gen))
        if shipments and committed:
            # Disaggregated admission: _admit imports the prefill engine's
            # incremental shipments instead of replay-prefilling; any
            # verification failure falls back to the replay path below.
            self._shipments[request.id] = (
                [dict(s) for s in shipments], int(ship_gen))
        if committed and len(committed) >= request.max_new_tokens:
            raise ValueError(
                f"request {request.id}: {len(committed)} committed tokens "
                f"already meet max_new_tokens {request.max_new_tokens} — "
                f"nothing to decode; the caller should record it done")
        aname = str(getattr(request, "adapter", "") or "")
        if aname:
            # adapter serving is opt-in at engine build; an unregistered
            # name is a caller error HERE (not a crash in the decode
            # loop) — registered-but-unresident queues behind a verified
            # page-in at admission
            if self.adapters is None:
                raise ValueError(
                    f"request {request.id} names adapter {aname!r} but "
                    f"the engine was built without adapter serving "
                    f"(adapter_rank=0)")
            if not self.adapters.known(aname):
                raise ValueError(
                    f"request {request.id} names unregistered adapter "
                    f"{aname!r}")
        if len(request.prompt) + request.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"request {request.id}: prompt {len(request.prompt)} + "
                f"max_new_tokens {request.max_new_tokens} exceeds the "
                f"cache max_len {self.engine.max_len}")
        if (self.kv_layout == "paged"
                and self._blocks_needed(request) > self.allocator.capacity):
            raise ValueError(
                f"request {request.id}: needs {self._blocks_needed(request)} "
                f"KV blocks but the pool only has "
                f"{self.allocator.capacity} usable blocks")
        if (self.spec_k and self._blocks_needed(request)
                > self.draft_allocator.capacity):
            raise ValueError(
                f"request {request.id}: needs {self._blocks_needed(request)} "
                f"DRAFT KV blocks but the draft pool only has "
                f"{self.draft_allocator.capacity} usable blocks")
        self.queue.append((request, self.clock()))

    def stop_admission(self) -> None:
        """Drain mode: active slots finish, the queue stays unserved."""
        self.admission_open = False

    def resume_admission(self) -> None:
        """Reopen admission after a hot weight swap's pause
        (deploy/reload.py). NOT part of the signal-drain lifecycle — a
        drain's stop is final for the process; the reloader only restores
        the admission state it found open."""
        self.admission_open = True

    def pending(self) -> bool:
        return bool(self.active or self._pending_prefill
                    or ((self.queue or self._spilled)
                        and self.admission_open))

    def unserved(self) -> List[Request]:
        """Queued requests a drain leaves behind. Spilled requests count:
        each is reported as a replay request carrying its generated tokens
        as the committed prefix, so a journal requeue resumes the stream
        bit-exactly on whoever picks it up (the artifact itself dies with
        this process's tier)."""
        out = [r for r, _ in self.queue]
        for rid in self._spill_order:
            sp = self._spilled[rid]
            out.append(dataclasses.replace(sp.request,
                                           committed=tuple(sp.tokens)))
        return out

    # --- one decode iteration ----------------------------------------------

    def _acquire_adapter(self, req: Request, slot: int) -> None:
        """Pin ``req``'s adapter version to ``slot`` (+1 allocator ref
        per page) and bank its gather operands. The null adapter pins
        nothing — rows divert to null page 0 with scale 0, the base-only
        gate. Callers guarantee residency (the admission gate's verified
        page-in ran first)."""
        if self.adapters is None:
            return
        aname = str(getattr(req, "adapter", "") or "")
        arow, ascale = self.adapters.acquire(aname, slot)
        self._adapter_rows[slot] = arow
        self._adapter_scales[slot] = ascale
        if aname:
            self._slot_adapter[slot] = aname

    def _release_adapter(self, slot: int) -> None:
        """Drop a slot's adapter pin (slot freed, drain rollback, or
        finish) and zero its gather operands — the next occupant starts
        from the null divert."""
        if self.adapters is None:
            return
        self.adapters.release(slot)
        self._adapter_rows[slot] = 0
        self._adapter_scales[slot] = 0.0
        self._slot_adapter.pop(slot, None)

    def _finish(self, slot: int, reason: str, done: List[Completion]) -> None:
        st = self.active.pop(slot)
        self._ship_state.pop(st.request.id, None)
        self._release_adapter(slot)
        if self.adaptive_k is not None:
            self.adaptive_k.forget(st.request.id)
        if self.kv_layout == "paged":
            blocks = self._slot_blocks.pop(slot, None)
            if blocks:
                self.allocator.free(blocks)
                self.block_tables[slot] = 0
        if self.spec_k:
            dblocks = self._slot_draft_blocks.pop(slot, None)
            if dblocks:
                self.draft_allocator.free(dblocks)
                self.draft_block_tables[slot] = 0
        c = Completion(request_id=st.request.id,
                       prompt_len=len(st.request.prompt),
                       tokens=list(st.tokens), reason=reason,
                       submitted_at=st.submitted_at,
                       first_token_at=st.first_token_at,
                       finished_at=self.clock(),
                       spec_proposed=st.spec_proposed,
                       spec_accepted=st.spec_accepted,
                       spec_emitted_not_proposed=st.spec_corrected,
                       trace_id=str(getattr(st.request, "trace_id", "")
                                    or ""))
        self.completed.append(c)
        done.append(c)
        self._m_ttft.observe(c.ttft_seconds)
        if len(c.tokens) > 1:
            self._m_tpot.observe(c.tpot_seconds)
        self._m_done.labels(reason=reason).inc()
        self._trace(st.request, "done", reason=reason,
                    tokens=len(c.tokens), ttft=c.ttft_seconds,
                    tpot=c.tpot_seconds)

    def _trace(self, request: Request, span: str,
               dur: Optional[float] = None, **payload) -> None:
        """Emit one reqtrace span for a traced request (no-op when the
        request carries no trace_id — direct Scheduler users like the
        bench driver opt out by default)."""
        tid = str(getattr(request, "trace_id", "") or "")
        if tid:
            reqtrace.emit(tid, request.id, span, dur=dur, **payload)

    def _count_chunk(self) -> None:
        self.prefill_chunks += 1
        self._m_chunks.inc()
        # which kernel the chunk's paged reads dispatched through — the
        # serving-visible proof there is no silent gather under pallas
        if getattr(self.engine, "paged_kernel", "gather") == "pallas":
            self.prefill_inplace_chunks += 1
            self._m_prefill_inplace.inc()
        else:
            self.prefill_gather_chunks += 1
            self._m_prefill_gather.inc()
        if self._on_prefill_chunk is not None:
            # chaos hook (prefill_kill): fires BEFORE the chunk's shipment
            # exports, so a kill at ordinal N lands with chunk N computed
            # but unshipped — the mid-chunk death the disagg scenario needs
            self._on_prefill_chunk(self.prefill_chunks - 1)

    def _drain_requested(self) -> bool:
        return self.stop_check is not None and bool(self.stop_check())

    def _admit(self, done: List[Completion]) -> None:
        if self._spilled:
            # Parked requests come home FIRST: a restore needs only a free
            # slot plus its private blocks (shared prefix re-acquired from
            # the cache), and runs before any new admission can take them.
            self._try_restores(done)
        taken = set(self.active)
        taken.update(p.slot for p in self._pending_prefill)
        free = [s for s in range(self.engine.slots) if s not in taken]
        while free and self.queue:
            if self._spilled:
                # A spilled request is still waiting for blocks: freed
                # capacity flows to its restore before any NEW admission
                # (strict anti-starvation — a preempted stream can never
                # be overtaken indefinitely by fresh arrivals).
                break
            req, submitted_at = self.queue[0]
            if self.role == "prefill" and self.pacing is not None:
                # Shipment pacing (ROADMAP item 2's control plane): every
                # block this prompt prefills becomes a shipment the decode
                # fleet must land, so admit only when the decode pool's
                # free-block gauges (heartbeat leases, via pacing()) cover
                # the need. Deferral keeps the queue intact — FIFO order
                # and the submit contract are untouched, the head simply
                # waits like it does for local pool shortage. pacing()
                # returning None (no decode peers visible) never stalls.
                decode_free = self.pacing()
                if (decode_free is not None
                        and decode_free < self._blocks_needed(req)):
                    self.prefill_paced += 1
                    self._m_paced.inc()
                    if req.id not in self._paced_logged:
                        # one audit line per request, not per retry round
                        self._paced_logged.add(req.id)
                        self._audit_xport(
                            "pace", self.transport.name, req.id,
                            self._blocks_needed(req),
                            f"decode fleet has {decode_free} free "
                            f"block(s), admission deferred")
                    break
            aname = str(getattr(req, "adapter", "") or "")
            if aname and self.adapters is not None \
                    and not self.adapters.resident(aname):
                # Combined KV+adapter admission: the adapter half of the
                # footprint must land (CRC-verified page-in, cold-adapter
                # eviction under pressure) BEFORE any KV blocks are
                # grabbed. A full pool leaves the head queued (FIFO, the
                # same wait as KV shortage); a corrupt artifact rejects
                # THIS request with the pool untouched — never a crash.
                from .adapters import AdapterIntegrityError
                try:
                    paged_in = self.adapters.page_in(aname)
                except (AdapterIntegrityError, KeyError) as e:
                    self.queue.popleft()
                    self.adapter_rejects += 1
                    events.emit_audit(logger, AUDIT_ADAPTER_FMT.format(
                        action="reject", name=aname,
                        pages=self.adapters.layout.pages_per_adapter,
                        detail=f"request {req.id}: {e}"), "adapter")
                    now = self.clock()
                    c = Completion(
                        request_id=req.id, prompt_len=len(req.prompt),
                        tokens=[], reason="adapter_rejected",
                        submitted_at=submitted_at, first_token_at=now,
                        finished_at=now,
                        trace_id=str(getattr(req, "trace_id", "") or ""))
                    self.completed.append(c)
                    done.append(c)
                    self._m_done.labels(reason="adapter_rejected").inc()
                    self._trace(req, "done", reason="adapter_rejected")
                    continue
                if not paged_in:
                    self.adapter_waits += 1
                    break
                events.emit_audit(logger, AUDIT_ADAPTER_FMT.format(
                    action="page-in", name=aname,
                    pages=self.adapters.layout.pages_per_adapter,
                    detail=f"request {req.id} admitted behind verified "
                           f"load"), "adapter")
            art_entry = self._handoff_artifacts.get(req.id)
            if (art_entry is not None and self.kv_layout == "paged"
                    and not self.spec_k):
                # Block-shipment admission: import the handed-off blocks
                # instead of replay-prefilling the committed prefix.
                outcome = self._admit_from_handoff(req, submitted_at, free,
                                                   art_entry, done)
                if outcome == "wait":
                    break
                if outcome == "imported":
                    continue
                # "fallback": artifact rejected — the replay path below
                # re-derives the stream bit-exactly from prompt+committed
            ship_entry = self._shipments.get(req.id)
            if (ship_entry is not None and self.kv_layout == "paged"
                    and not self.spec_k):
                # Disaggregated admission: import the prefill engine's
                # incremental shipments (prefix-cache-deduped) instead of
                # replay-prefilling the committed prefix.
                outcome = self._admit_from_shipments(req, submitted_at,
                                                     free, ship_entry, done)
                if outcome == "wait":
                    break
                if outcome == "imported":
                    continue
                # "fallback": shipment rejected — the replay path below
                # re-derives the stream bit-exactly from prompt+committed
            if (self.kv_store is not None and self.prefix_cache is not None
                    and not self.spec_k):
                # Fleet-store fetch: land the deepest published train
                # matching this prompt in the LOCAL prefix cache first, so
                # every admission lane below (sequential, packed, full-hit
                # COW, drain rollback) sees it as an ordinary deep prefix
                # hit. A miss or CRC reject changes nothing — the local
                # chunked prefill below IS the fallback.
                self._maybe_store_fetch(req)
            # replay admissions prefill prompt + committed[:-1]; every
            # prefix-cache and prefill path below works on this view
            eff = self._effective_prompt(req)
            blocks, dblocks = None, None
            hit, dhit = None, None
            if self.kv_layout == "paged":
                # admission is by free-BLOCK count, not free-slot count:
                # the head of the queue waits (FIFO, no starvation) until
                # eviction frees enough blocks for its actual need. A
                # prefix-cache hit covers its blocks at zero cost (one
                # refcount each); only the remainder is allocated fresh —
                # plus one COW block when the hit covers the whole prompt
                # (prefill must resume inside the final shared block). On
                # shortage, LRU cached prefixes no live slot references
                # are evicted before the head of the queue waits. Spec
                # mode admits by the COMBINED footprint — both pools must
                # cover the request, and a partial grab is rolled back so
                # a draft-pool shortage can't strand target blocks.
                total = self._blocks_needed(req)
                if self.prefix_cache is not None:
                    hit = self.prefix_cache.match(eff)
                    if not hit.blocks:
                        hit = None
                fresh = total - (len(hit.blocks) if hit else 0) \
                    + (1 if hit and hit.full else 0)
                if hit is not None:
                    # reference the hit FIRST: the eviction below can then
                    # never free the prefix this slot is about to reuse
                    self.prefix_cache.acquire(hit)
                blocks = self.allocator.alloc(fresh)
                if blocks is None and self.prefix_cache is not None:
                    if self.prefix_cache.evict(
                            fresh - self.allocator.free_count):
                        blocks = self.allocator.alloc(fresh)
                if blocks is None and self.enable_spill:
                    # Spill tier: preempt the coldest active request into
                    # a host-side checksummed artifact instead of making
                    # the head of the queue wait for a natural eviction.
                    blocks = self._spill_for(fresh, free)
                if blocks is None:
                    if hit is not None:
                        self.allocator.free(hit.blocks)
                    break
                if self.spec_k:
                    # DRAFT-pool mirror of the same protocol. A full draft
                    # hit takes NO extra COW block: the draft phase is
                    # skipped outright (module docstring). A shortage here
                    # rolls back every reference both pools acquired.
                    if self.draft_prefix_cache is not None:
                        dhit = self.draft_prefix_cache.match(eff)
                        if not dhit.blocks:
                            dhit = None
                    dfresh = total - (len(dhit.blocks) if dhit else 0)
                    if dhit is not None:
                        self.draft_prefix_cache.acquire(dhit)
                    dblocks = self.draft_allocator.alloc(dfresh)
                    if (dblocks is None
                            and self.draft_prefix_cache is not None):
                        if self.draft_prefix_cache.evict(
                                dfresh - self.draft_allocator.free_count):
                            dblocks = self.draft_allocator.alloc(dfresh)
                    if dblocks is None:
                        if dhit is not None:
                            self.draft_allocator.free(dhit.blocks)
                        self.allocator.free(blocks)
                        if hit is not None:
                            self.allocator.free(hit.blocks)
                        break
            self.queue.popleft()
            slot = free.pop(0)
            self._acquire_adapter(req, slot)
            self._trace(req, "queue", dur=self.clock() - submitted_at,
                        slot=slot)
            if self.kv_layout == "paged":
                start_pos = 0
                slot_blocks = blocks
                if hit is not None:
                    slot_blocks = list(hit.blocks)
                    start_pos = hit.tokens
                    fresh_tail = blocks
                    if hit.full:
                        # Full-prompt hit: sampling the first token needs
                        # the LAST prompt position's logits, so prefill
                        # resumes at prompt_len - 1 — a write into the
                        # final shared block. Copy-on-write: duplicate it
                        # into the first fresh block, remap, and drop this
                        # slot's reference on the shared original.
                        cow_dst = blocks[0]
                        self.engine.cow_copy(slot_blocks[-1], cow_dst)
                        self.allocator.free([slot_blocks[-1]])
                        slot_blocks[-1] = cow_dst
                        start_pos = hit.tokens - 1
                        fresh_tail = blocks[1:]
                        self.prefix_cache.cow_copies += 1
                        self.prefix_cow_copies += 1
                    slot_blocks = slot_blocks + fresh_tail
                row = np.zeros((self.engine.max_blocks_per_slot,), np.int32)
                row[:len(slot_blocks)] = slot_blocks
                self.block_tables[slot] = row
                if self.role == "prefill":
                    # incremental-shipment ledger; a prefix-cache hit's
                    # leading blocks are committed KV by definition, so
                    # they ship IMMEDIATELY as artifact 0 — the decode
                    # engine can be importing them while prefill still
                    # streams the divergent remainder
                    self._ship_state[req.id] = {
                        "shipped": 0, "seq": 0,
                        "gen": self._ship_req_gen.pop(req.id, 0)}
                    if start_pos:
                        self._ship_commit(req, slot_blocks, eff, start_pos)
                if self.prefill_batch > 1:
                    # PACKED lane: ownership established (blocks, prefix
                    # references, full-hit COW all done above) — enqueue
                    # the prompt for the chunk-interleaved rounds instead
                    # of streaming it to completion here. Prefix insert /
                    # hit accounting moves to the row's completion, where
                    # the sequential lane does it too.
                    self._pending_prefill.append(_PendingPrefill(
                        request=req, submitted_at=submitted_at, slot=slot,
                        row=row, blocks=slot_blocks, start_pos=start_pos,
                        pos=start_pos, eff=eff))
                    continue
                spec_kw = {}
                slot_dblocks = dblocks
                if self.spec_k:
                    draft_start = 0
                    if dhit is not None:
                        # mirror of the target's hit splice, minus the
                        # full-hit COW: the shared blocks lead the row, the
                        # fresh tail covers the divergent prompt remainder
                        # and the generation budget; a full hit resumes at
                        # == prompt_len, i.e. skips the draft phase.
                        slot_dblocks = list(dhit.blocks) + dblocks
                        draft_start = dhit.tokens
                    drow = np.zeros((self.engine.max_blocks_per_slot,),
                                    np.int32)
                    drow[:len(slot_dblocks)] = slot_dblocks
                    self.draft_block_tables[slot] = drow
                    # only spec-mode engines need (or accept) the draft
                    # row — non-spec engine doubles keep the old signature
                    spec_kw["draft_block_row"] = drow
                    if self.draft_prefix_cache is not None:
                        spec_kw["draft_start_pos"] = draft_start
                if self.prefix_cache is not None:
                    # only cache-aware engines accept the offset kwarg —
                    # test doubles without enable_prefix_cache never see it
                    spec_kw["start_pos"] = start_pos
                if self.adapters is not None:
                    # only adapter engines accept the adapter kwargs
                    spec_kw["adapter_row"] = self._adapter_rows[slot]
                    spec_kw["adapter_scale"] = float(
                        self._adapter_scales[slot])
                on_chunk = self._count_chunk
                if self.role == "prefill":
                    # chunk-granular shipping: each finished chunk commits
                    # its KV, so its full blocks export right here — the
                    # incremental half of the disaggregated pipeline (the
                    # packed lane does the same in _prefill_round)
                    chunk_max = self.engine.prefill_buckets[-1]
                    ship_pos = {"pos": start_pos}
                    _req, _blocks, _eff = req, slot_blocks, eff

                    def on_chunk():
                        self._count_chunk()
                        ship_pos["pos"] += min(chunk_max,
                                               len(_eff) - ship_pos["pos"])
                        self._ship_commit(_req, _blocks, _eff,
                                          ship_pos["pos"])
                t0 = self.clock()
                first = self.engine.prefill(
                    slot, eff, block_row=row,
                    temperature=req.temperature, top_p=req.top_p,
                    seed=req.seed, stop_check=self._drain_requested,
                    on_chunk=on_chunk, **spec_kw)
                pf_dur = self.clock() - t0
                self.prefill_seconds += pf_dur
                if first is None:
                    # Drain fired mid-prompt: the engine finished the
                    # current chunk and stopped. Free the slot's blocks
                    # exactly once each (fresh, COW and acquired shared
                    # references alike — shared blocks survive under the
                    # cache's own reference), put the request back at the
                    # head so it is REPORTED unserved, and close
                    # admission — the drain stays exact.
                    self.allocator.free(slot_blocks)
                    self.block_tables[slot] = 0
                    self._ship_state.pop(req.id, None)
                    if self.spec_k:
                        self.draft_allocator.free(slot_dblocks)
                        self.draft_block_tables[slot] = 0
                    self._release_adapter(slot)
                    self.queue.appendleft((req, submitted_at))
                    self.stop_admission()
                    return
                self._slot_blocks[slot] = slot_blocks
                if self.spec_k:
                    self._slot_draft_blocks[slot] = slot_dblocks
                    if self.draft_prefix_cache is not None:
                        self.draft_prefix_cache.insert(eff, slot_dblocks)
                        self.draft_prefix_cache.note_admission(
                            draft_start, len(eff))
                if self.prefix_cache is not None:
                    self.prefix_cache.insert(eff, slot_blocks)
                    self.prefix_cache.note_admission(start_pos, len(eff))
                    self._m_prefix_hit_rate.set(self.prefix_cache.hit_rate)
                    self._maybe_store_publish(req, eff, slot_blocks)
            else:
                t0 = self.clock()
                first = self.engine.prefill(slot, eff,
                                            temperature=req.temperature,
                                            top_p=req.top_p, seed=req.seed)
                pf_dur = self.clock() - t0
                self.prefill_seconds += pf_dur
            self._check_replay(req, first)
            st = self.active[slot] = _Slot(req, first, submitted_at,
                                           self.clock())
            self._trace(req, "prefill", dur=pf_dur,
                        prompt_tokens=len(eff), packed=False,
                        replayed=len(list(req.committed or ())))
            self._trace(req, "first_token",
                        ttft=st.first_token_at - st.submitted_at)
            self.max_concurrent = max(self.max_concurrent, len(self.active))
            self._m_tokens.inc()  # the prefill's first token
            if self.role == "prefill":
                # prefill engine's contract: decode belongs to a decode
                # engine. The final shipment exported with the last chunk;
                # finish with the first token as the committed handoff
                # point (fleet.py journals prefill_done, the router places
                # the decode). EOS/budget on that token are the DECODE
                # admission's finish checks — uniform either way.
                self._finish(slot, "prefill", done)
                continue
            # a request can finish straight out of prefill (a replay can
            # arrive with EOS as its last committed token, or within one
            # token of its budget — the same checks, on the banked tail)
            if (self.eos_token_id is not None
                    and st.tokens[-1] == self.eos_token_id):
                self._finish(slot, "eos", done)
            elif len(st.tokens) >= req.max_new_tokens:
                self._finish(slot, "length", done)

    # --- spill tier + handoff (tiered KV-block lifecycle) -------------------

    def _spill_tier_root(self) -> str:
        if self._spill_root is None:
            if self._spill_dir_arg:
                os.makedirs(self._spill_dir_arg, exist_ok=True)
                self._spill_root = self._spill_dir_arg
            else:
                self._spill_root = tempfile.mkdtemp(prefix="kv_spill_")
        return self._spill_root

    def _audit_tier(self, action: str, rid: str, blocks: int,
                    nbytes: int) -> None:
        tier = self._spill_dir_arg or "host-ram"
        events.emit_audit(logger, AUDIT_KV_TIER_FMT.format(
            action=action, id=rid, blocks=blocks, bytes=nbytes, tier=tier),
            "kv_tier")

    def _audit_handoff(self, action: str, rid: str, gen: int, blocks: int,
                       detail: str) -> None:
        events.emit_audit(logger, AUDIT_HANDOFF_FMT.format(
            action=action, id=rid, gen=gen, blocks=blocks, detail=detail),
            "handoff")

    def _set_spill_gauges(self) -> None:
        self._m_blocks_spilled.set(
            sum(len(sp.private_positions) for sp in self._spilled.values()))
        self._m_spill_bytes.set(
            float(sum(sp.bytes for sp in self._spilled.values())))

    def _pick_spill_victim(self) -> Optional[int]:
        """The COLDEST active request: the one farthest from completion
        (largest remaining token budget — it would hold its blocks the
        longest), ties broken toward the most recently submitted, then
        the highest slot. Deterministic for a fixed workload."""
        best, best_key = None, None
        for slot, st in self.active.items():
            remaining = st.request.max_new_tokens - len(st.tokens)
            if remaining <= 0:
                continue
            key = (remaining, st.submitted_at, slot)
            if best_key is None or key > best_key:
                best, best_key = slot, key
        return best

    def _spill_for(self, fresh: int, free: List[int]) -> Optional[List[int]]:
        """Preempt victims until ``fresh`` blocks allocate (or no victim
        remains). Freed victim slots rejoin the admission ``free`` list."""
        blocks = None
        while blocks is None:
            victim = self._pick_spill_victim()
            if victim is None or not self._spill_slot(victim):
                return None
            free.append(victim)
            free.sort()
            blocks = self.allocator.alloc(fresh)
            if blocks is None and self.prefix_cache is not None:
                if self.prefix_cache.evict(
                        fresh - self.allocator.free_count):
                    blocks = self.allocator.alloc(fresh)
        return blocks

    def _spill_slot(self, slot: int) -> bool:
        """Export ``slot``'s PRIVATE blocks to the spill tier and release
        the device row. Shared prefix-cache blocks are NOT spilled — their
        bytes stay warm on the device under the cache's own reference and
        the restore re-acquires them by content; only this slot's
        references are dropped. Returns False if the slot holds nothing
        spillable (row fully shared, or sharing isn't the leading prefix
        the restore splice depends on)."""
        st = self.active[slot]
        rid = st.request.id
        if rid in self._spilled:
            raise RuntimeError(f"request {rid} is already spilled — "
                               f"double spill")
        row_blocks = list(self._slot_blocks[slot])
        shared = 0
        while (shared < len(row_blocks)
               and self.allocator.refcount(row_blocks[shared]) > 1):
            shared += 1
        if any(self.allocator.refcount(b) > 1 for b in row_blocks[shared:]):
            return False
        private = row_blocks[shared:]
        if not private:
            return False
        bs = self.engine.block_size
        # positions 0..lengths[slot) hold the KV of prompt+tokens in
        # order; the shared leading blocks therefore cover exactly the
        # first shared*bs of that stream — the content-addressed key the
        # restore re-matches against the prefix cache
        full_stream = list(st.request.prompt) + [int(t) for t in st.tokens]
        shared_tokens = full_stream[:shared * bs]
        art_dir = os.path.join(self._spill_tier_root(),
                               f"spill_{self.spill_exports:04d}_{rid}")
        manifest = self.engine.export_slot_blocks(
            private, art_dir, slot=slot,
            meta={"kind": "spill", "request_id": rid,
                  "tokens": [int(t) for t in st.tokens],
                  "positions": list(range(shared, len(row_blocks)))})
        nbytes = artifact_bytes(manifest)
        ordinal = self.spill_exports
        self.spill_exports += 1
        if self._on_spill is not None:
            # chaos hook (spill_corrupt): keyed by export ordinal
            self._on_spill(art_dir, ordinal)
        self._spilled[rid] = _SpilledRequest(
            request=st.request, submitted_at=st.submitted_at,
            first_token_at=st.first_token_at,
            tokens=[int(t) for t in st.tokens], steps=st.steps,
            emitted=list(st.emitted), shared_tokens=shared_tokens,
            private_positions=list(range(shared, len(row_blocks))),
            blocks_total=len(row_blocks), artifact_dir=art_dir,
            bytes=nbytes)
        self._spill_order.append(rid)
        self.active.pop(slot)
        del self._slot_blocks[slot]
        self.allocator.free(row_blocks)
        self.block_tables[slot] = 0
        # the parked request drops its adapter pin too — a cold adapter
        # may evict while it waits; the restore pages it back in verified
        self._release_adapter(slot)
        self._set_spill_gauges()
        self._audit_tier("export", rid, len(private), nbytes)
        self._trace(st.request, "spill", blocks=len(private), bytes=nbytes)
        return True

    def spill(self, slot: int) -> None:
        """Explicit preemption (tests; the future SLO scheduler's
        preempt-by-class hook): spill ``slot``'s active request to the
        host tier now."""
        if not self.enable_spill:
            raise RuntimeError("spill tier disabled (enable_spill/"
                               "spill_dir not set)")
        if slot not in self.active:
            raise KeyError(f"slot {slot} has no active request")
        if not self._spill_slot(slot):
            raise RuntimeError(f"slot {slot} holds no spillable private "
                               f"blocks")

    def _try_restores(self, done: List[Completion]) -> None:
        taken = set(self.active)
        taken.update(p.slot for p in self._pending_prefill)
        free = [s for s in range(self.engine.slots) if s not in taken]
        for rid in list(self._spill_order):
            if not free:
                return
            outcome = self._restore_one(rid, free[0], done)
            if outcome == "wait":
                # FIFO across the tier: the oldest parked request gets the
                # next blocks; younger ones don't overtake it
                return
            if outcome == "restored":
                free.pop(0)

    def _restore_one(self, rid: str, slot: int,
                     done: List[Completion]) -> str:
        """Bring one spilled request back onto the device: re-acquire its
        shared prefix from the cache by content, allocate private blocks,
        CRC-verify + import the artifact, and resurrect the slot state so
        the next decode folds exactly the step the preempted stream would
        have. Any failure — evicted prefix, rejected artifact — falls back
        to a bit-exact replay from prompt+committed. Returns
        'restored' | 'wait' | 'replay'."""
        sp = self._spilled.get(rid)
        if sp is None:
            raise RuntimeError(f"request {rid} is not spilled — "
                               f"double restore")
        aname = str(getattr(sp.request, "adapter", "") or "")
        if aname and self.adapters is not None \
                and not self.adapters.resident(aname):
            # the adapter may have evicted while the request was parked:
            # page it back in (verified) before touching any KV blocks,
            # so a shortage or reject leaves both pools untouched
            from .adapters import AdapterIntegrityError
            try:
                if not self.adapters.page_in(aname):
                    return "wait"
            except (AdapterIntegrityError, KeyError) as e:
                self._spill_fallback(rid, f"adapter page-in rejected: {e}")
                return "replay"
        bs = self.engine.block_size
        n_shared = len(sp.shared_tokens) // bs
        hit = None
        if n_shared:
            if self.prefix_cache is not None:
                h = self.prefix_cache.match(sp.shared_tokens)
                if h.blocks and h.tokens >= len(sp.shared_tokens):
                    hit = h
            if hit is None:
                # the cache evicted the shared prefix while we were
                # parked: those device bytes are gone — replay fallback
                self._spill_fallback(rid, "shared prefix evicted")
                return "replay"
            self.prefix_cache.acquire(hit)
        n_private = len(sp.private_positions)
        blocks = self.allocator.alloc(n_private)
        if blocks is None and self.prefix_cache is not None:
            if self.prefix_cache.evict(
                    n_private - self.allocator.free_count):
                blocks = self.allocator.alloc(n_private)
        if blocks is None:
            if hit is not None:
                self.allocator.free(hit.blocks)
            return "wait"
        try:
            self.engine.import_slot_blocks(sp.artifact_dir, blocks, slot)
        except KVBlockIntegrityError as e:
            self.allocator.free(blocks)
            if hit is not None:
                self.allocator.free(hit.blocks)
            self._spill_fallback(rid, f"restore rejected: {e}")
            return "replay"
        slot_blocks = (list(hit.blocks)[:n_shared] if hit is not None
                       else []) + blocks
        row = np.zeros((self.engine.max_blocks_per_slot,), np.int32)
        row[:len(slot_blocks)] = slot_blocks
        self.block_tables[slot] = row
        self._slot_blocks[slot] = slot_blocks
        st = _Slot(sp.request, sp.tokens[-1], sp.submitted_at,
                   sp.first_token_at)
        st.tokens = list(sp.tokens)
        st.steps = sp.steps
        st.emitted = list(sp.emitted)
        self._acquire_adapter(sp.request, slot)
        self.active[slot] = st
        self.max_concurrent = max(self.max_concurrent, len(self.active))
        self._drop_spilled(rid)
        self.spill_restores += 1
        self._m_spill_restores.inc()
        self._audit_tier("restore", rid, n_private, sp.bytes)
        self._trace(sp.request, "restore", blocks=n_private,
                    shared=n_shared)
        return "restored"

    def _spill_fallback(self, rid: str, detail: str) -> None:
        """Restore impossible: requeue a replay request at the head —
        prompt + committed re-derives the stream bit-exactly (the PR 11
        migration invariant), so a lost/corrupt artifact costs prefill
        compute, never correctness."""
        sp = self._spilled[rid]
        self.spill_rejects += 1
        self._audit_tier("reject", rid, len(sp.private_positions), sp.bytes)
        logger.warning("Spill restore of request %s fell back to "
                       "committed-prefix replay: %s", rid, detail)
        replay = dataclasses.replace(sp.request, committed=tuple(sp.tokens))
        self.queue.appendleft((replay, sp.submitted_at))
        self._drop_spilled(rid)
        self._trace(sp.request, "spill_replay", blocks=0, detail=detail)

    def _drop_spilled(self, rid: str) -> None:
        sp = self._spilled.pop(rid)
        self._spill_order.remove(rid)
        shutil.rmtree(sp.artifact_dir, ignore_errors=True)
        self._set_spill_gauges()

    def discard_spilled(self) -> int:
        """Drain epilogue: drop every parked artifact. The requests were
        reported unserved with their committed prefixes (see
        :meth:`unserved`) — the journal requeue is their durable form; the
        tier dies with this process. Returns how many were discarded."""
        n = len(self._spilled)
        for rid in list(self._spill_order):
            self._drop_spilled(rid)
        if self._spill_root is not None and not self._spill_dir_arg:
            shutil.rmtree(self._spill_root, ignore_errors=True)
            self._spill_root = None
        return n

    def export_handoff(self, slot: int, out_dir: str, gen: int = 0) -> dict:
        """Drain-with-handoff (fleet.py): serialize ``slot``'s committed
        blocks — shared prefix included, the survivor's cache is a
        different pool — into a checksummed artifact, release the device
        row, and requeue the request with its committed prefix so it is
        REPORTED unserved exactly like a plain drain. The journal's
        ``handoff`` record then lets the router ship blocks instead of
        replaying; a missing/torn/corrupt artifact degrades to the
        existing replay migration. Returns the shipment summary."""
        st = self.active[slot]
        rid = st.request.id
        bs = self.engine.block_size
        length = int(np.asarray(self.engine.cache.lengths)[slot])
        n = -(-length // bs)
        row_blocks = list(self._slot_blocks[slot])
        manifest = self.engine.export_slot_blocks(
            row_blocks[:n], out_dir, slot=slot,
            meta={"kind": "handoff", "request_id": rid,
                  "prompt": [int(t) for t in st.request.prompt],
                  "tokens": [int(t) for t in st.tokens],
                  "positions": list(range(n))})
        nbytes = artifact_bytes(manifest)
        self.active.pop(slot)
        del self._slot_blocks[slot]
        self.allocator.free(row_blocks)
        self.block_tables[slot] = 0
        replay = dataclasses.replace(st.request, committed=tuple(st.tokens))
        self.queue.appendleft((replay, st.submitted_at))
        self._m_handoff_shipped.inc(n)
        self._audit_handoff("export", rid, gen, n,
                            os.path.basename(out_dir))
        self._trace(st.request, "handoff_export", blocks=n, bytes=nbytes)
        return {"dir": out_dir, "blocks": n, "bytes": nbytes,
                "tokens": [int(t) for t in st.tokens], "request": replay}

    def _admit_from_handoff(self, req: Request, submitted_at: float,
                            free: List[int], art_entry,
                            done: List[Completion]) -> str:
        """Admission by block import: verify the handed-off artifact
        (CRC + journal agreement) BEFORE touching the device, allocate the
        request's full footprint, scatter the shipped blocks in, and
        resurrect the slot at the exact decode step the departed host
        would have run next — no replay prefill. Returns 'imported',
        'wait' (pool shortage: head-of-line semantics unchanged), or
        'fallback' (artifact rejected; the caller's replay path serves the
        request bit-exactly)."""
        art_dir, gen = art_entry
        from .kv_cache import verify_block_artifact
        committed = [int(t) for t in (req.committed or ())]
        try:
            manifest = verify_block_artifact(art_dir)
        except KVBlockIntegrityError as e:
            self._handoff_reject(req, gen, str(e))
            return "fallback"
        meta = manifest.get("meta", {})
        n = len(manifest.get("blocks", []))
        total = self._blocks_needed(req)
        if (meta.get("kind") != "handoff"
                or [int(t) for t in meta.get("tokens", [])] != committed
                or ([int(t) for t in meta.get("prompt", [])]
                    != [int(t) for t in req.prompt])
                or n > total):
            self._handoff_reject(req, gen,
                                 "artifact disagrees with the journal")
            return "fallback"
        blocks = self.allocator.alloc(total)
        if blocks is None and self.prefix_cache is not None:
            if self.prefix_cache.evict(total - self.allocator.free_count):
                blocks = self.allocator.alloc(total)
        if blocks is None and self.enable_spill:
            blocks = self._spill_for(total, free)
        if blocks is None:
            return "wait"
        slot = free[0]
        try:
            self.engine.import_slot_blocks(art_dir, blocks[:n], slot)
        except KVBlockIntegrityError as e:
            self.allocator.free(blocks)
            self._handoff_reject(req, gen, str(e))
            return "fallback"
        self.queue.popleft()
        free.pop(0)
        self._handoff_artifacts.pop(req.id, None)
        row = np.zeros((self.engine.max_blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
        self.block_tables[slot] = row
        self._slot_blocks[slot] = blocks
        eff = self._effective_prompt(req)
        if self.prefix_cache is not None:
            # the imported row covers the full committed prompt — cache it
            # so sibling prompts share it, exactly as a prefill would have
            self.prefix_cache.insert(eff, blocks)
            self.prefix_cache.note_admission(len(eff), len(eff))
            self._m_prefix_hit_rate.set(self.prefix_cache.hit_rate)
        self._trace(req, "queue", dur=self.clock() - submitted_at,
                    slot=slot)
        self._acquire_adapter(req, slot)
        st = self.active[slot] = _Slot(req, committed[-1], submitted_at,
                                       self.clock())
        self.handoff_imports += 1
        self._m_handoff_shipped.inc(n)
        self._audit_handoff("import", req.id, gen, n,
                            os.path.basename(art_dir))
        self._trace(req, "handoff_import", blocks=n,
                    committed=len(committed))
        self._trace(req, "first_token",
                    ttft=st.first_token_at - st.submitted_at)
        self.max_concurrent = max(self.max_concurrent, len(self.active))
        if (self.eos_token_id is not None
                and st.tokens[-1] == self.eos_token_id):
            self._finish(slot, "eos", done)
        elif len(st.tokens) >= req.max_new_tokens:
            self._finish(slot, "length", done)
        return "imported"

    def _handoff_reject(self, req: Request, gen: int, detail: str) -> None:
        self._handoff_artifacts.pop(req.id, None)
        self.handoff_rejects += 1
        self._m_handoff_rejected.inc()
        self._audit_handoff("reject", req.id, gen, 0, detail)
        logger.warning("Handoff import of request %s rejected (%s); "
                       "falling back to committed-prefix replay", req.id,
                       detail)
        self._trace(req, "handoff_reject", detail=detail)

    # --- disaggregated prefill/decode shipping ------------------------------

    def _ship_root(self) -> str:
        if self._ship_root_path is None:
            if self._ship_dir_arg:
                os.makedirs(self._ship_dir_arg, exist_ok=True)
                self._ship_root_path = self._ship_dir_arg
            else:
                self._ship_root_path = tempfile.mkdtemp(prefix="kv_ship_")
        return self._ship_root_path

    def _audit_ship(self, action: str, rid: str, seq: int, gen: int,
                    start: int, end: int, detail: str) -> None:
        events.emit_audit(logger, AUDIT_DISAGG_SHIP_FMT.format(
            action=action, id=rid, seq=seq, gen=gen, start=start, end=end,
            detail=detail), "disagg_ship")

    def _audit_xport(self, action: str, lane: str, rid: str, blocks: int,
                     detail: str) -> None:
        events.emit_audit(logger, AUDIT_KV_XPORT_FMT.format(
            action=action, lane=lane, id=rid, blocks=blocks,
            detail=detail), "kv_xport", action=action, lane=lane, id=rid,
            blocks=blocks)

    def _ship_commit(self, req: Request, slot_blocks: List[int],
                     eff: Sequence[int], pos: int) -> None:
        """Export the blocks the prefill just COMMITTED — full blocks up
        to absolute position ``pos``, everything once ``pos`` reaches the
        prompt end — as one incremental checksummed shipment. Chunk
        boundaries rarely align with block boundaries, so a chunk whose
        tokens all land inside a still-open block ships nothing; the next
        boundary crossing carries it. The partially-filled final block
        ships only with the LAST commit (its bytes keep changing until
        then), which is what makes "decode never reads an uncommitted
        block" structural: a shipment's blocks are immutable on export."""
        st = self._ship_state.get(req.id)
        if st is None:
            return
        bs = self.engine.block_size
        end = -(-len(eff) // bs) if pos >= len(eff) else pos // bs
        if end <= st["shipped"]:
            return
        start = st["shipped"]
        seq = st["seq"]
        length = int(min(pos, len(eff)))
        art_dir = os.path.join(
            self._ship_root(),
            f"ship_{self.ship_exports:05d}_{req.id}_{seq:02d}")
        t0 = self.clock()
        manifest = self.transport.export(
            self.engine.cache, list(slot_blocks[start:end]), art_dir,
            length=length,
            meta={"kind": "ship", "request_id": req.id,
                  "prompt": [int(t) for t in eff],
                  "seq": seq, "start_block": start, "end_block": end})
        dur = self.clock() - t0
        nbytes = artifact_bytes(manifest)
        ordinal = self.ship_exports
        self.ship_exports += 1
        st["shipped"] = end
        st["seq"] = seq + 1
        self._m_ship_exports.inc()
        self._m_handoff_shipped.inc(end - start)
        self._m_xport_bytes.labels(lane="fs").inc(nbytes)
        self._audit_ship("export", req.id, seq, st.get("gen", 0), start,
                         end, os.path.basename(art_dir))
        if self.transport.name == "mem":
            # the mem lane rides the same export: the device arrays are
            # already in the fabric, addressed by the artifact path
            self._m_xport_bytes.labels(lane="mem").inc(nbytes)
            self._audit_xport("push", "mem", req.id, end - start,
                              f"seq {seq}, {nbytes} byte(s)")
        self._trace(req, "block_ship", dur=dur, seq=seq,
                    blocks=end - start, bytes=nbytes, length=length)
        if self._on_ship is not None:
            # fleet.py: chaos (ship_corrupt, keyed by export ordinal)
            # then the journal's ship record
            self._on_ship(req, art_dir, ordinal, seq, start, end, length)

    def _admit_from_shipments(self, req: Request, submitted_at: float,
                              free: List[int], ship_entry,
                              done: List[Completion]) -> str:
        """Decode-side admission by incremental block import: CRC-verify
        EVERY shipment and check contiguous coverage of the committed
        prompt BEFORE touching the device (decode never reads an
        uncommitted block), dedupe the leading shipments against the
        prefix cache (already-resident shared-prompt blocks are acquired
        by content, not re-imported), scatter the rest in, and resurrect
        the slot at the exact decode step the prefill engine committed —
        fold_in(seed, len(committed)) continues the SAME stream. Returns
        'imported', 'wait' (pool shortage: head-of-line semantics
        unchanged) or 'fallback' (rejected: the caller's replay path
        re-derives the stream bit-exactly, the PR 13 degradation
        contract)."""
        ships, gen = ship_entry
        committed = [int(t) for t in (req.committed or ())]
        eff = [int(t) for t in self._effective_prompt(req)]
        bs = self.engine.block_size
        n_ship_blocks = -(-len(eff) // bs)
        ships = sorted((dict(s) for s in ships),
                       key=lambda s: int(s.get("seq", 0)))
        if not committed or not ships:
            self._ship_reject(req, gen, "no shipments for the committed "
                                        "prefix")
            return "fallback"
        pos = 0
        for s in ships:
            if int(s.get("start_block", -1)) != pos:
                pos = -1
                break
            pos = int(s.get("end_block", -1))
        if (pos != n_ship_blocks
                or int(ships[-1].get("length", -1)) != len(eff)):
            self._ship_reject(req, gen, "shipments do not cover the "
                                        "committed prompt contiguously")
            return "fallback"
        # Lane ladder: try the transport's lanes in preference order (mem
        # first when available, then the durable fs artifact). Each lane
        # verifies EVERY shipment under its own contract — mem checks the
        # push-time metadata digest, fs re-runs the CRC walk — before any
        # device write; a non-final lane failing degrades the whole train,
        # never a mixed import.
        lane, fail_detail = None, ""
        for cand in self.transport.lanes:
            ok = True
            for s in ships:
                art = str(s.get("artifact", ""))
                try:
                    manifest = self.transport.verify(art, lane=cand)
                except (KVBlockIntegrityError, OSError) as e:
                    ok = False
                    fail_detail = f"{os.path.basename(art)}: {e}"
                    break
                meta = manifest.get("meta", {})
                s_start = int(s.get("start_block", -1))
                s_end = int(s.get("end_block", -1))
                if (meta.get("kind") != "ship"
                        or str(meta.get("request_id")) != req.id
                        or [int(t) for t in meta.get("prompt", [])] != eff
                        or int(meta.get("seq", -1)) != int(s.get("seq", 0))
                        or int(meta.get("start_block", -1)) != s_start
                        or int(meta.get("end_block", -1)) != s_end
                        or int(manifest.get("length", -1))
                        != int(s.get("length", -1))
                        or len(manifest.get("blocks", []))
                        != s_end - s_start):
                    ok = False
                    fail_detail = (f"{os.path.basename(art)} disagrees "
                                   f"with the journal")
                    break
            if ok:
                lane = cand
                break
            if cand != self.transport.lanes[-1]:
                self.lane_fallbacks += 1
                self._m_lane_fallbacks.inc()
                self._audit_xport("fallback", cand, req.id, len(ships),
                                  fail_detail)
        if lane is None:
            self._ship_reject(req, gen, fail_detail)
            return "fallback"
        # prefix-cache dedupe: shipments whose blocks are already resident
        # (a shared prompt another decode admitted) are skipped, not
        # re-imported — clamped DOWN to a shipment boundary because an
        # artifact imports whole, and to FULL blocks only (the cache never
        # holds the partial final block, which decode will write into)
        n_full = len(eff) // bs
        n_use, hit = 0, None
        if self.prefix_cache is not None and n_full:
            h = self.prefix_cache.match(eff)
            covered = min(h.tokens // bs, n_full) if h.blocks else 0
            if covered:
                n_use = max([int(s["start_block"]) for s in ships
                             if int(s["start_block"]) <= covered] + [0])
            if n_use:
                hit = self.prefix_cache.match(eff[:n_use * bs])
                if hit.blocks and hit.tokens >= n_use * bs:
                    self.prefix_cache.acquire(hit)
                else:
                    hit, n_use = None, 0
        total = self._blocks_needed(req)
        blocks = self.allocator.alloc(total - n_use)
        if blocks is None and self.prefix_cache is not None:
            if self.prefix_cache.evict(
                    (total - n_use) - self.allocator.free_count):
                blocks = self.allocator.alloc(total - n_use)
        if blocks is None and self.enable_spill:
            blocks = self._spill_for(total - n_use, free)
        if blocks is None:
            if hit is not None:
                self.allocator.free(hit.blocks)
            return "wait"
        slot = free[0]
        t0 = self.clock()
        imported = 0
        parts = []
        for s in ships:
            s_start, s_end = int(s["start_block"]), int(s["end_block"])
            if s_end <= n_use:
                continue  # deduped: resident via the prefix cache
            parts.append((str(s["artifact"]),
                          blocks[s_start - n_use:s_end - n_use]))
            imported += s_end - s_start
        try:
            if parts:
                # the whole shipment train lands as ONE scatter per pool
                # array — admission stall stays off the decode-round tail
                try:
                    self.transport.import_batch(self.engine, parts,
                                                lane=lane)
                except KVBlockIntegrityError as e:
                    if lane == "fs":
                        raise
                    # the mem landing failed between verify and scatter:
                    # degrade this train to the durable fs artifacts
                    self.lane_fallbacks += 1
                    self._m_lane_fallbacks.inc()
                    self._audit_xport("fallback", lane, req.id, imported,
                                      str(e))
                    lane = "fs"
                    self.transport.import_batch(self.engine, parts,
                                                lane="fs")
        except KVBlockIntegrityError as e:
            self.allocator.free(blocks)
            if hit is not None:
                self.allocator.free(hit.blocks)
            self._ship_reject(req, gen, str(e))
            return "fallback"
        # all shipments resident: the slot's committed length lands ONCE
        self.engine.set_slot_length(slot, len(eff))
        imp_dur = self.clock() - t0
        self.queue.popleft()
        free.pop(0)
        self._shipments.pop(req.id, None)
        slot_blocks = (list(hit.blocks)[:n_use] if hit is not None
                       else []) + blocks
        row = np.zeros((self.engine.max_blocks_per_slot,), np.int32)
        row[:len(slot_blocks)] = slot_blocks
        self.block_tables[slot] = row
        self._slot_blocks[slot] = slot_blocks
        if self.prefix_cache is not None:
            # the imported row covers the committed prompt — cache it so
            # sibling prompts dedupe against it, exactly as prefill would
            self.prefix_cache.insert(eff, slot_blocks)
            self.prefix_cache.note_admission(n_use * bs, len(eff))
            self._m_prefix_hit_rate.set(self.prefix_cache.hit_rate)
        self._trace(req, "queue", dur=self.clock() - submitted_at,
                    slot=slot)
        self._acquire_adapter(req, slot)
        st = self.active[slot] = _Slot(req, committed[-1], submitted_at,
                                       self.clock())
        self.ship_imports += 1
        self._m_ship_imports.inc(len(ships))
        self._m_handoff_shipped.inc(imported)
        if lane == "mem":
            self.mem_lane_imports += 1
        self._audit_xport("land", lane, req.id, imported,
                          f"{len(ships)} shipment(s), "
                          f"{imp_dur * 1e3:.1f} ms")
        self._audit_ship("import", req.id, int(ships[-1].get("seq", 0)),
                         gen, n_use, n_ship_blocks,
                         f"{imported} imported, {n_use} deduped")
        self._trace(req, "shipment_import", dur=imp_dur,
                    shipments=len(ships), blocks=imported, deduped=n_use)
        self._trace(req, "first_token",
                    ttft=st.first_token_at - st.submitted_at)
        self.max_concurrent = max(self.max_concurrent, len(self.active))
        if (self.eos_token_id is not None
                and st.tokens[-1] == self.eos_token_id):
            self._finish(slot, "eos", done)
        elif len(st.tokens) >= req.max_new_tokens:
            self._finish(slot, "length", done)
        return "imported"

    def _ship_reject(self, req: Request, gen: int, detail: str) -> None:
        self._shipments.pop(req.id, None)
        self.ship_rejects += 1
        self._m_ship_rejected.inc()
        self._audit_ship("reject", req.id, -1, gen, 0, 0, detail)
        logger.warning("Shipment import of request %s rejected (%s); "
                       "falling back to committed-prefix replay", req.id,
                       detail)
        self._trace(req, "ship_reject", detail=detail)

    # --- fleet-global KV store (inference/kvstore.py) -----------------------

    def _audit_store(self, action: str, key: str, rid: str, blocks: int,
                     detail: str) -> None:
        events.emit_audit(logger, AUDIT_KV_STORE_FMT.format(
            action=action, key=key[:12], id=rid, blocks=blocks,
            detail=detail), "kv_store")

    def _maybe_store_fetch(self, req: Request) -> None:
        """Fetch the deepest fleet-store train matching ``req``'s prompt
        into the local prefix cache, when it beats the local hit depth.
        The train lands through the batched verify-before-first-device-
        write import into fresh blocks, is inserted under its content
        address (the cache's own reference keeps the blocks), and the
        normal admission then matches it like any resident prefix. The
        in-flight fetch holds a journaled store refcount so the sweeper
        can never evict the train mid-import; any CRC/metadata reject or
        pool shortage leaves the pool untouched and the request on the
        local-prefill path."""
        bs = self.engine.block_size
        eff = self._effective_prompt(req)
        keys = chain_hashes(eff, bs)
        if not keys:
            return
        store_hit = self.kv_store.match(keys)
        if store_hit is None:
            return
        local = self.prefix_cache.match(eff)
        n = store_hit.depth
        if n <= local.depth:
            return  # the local cache already covers at least as much
        owner = f"fetch-{req.id}"
        self.kv_store.acquire(store_hit.key, owner)
        blocks = self.allocator.alloc(n)
        if blocks is None:
            if self.prefix_cache.evict(n - self.allocator.free_count):
                blocks = self.allocator.alloc(n)
        if blocks is None:
            # pool pressure: not a reject — plain local admission decides
            self.kv_store.release(store_hit.key, owner)
            return
        t0 = self.clock()
        try:
            # lane ladder: mem fabric first when the transport has it,
            # the CRC-verified artifact as the terminal rung
            manifest, lane = None, "fs"
            for cand in self.transport.lanes:
                try:
                    manifest = self.transport.import_batch(
                        self.engine, [(store_hit.art_dir, blocks)],
                        lane=cand,
                        allow_partial=store_hit.partial)[0]
                    lane = cand
                    break
                except (KVBlockIntegrityError, OSError, ValueError) as e:
                    if cand == self.transport.lanes[-1]:
                        raise
                    self.lane_fallbacks += 1
                    self._m_lane_fallbacks.inc()
                    self._audit_xport("fallback", cand, req.id, n, str(e))
            meta = manifest.get("meta", {})
            mkeys = [str(k) for k in meta.get("keys", [])]
            # a partial (sub-train) hit imports a PREFIX of a longer
            # train: the manifest must hold at least n blocks and its
            # per-block chain must agree with the prompt's at depth n
            if (meta.get("kind") != "store"
                    or str(meta.get("key", "")) != store_hit.key
                    or len(manifest.get("blocks", [])) < n
                    or (store_hit.partial
                        and (len(mkeys) < n
                             or mkeys[n - 1] != keys[n - 1].hex()))):
                raise KVBlockIntegrityError(
                    "store train manifest disagrees with its content "
                    "address")
        except (KVBlockIntegrityError, OSError, ValueError) as e:
            self.allocator.free(blocks)
            self.kv_store.release(store_hit.key, owner)
            self.store_rejects += 1
            self._m_store_rejected.inc()
            self._audit_store("reject", store_hit.key, req.id, 0, str(e))
            logger.warning("Fleet-store fetch for request %s rejected "
                           "(%s); falling back to local chunked prefill",
                           req.id, e)
            self._trace(req, "store_reject", key=store_hit.key,
                        detail=str(e))
            return
        dur = self.clock() - t0
        # content-address the imported blocks: insert takes the cache's
        # reference, then this fetch's own allocation reference drops —
        # exactly one holder, the ownership protocol every other resident
        # prefix lives under. Keys the cache already holds keep their
        # canonical block; the duplicate import rows free back to the pool.
        self.prefix_cache.insert(eff[:n * bs], blocks)
        self.allocator.free(blocks)
        self.kv_store.touch(store_hit.key)
        self.kv_store.release(store_hit.key, owner)
        self.store_fetches += 1
        self.store_fetch_blocks += n
        self._m_store_hits.inc()
        self._m_store_fetch_blocks.inc(n)
        self._m_store_hit_depth.observe(n)
        self._m_store_bytes.set(self.kv_store.resident_bytes())
        if lane == "mem":
            self.mem_lane_imports += 1
        if store_hit.partial:
            self.store_partial_hits += 1
            self._m_store_partial.inc()
        self._audit_store(
            "fetch", store_hit.key, req.id, n,
            f"depth {n}"
            + (f" of {store_hit.blocks} (partial)" if store_hit.partial
               else "")
            + f", lane {lane}, {dur * 1e3:.1f} ms")
        self._trace(req, "store_fetch", dur=dur, key=store_hit.key,
                    depth=n, lane=lane, partial=store_hit.partial,
                    prompt_tokens=len(eff))

    def _maybe_store_publish(self, req: Request, eff: Sequence[int],
                             slot_blocks: Sequence[int]) -> None:
        """Publish the just-committed prompt's full prefix blocks as one
        content-addressed train. Dedup is free: identical prefixes hash
        identically, so a key any host already published skips the export
        outright — which also makes a fetched-then-reinserted prefix a
        no-op here."""
        if self.kv_store is None:
            return
        bs = self.engine.block_size
        keys = chain_hashes(eff, bs)
        if not keys or self.kv_store.has(keys[-1].hex()):
            return
        n = len(keys)
        if (self.kv_store_max_bytes
                and self.kv_store.resident_bytes()
                > self.kv_store_max_bytes):
            # byte-budget backpressure: the sweeper daemon owns getting
            # resident bytes back under budget; publishers just stop
            # adding to the pile (and say so) until it does
            self.store_publish_skipped += 1
            self._m_store_skipped.inc()
            self._audit_store("skip", keys[-1].hex(), req.id, n,
                              "resident bytes over budget")
            return
        t0 = self.clock()
        manifest = self.kv_store.publish(
            self.engine.cache, keys, list(slot_blocks[:n]),
            length=n * bs, meta={"request_id": req.id},
            on_put=self._on_store_put, transport=self.transport)
        if manifest is None:
            return
        dur = self.clock() - t0
        nbytes = artifact_bytes(manifest)
        key = keys[-1].hex()
        self.store_publishes += 1
        self._m_store_publishes.inc()
        self._m_store_bytes.set(self.kv_store.resident_bytes())
        self._audit_store("publish", key, req.id, n, f"{nbytes} byte(s)")
        self._trace(req, "store_publish", dur=dur, key=key, blocks=n,
                    bytes=nbytes)

    def _abort_pending_prefill(self) -> None:
        """Drain landed while packed rows were mid-prompt: free every
        pending row's blocks exactly once (fresh, COW and acquired shared
        references alike — shared blocks survive under the cache's own
        reference), requeue the requests at the head in admission order so
        they are REPORTED unserved, and close admission — the sequential
        lane's mid-chunk drain contract, at round granularity."""
        for p in reversed(self._pending_prefill):
            self.allocator.free(p.blocks)
            self.block_tables[p.slot] = 0
            self._ship_state.pop(p.request.id, None)
            self._release_adapter(p.slot)
            self.queue.appendleft((p.request, p.submitted_at))
        self._pending_prefill.clear()
        self.stop_admission()

    def _finish_prefill(self, p: _PendingPrefill, first: int,
                        done: List[Completion]) -> None:
        """A packed row's FINAL chunk landed: the round's sampled token is
        its first generated token — promote the row to an active decode
        slot (everything the sequential lane does after engine.prefill
        returns, including the straight-out-of-prefill finish checks)."""
        self._slot_blocks[p.slot] = p.blocks
        if self.prefix_cache is not None:
            self.prefix_cache.insert(p.eff, p.blocks)
            self.prefix_cache.note_admission(p.start_pos, len(p.eff))
            self._m_prefix_hit_rate.set(self.prefix_cache.hit_rate)
            self._maybe_store_publish(p.request, p.eff, p.blocks)
        self._check_replay(p.request, first)
        st = self.active[p.slot] = _Slot(p.request, first, p.submitted_at,
                                         self.clock())
        self._trace(p.request, "prefill", prompt_tokens=len(p.eff),
                    packed=True,
                    replayed=len(list(p.request.committed or ())))
        self._trace(p.request, "first_token",
                    ttft=st.first_token_at - st.submitted_at)
        self.max_concurrent = max(self.max_concurrent, len(self.active))
        self._m_tokens.inc()  # the prefill's first token
        if self.role == "prefill":
            # same prefill-engine contract as the sequential lane
            self._finish(p.slot, "prefill", done)
            return
        if (self.eos_token_id is not None
                and st.tokens[-1] == self.eos_token_id):
            self._finish(p.slot, "eos", done)
        elif len(st.tokens) >= p.request.max_new_tokens:
            self._finish(p.slot, "length", done)

    def _prefill_round(self, done: List[Completion]) -> None:
        """ONE packed prefill round: walk the pending rows in admission
        order, take up to ``prefill_batch`` whose next chunk best-fits the
        HEAD row's bucket (each row computes its chunk with exactly the
        sequential ``_stream_chunks`` discipline — largest bucket while
        the remainder exceeds it, best-fit on the final chunk — which is
        what keeps per-row chunk shapes, and so the streams on the gather
        impl, bit-identical to sequential prefill), and dispatch them in
        one (P, bucket) program. Rows needing a different bucket stay
        pending for a later round. The drain probe runs at round
        boundaries, the packed analogue of the sequential lane's
        between-chunk ``stop_check``."""
        if self._drain_requested():
            self._abort_pending_prefill()
            return
        chunk = self.engine.prefill_buckets[-1]
        head_bucket = None
        batch: List = []  # (row, chunk_len) pairs this round
        for p in self._pending_prefill:
            m = min(chunk, len(p.eff) - p.pos)
            bucket = next(b for b in self.engine.prefill_buckets if b >= m)
            if head_bucket is None:
                head_bucket = bucket
            if bucket != head_bucket:
                continue
            batch.append((p, m))
            if len(batch) == self.prefill_batch:
                break
        rows = [(p.slot,
                 np.asarray(p.eff[p.pos:p.pos + m], np.int32),
                 p.pos, p.row, p.request.temperature, p.request.top_p,
                 p.request.seed) for p, m in batch]
        packed_kw = {}
        if self.adapters is not None:
            # each packed row gathers ITS slot's adapter pages — one
            # dispatch across rows with different adapters
            packed_kw = dict(
                adapter_rows=[self._adapter_rows[p.slot] for p, _ in batch],
                adapter_scales=[float(self._adapter_scales[p.slot])
                                for p, _ in batch])
        t0 = self.clock()
        toks = self.engine.prefill_packed(rows, head_bucket, **packed_kw)
        self.prefill_seconds += self.clock() - t0
        self.prefill_packed_rounds += 1
        self.prefill_packed_rows += len(rows)
        self._m_prefill_batch.observe(len(rows))
        for (p, m), tok in zip(batch, toks):
            self._count_chunk()
            p.pos += m
            if self.role == "prefill":
                # packed analogue of the sequential lane's per-chunk ship
                self._ship_commit(p.request, p.blocks, p.eff, p.pos)
            if p.pos >= len(p.eff):
                self._pending_prefill.remove(p)
                self._finish_prefill(p, tok, done)

    def _sync_adapter_metrics(self) -> None:
        """Mirror the AdapterManager's counters onto the /metrics surface
        (the manager counts monotonically; the registry counters advance
        by the delta since the last sync)."""
        mgr = self.adapters
        self._m_adapter_pageins.inc(mgr.pageins - self._adapter_pageins_seen)
        self._adapter_pageins_seen = mgr.pageins
        self._m_adapter_evictions.inc(
            mgr.evictions - self._adapter_evictions_seen)
        self._adapter_evictions_seen = mgr.evictions
        self._m_adapter_resident_bytes.set(mgr.resident_bytes())
        counts = mgr.active_slots()
        for name in mgr.served:
            self._m_adapter_slots.labels(adapter=name).set(
                counts.get(name, 0))

    def step(self) -> List[Completion]:
        """Admit into free slots, run one decode iteration, evict finished
        requests. Returns the completions produced by this iteration.
        In the packed-prefill lane, one packed chunk round runs before the
        decode round, so admitted prompts and active decodes interleave
        instead of prefill draining the queue first."""
        done: List[Completion] = []
        if self.admission_open:
            self._admit(done)
        if self._pending_prefill:
            self._prefill_round(done)
        self._m_queue.set(len(self.queue))
        self._m_occupancy.set(len(self.active) / max(self.engine.slots, 1))
        if self.kv_layout == "paged":
            self._m_blocks_free.set(self.allocator.free_count)
            self._m_blocks_total.set(self.allocator.capacity)
            util = self.allocator.used_count / max(self.allocator.capacity, 1)
            self._m_block_util.set(util)
            self.max_block_utilization = max(self.max_block_utilization, util)
            self._m_blocks_shared.set(self.allocator.shared_count)
        if self.adapters is not None:
            self._sync_adapter_metrics()
        if not self.active:
            return done
        slots = self.engine.slots
        tokens = np.zeros((slots,), np.int32)
        active = np.zeros((slots,), bool)
        temperature = np.zeros((slots,), np.float32)
        top_p = np.ones((slots,), np.float32)
        seeds = np.zeros((slots,), np.int32)
        steps = np.zeros((slots,), np.int32)
        for s, st in self.active.items():
            tokens[s] = st.tokens[-1]
            active[s] = True
            temperature[s] = st.request.temperature
            top_p[s] = st.request.top_p
            seeds[s] = st.request.seed
            steps[s] = st.steps
        t0 = self.clock()
        burst_out = None
        if self.spec_k:
            # Speculative round: lengths[s] is the slot's committed KV
            # count (prompt + emitted − 1 positions hold keys; the latest
            # emitted token is the round's input and is written by the
            # draft/verify programs themselves). steps doubles as the
            # round counter that derives the per-round PRNG streams.
            lengths = np.zeros((slots,), np.int32)
            for s, st in self.active.items():
                lengths[s] = len(st.request.prompt) + len(st.tokens) - 1
            round_k = self.spec_k
            if self.adaptive_k is not None:
                round_k = self.adaptive_k.round_k(
                    st.request.id for st in self.active.values())
            self._m_spec_round_k.set(round_k)
            if self.spec_tree is not None:
                # TREE round: the adaptive budget maps to a deterministic
                # sub-shape of the configured tree; the refeed window
                # carries each slot's previously banked tokens (bonus
                # last) so the draft rewrites their KV before proposing.
                tree_shape = (self.spec_tree if self.adaptive_k is None
                              else self.spec_tree.shrink_to(round_k))
                r_w = self.engine._tree_refeed
                refeed = np.zeros((slots, r_w), np.int32)
                refeed_len = np.ones((slots,), np.int32)
                for s, st in self.active.items():
                    em = st.emitted[-r_w:]
                    refeed[s, :len(em)] = em
                    refeed_len[s] = len(em)
                out, acc, path = self.engine.spec_tree_round(
                    refeed, refeed_len, lengths, active, temperature,
                    top_p, seeds, steps, block_tables=self.block_tables,
                    draft_block_tables=self.draft_block_tables,
                    shape=tree_shape)
            else:
                spec_kw = {}
                if self.adaptive_k is not None:
                    # only ladder-aware engines take the width kwarg —
                    # test doubles built before adaptive-k keep the old
                    # signature
                    spec_kw["k"] = round_k
                out, acc = self.engine.spec_round(
                    tokens, lengths, active, temperature, top_p, seeds,
                    steps, block_tables=self.block_tables,
                    draft_block_tables=self.draft_block_tables, **spec_kw)
            self.decode_dispatches += 2  # draft + verify programs
            self.decode_host_syncs += 1  # one result sync per round
            self._m_dispatches.inc(2)
            self._m_host_syncs.inc()
        elif self.kv_layout == "paged" and self.decode_burst > 1:
            # One n-token burst program: clamp n to the tightest remaining
            # budget so KV writes never walk past a slot's allocated
            # blocks (admission sized them for prompt + max_new_tokens);
            # EOS overshoot inside the burst is truncated at banking.
            n = self.decode_burst
            if self.adaptive_burst:
                # halve per unit of admission pressure so a burst never
                # walls off the queue; the budget clamp below is unchanged
                pressure = len(self.queue) + len(self._pending_prefill)
                if pressure:
                    n = max(1, n // (1 + pressure))
            for st in self.active.values():
                n = min(n, st.request.max_new_tokens - len(st.tokens))
            n = max(int(n), 1)
            ad_kw = ({} if self.adapters is None else dict(
                adapter_rows=self._adapter_rows,
                adapter_scales=self._adapter_scales))
            burst_out = self.engine.decode_burst(
                tokens, active, temperature, top_p, seeds, steps, n,
                block_tables=self.block_tables, **ad_kw)
            self.decode_dispatches += 1
            self.decode_host_syncs += 1
            self._m_dispatches.inc()
            self._m_host_syncs.inc()
        elif self.kv_layout == "paged":
            ad_kw = ({} if self.adapters is None else dict(
                adapter_rows=self._adapter_rows,
                adapter_scales=self._adapter_scales))
            next_tokens = self.engine.decode_step(
                tokens, active, temperature, top_p, seeds, steps,
                block_tables=self.block_tables, **ad_kw)
            self.decode_dispatches += 1
            self.decode_host_syncs += 1
            self._m_dispatches.inc()
            self._m_host_syncs.inc()
        else:
            next_tokens = self.engine.decode_step(tokens, active, temperature,
                                                  top_p, seeds, steps)
            self.decode_dispatches += 1
            self.decode_host_syncs += 1
            self._m_dispatches.inc()
            self._m_host_syncs.inc()
        step_wall = self.clock() - t0
        self.step_seconds.append(step_wall)
        self._m_decode.observe(step_wall)
        wall = sum(self.step_seconds)
        if wall > 0:
            self._m_tps.set(self._m_tokens.value / wall)
        self.iterations += 1
        if self.spec_k:
            if self.spec_tree is not None:
                self._bank_tree(out, acc, path, tree_shape, done)
            else:
                self._bank_spec(out, acc, done, k=round_k)
            return done
        if burst_out is not None:
            self._bank_burst(burst_out, done)
            return done
        for s in list(self.active):
            st = self.active[s]
            tok = int(next_tokens[s])
            st.tokens.append(tok)
            st.steps += 1
            self.decode_tokens += 1
            self._m_tokens.inc()
            self._m_burst_tokens.observe(1)
            self._trace(st.request, "decode_round", tokens=1, mode="token")
            if self.eos_token_id is not None and tok == self.eos_token_id:
                self._finish(s, "eos", done)
            elif len(st.tokens) >= st.request.max_new_tokens:
                self._finish(s, "length", done)
        return done

    def _bank_burst(self, out: np.ndarray, done: List[Completion]) -> None:
        """Bank one fused burst's (slots, n) tokens, truncating each slot
        at EOS and at its max_new_tokens budget — discarded overshoot is
        tokens the sequential path would never have produced, so the
        emitted stream stays identical to per-token decode (the same
        truncation contract as ``_bank_spec``; the device's overshoot KV
        is stale pool content past the committed length, masked and
        overwritten by the slot's next occupant)."""
        n = out.shape[1]
        for s in list(self.active):
            st = self.active[s]
            banked = 0
            finished = None
            for i in range(n):
                tok = int(out[s, i])
                st.tokens.append(tok)
                st.steps += 1
                banked += 1
                self._m_tokens.inc()
                if (self.eos_token_id is not None
                        and tok == self.eos_token_id):
                    finished = "eos"
                    break
                if len(st.tokens) >= st.request.max_new_tokens:
                    finished = "length"
                    break
            self.decode_tokens += banked
            self._m_burst_tokens.observe(banked)
            self._trace(st.request, "decode_round", tokens=banked,
                        mode="burst")
            if finished:
                self._finish(s, finished, done)

    def _bank_spec(self, out: np.ndarray, acc: np.ndarray,
                   done: List[Completion], k: Optional[int] = None) -> None:
        """Bank one verify round's output: the accepted draft prefix plus
        the bonus/corrected token at position acc, truncated by EOS and by
        the request's max_new_tokens budget (truncation discards tokens the
        non-spec path would never have produced, keeping the emitted stream
        identical to sequential decoding). ``k`` is the round's actual
        width (adaptive-k may run below spec_k; accounting follows it)."""
        k = self.spec_k if k is None else int(k)
        self.spec_rounds += 1
        n_active = len(self.active)
        self.spec_draft_tokens += k * n_active
        self._m_spec_draft.inc(k * n_active)
        round_accepted = 0
        for s in list(self.active):
            st = self.active[s]
            a = int(acc[s])
            st.steps += 1
            st.spec_proposed += k
            st.spec_accepted += a
            round_accepted += a
            if self.adaptive_k is not None:
                self.adaptive_k.observe(st.request.id, a, k)
            banked = 0
            finished = None
            for i in range(a + 1):
                tok = int(out[s, i])
                st.tokens.append(tok)
                banked += 1
                self._m_tokens.inc()
                if i == a:
                    # position acc is the verifier's own token (bonus on
                    # full accept, correction otherwise) — emitted without
                    # ever having been proposed by the draft.
                    st.spec_corrected += 1
                if self.eos_token_id is not None and tok == self.eos_token_id:
                    finished = "eos"
                    break
                if len(st.tokens) >= st.request.max_new_tokens:
                    finished = "length"
                    break
            self.decode_tokens += banked
            self._m_spec_round_tokens.observe(banked)
            self._m_burst_tokens.observe(banked)
            self._trace(st.request, "decode_round", tokens=banked,
                        mode="spec", accepted=a)
            if finished:
                self._finish(s, finished, done)
        self.spec_accepted_tokens += round_accepted
        self._m_spec_accepted.inc(round_accepted)
        if self.spec_draft_tokens:
            self._m_spec_rate.set(
                self.spec_accepted_tokens / self.spec_draft_tokens)

    def _bank_tree(self, out: np.ndarray, acc: np.ndarray, path: np.ndarray,
                   shape, done: List[Completion]) -> None:
        """Bank one TREE round under ``_bank_spec``'s truncation contract.
        The round proposed ``sum(fanouts)`` draft tokens (the tree minus
        its root) and scored ``shape.size`` nodes in one verify dispatch;
        ``path[s, :acc[s]]`` names the accepted nodes' tree rows, which is
        what attributes acceptance to branches — a row off
        ``shape.primary_rows`` is a token linear speculation would have
        thrown away with the rejected suffix. The banked tokens become the
        slot's refeed window for the next round."""
        budget = shape.size - 1
        self.spec_rounds += 1
        self.spec_tree_rounds += 1
        n_active = len(self.active)
        self.spec_draft_tokens += budget * n_active
        self._m_spec_draft.inc(budget * n_active)
        self.spec_tree_nodes += shape.size * n_active
        self._m_tree_nodes.inc(shape.size * n_active)
        primary = shape.primary_rows
        round_accepted = 0
        for s in list(self.active):
            st = self.active[s]
            a = int(acc[s])
            st.steps += 1
            st.spec_proposed += budget
            st.spec_accepted += a
            round_accepted += a
            self._m_tree_path_len.observe(a)
            self.spec_tree_accepted += a
            self.spec_tree_off_primary += sum(
                1 for j in range(a) if int(path[s, j]) != primary[j])
            if self.adaptive_k is not None:
                self.adaptive_k.observe(st.request.id, a, shape.depth)
            banked = 0
            finished = None
            emitted: List[int] = []
            for i in range(a + 1):
                tok = int(out[s, i])
                st.tokens.append(tok)
                emitted.append(tok)
                banked += 1
                self._m_tokens.inc()
                if i == a:
                    # the verifier's own token (bonus or correction) —
                    # emitted without ever having been proposed
                    st.spec_corrected += 1
                if self.eos_token_id is not None and tok == self.eos_token_id:
                    finished = "eos"
                    break
                if len(st.tokens) >= st.request.max_new_tokens:
                    finished = "length"
                    break
            st.emitted = emitted
            self.decode_tokens += banked
            self._m_spec_round_tokens.observe(banked)
            self._m_burst_tokens.observe(banked)
            self._trace(st.request, "decode_round", tokens=banked,
                        mode="tree", accepted=a)
            if finished:
                self._finish(s, finished, done)
        self.spec_accepted_tokens += round_accepted
        self._m_spec_accepted.inc(round_accepted)
        if self.spec_draft_tokens:
            self._m_spec_rate.set(
                self.spec_accepted_tokens / self.spec_draft_tokens)
        if self.spec_tree_accepted:
            self._m_tree_branch_util.set(
                self.spec_tree_off_primary / self.spec_tree_accepted)

    def run(self, stop: Optional[Callable[[], bool]] = None
            ) -> List[Completion]:
        """Drive until idle; ``stop()`` returning True switches to drain
        mode (finish active, leave the queue). Returns all completions."""
        if stop is not None and self.stop_check is None:
            self.stop_check = stop  # also probed between prefill chunks
        while self.pending():
            if stop is not None and self.admission_open and stop():
                self.stop_admission()
            self.step()
        # drain/idle contract: every block is free or cache-held — a leak
        # here is a refcount bug, turned into a hard failure (tests drive
        # run(); serve.py audits non-strict to keep its exit-0 contract)
        self.audit_block_leaks(strict=True)
        return self.completed

    def audit_block_leaks(self, strict: bool = True) -> List[str]:
        """Allocator leak guard for the drained/idle state (no active
        slots): every block in EITHER pool must be either free or held
        solely by its pool's prefix cache (exactly one reference — the
        draft pool runs the mirror cache, module docstring). Violations
        are audited ONCE (``[KV LEAK]``) through the flight recorder and,
        in strict mode, raised. Returns the violation descriptions."""
        if self.kv_layout != "paged" or self.active or self._pending_prefill:
            return []
        leaks: List[str] = []
        cached = (self.prefix_cache.cached_blocks
                  if self.prefix_cache is not None else 0)
        extra = self.allocator.used_count - cached
        if extra != 0 or self.allocator.shared_count or self._slot_blocks:
            leaks.append(AUDIT_KV_LEAK_FMT.format(
                pool="target", leaked=extra,
                used=self.allocator.used_count, cached=cached))
        if self.spec_k:
            dcached = (self.draft_prefix_cache.cached_blocks
                       if self.draft_prefix_cache is not None else 0)
            dextra = self.draft_allocator.used_count - dcached
            if (dextra != 0 or self.draft_allocator.shared_count
                    or self._slot_draft_blocks):
                leaks.append(AUDIT_KV_LEAK_FMT.format(
                    pool="draft", leaked=dextra,
                    used=self.draft_allocator.used_count, cached=dcached))
        if self.adapters is not None:
            # adapter-pool half of the guard: with no active slots every
            # allocated adapter page belongs to a resident (or stale
            # in-swap) record holding exactly its base reference — any
            # surplus is a slot pin that never released
            aused = self.adapters.allocator.used_count
            aresident = self.adapters.resident_pages()
            if (aused != aresident or self.adapters.allocator.shared_count
                    or self._slot_adapter):
                leaks.append(AUDIT_KV_LEAK_FMT.format(
                    pool="adapter", leaked=aused - aresident,
                    used=aused, cached=aresident))
        if self.enable_spill and self._spill_root is not None:
            # cross-tier half of the guard: every parked request must have
            # an intact artifact (manifest present), and every artifact
            # directory in the tier must belong to a parked request —
            # device pool + spill tier + cache-held = accounted
            tracked = {sp.artifact_dir for sp in self._spilled.values()}
            missing = [d for d in sorted(tracked) if not os.path.isfile(
                os.path.join(d, BLOCK_MANIFEST_NAME))]
            try:
                on_disk = {os.path.join(self._spill_root, name)
                           for name in os.listdir(self._spill_root)
                           if os.path.isdir(
                               os.path.join(self._spill_root, name))}
            except OSError:
                on_disk = set()
            orphans = sorted(on_disk - tracked)
            if missing or orphans:
                leaks.append(AUDIT_KV_LEAK_FMT.format(
                    pool="spill", leaked=len(missing) + len(orphans),
                    used=len(self._spilled), cached=0))
        if leaks and not self._leak_audited:
            self._leak_audited = True
            for text in leaks:
                events.emit_audit(logger, text, "kv_leak")
        if leaks and strict:
            raise RuntimeError("KV block leak after drain: "
                               + "; ".join(leaks))
        return leaks

    # --- aggregate metrics -------------------------------------------------

    def metrics(self) -> dict:
        lat = np.asarray(self.step_seconds or [0.0])
        generated = sum(len(c.tokens) for c in self.completed) + sum(
            len(st.tokens) for st in self.active.values())
        wall = float(lat.sum())
        tps = generated / wall if wall > 0 else 0.0
        self._m_tps.set(tps)
        out = {
            "iterations": self.iterations,
            "requests_completed": len(self.completed),
            "tokens_generated": int(generated),
            "max_concurrent": self.max_concurrent,
            "decode_p50_ms": float(np.percentile(lat, 50) * 1e3),
            "decode_p95_ms": float(np.percentile(lat, 95) * 1e3),
            "tokens_per_sec": tps,
            "tokens_per_sec_per_slot": tps / max(self.engine.slots, 1),
            "prefill_chunks": self.prefill_chunks,
            "prefill_seconds": self.prefill_seconds,
            "prefill_batch": self.prefill_batch,
            "prefill_packed_rounds": self.prefill_packed_rounds,
            "prefill_packed_rows": self.prefill_packed_rows,
            "prefill_packed_occupancy": (
                self.prefill_packed_rows
                / (self.prefill_packed_rounds * self.prefill_batch)
                if self.prefill_packed_rounds else 0.0),
            "prefill_inplace_chunks": self.prefill_inplace_chunks,
            "prefill_gather_chunks": self.prefill_gather_chunks,
            "decode_burst": self.decode_burst,
            "adaptive_burst": self.adaptive_burst,
            "decode_dispatches": self.decode_dispatches,
            "decode_host_syncs": self.decode_host_syncs,
            "decode_tokens": self.decode_tokens,
            "dispatches_per_token": (
                self.decode_dispatches / self.decode_tokens
                if self.decode_tokens else 0.0),
            "host_syncs_per_token": (
                self.decode_host_syncs / self.decode_tokens
                if self.decode_tokens else 0.0),
        }
        ttfts = [c.ttft_seconds for c in self.completed]
        tpots = [c.tpot_seconds for c in self.completed
                 if len(c.tokens) > 1]
        for name, vals in (("ttft", ttfts), ("tpot", tpots)):
            arr = np.asarray(vals or [0.0])
            for q in (50, 95, 99):
                out[f"{name}_p{q}_ms"] = float(
                    np.percentile(arr, q) * 1e3)
        out["engine_role"] = self.role
        if self.role != "both" or self.ship_exports or self.ship_imports \
                or self.ship_rejects:
            out["ship_exports"] = self.ship_exports
            out["ship_imports"] = self.ship_imports
            out["ship_rejects"] = self.ship_rejects
        if self.kv_store is not None or self.store_publishes \
                or self.store_fetches or self.store_rejects:
            out["kv_store_publishes"] = self.store_publishes
            out["kv_store_fetches"] = self.store_fetches
            out["kv_store_fetch_blocks"] = self.store_fetch_blocks
            out["kv_store_rejects"] = self.store_rejects
            out["kv_store_partial_hits"] = self.store_partial_hits
            out["kv_store_publish_skipped"] = self.store_publish_skipped
        out["kv_transport_lane"] = self.transport.name
        out["kv_transport_bytes"] = dict(self.transport.lane_bytes)
        out["kv_transport_land_seconds"] = dict(
            self.transport.land_seconds)
        if self.transport.name == "mem" or self.lane_fallbacks:
            out["kv_transport_mem_imports"] = self.mem_lane_imports
            out["kv_transport_lane_fallbacks"] = self.lane_fallbacks
        if self.pacing is not None or self.prefill_paced:
            out["prefill_paced"] = self.prefill_paced
        if self.adapters is not None:
            ast = self.adapters.stats()
            out["adapters_served"] = ast["served"]
            out["adapters_resident"] = list(ast["resident"])
            out["adapter_pages_resident"] = ast["resident_pages"]
            out["adapter_pages_resident_bytes"] = ast["resident_bytes"]
            out["adapter_pageins"] = ast["pageins"]
            out["adapter_evictions"] = ast["evictions"]
            out["adapter_pool_pages_free"] = ast["free_pages"]
            out["adapter_stale_versions"] = ast["stale_versions"]
            out["adapter_waits"] = self.adapter_waits
            out["adapter_rejects"] = self.adapter_rejects
        if self.kv_layout == "paged":
            out["kv_blocks_total"] = self.allocator.capacity
            out["kv_blocks_free"] = self.allocator.free_count
            out["kv_block_utilization_peak"] = self.max_block_utilization
            # storage-dtype surface (--kv-dtype): what a block costs in
            # the selected layout — the bench's blocks-per-byte-budget
            # numbers read straight off these
            out["kv_dtype"] = getattr(self.engine, "kv_dtype", "bf16")
            cache = getattr(self.engine, "cache", None)
            out["kv_bytes_per_block"] = (
                block_bytes(cache) if cache is not None else 0)
            if self.prefix_cache is not None:
                pc = self.prefix_cache
                out["prefix_lookups"] = pc.lookups
                out["prefix_hits"] = pc.hits
                out["prefix_hit_tokens"] = pc.hit_tokens
                out["prefix_hit_rate"] = pc.hit_rate
                out["prefix_cached_blocks"] = pc.cached_blocks
                out["prefix_evictions"] = pc.evictions
                out["prefix_cow_copies"] = pc.cow_copies
                out["kv_blocks_shared"] = self.allocator.shared_count
        if self.spec_k:
            out["spec_k"] = self.spec_k
            out["spec_rounds"] = self.spec_rounds
            out["spec_draft_tokens"] = self.spec_draft_tokens
            out["spec_accepted_tokens"] = self.spec_accepted_tokens
            out["spec_acceptance_rate"] = (
                self.spec_accepted_tokens / self.spec_draft_tokens
                if self.spec_draft_tokens else 0.0)
            out["draft_kv_blocks_total"] = self.draft_allocator.capacity
            out["draft_kv_blocks_free"] = self.draft_allocator.free_count
            if self.draft_prefix_cache is not None:
                dpc = self.draft_prefix_cache
                out["draft_prefix_hits"] = dpc.hits
                out["draft_prefix_hit_tokens"] = dpc.hit_tokens
                out["draft_prefix_hit_rate"] = dpc.hit_rate
                out["draft_prefix_cached_blocks"] = dpc.cached_blocks
            if self.spec_tree is not None:
                out["spec_tree"] = ",".join(
                    str(f) for f in self.spec_tree.fanouts)
                out["spec_tree_rounds"] = self.spec_tree_rounds
                out["spec_tree_nodes"] = self.spec_tree_nodes
                out["spec_tree_accepted_off_primary"] = (
                    self.spec_tree_off_primary)
                out["spec_tree_branch_utilization"] = (
                    self.spec_tree_off_primary / self.spec_tree_accepted
                    if self.spec_tree_accepted else 0.0)
                out["spec_accepted_per_round"] = (
                    self.spec_accepted_tokens / self.spec_rounds
                    if self.spec_rounds else 0.0)
        return out
