"""Declarative fault-schedule grammar.

One schedule entry names one fault at one trigger::

    step=<N>:<fault>[=<arg>][@rank=<R>]     fire at global step N
    t=<DUR>:<fault>[=<arg>][@rank=<R>]      fire once DUR has elapsed since
                                            the injector was built
    p=<PROB>:<fault>[=<arg>][@rank=<R>]     fire with probability PROB at
                                            each injection-site visit
                                            (seeded; 0 < PROB <= 1)

entries separated by ``;``. Examples:

    --chaos "step=50:sigusr1"
    --chaos "step=80:exception@rank=1"
    --chaos "step=120:ckpt_corrupt;step=140:loader_stall=5s"
    --chaos "t=30s:sigterm"
    --chaos "p=0.1:kv_delay=250ms"

Every entry — whatever its trigger — fires at most ONCE per process
(``ChaosEntry.fired`` latches), so a ``p=`` entry is "at a seeded-random
step", not a persistent failure rate.

``--chaos`` also accepts a JSON file path (detected by an existing file or
an ``@`` prefix) holding a list of ``{"step": N, "fault": "...",
"arg": ..., "rank": ...}`` objects — the form campaign runners generate.

Fault classes (each hooks a different layer — chaos/injector.py):

==============  ============================================================
sigusr1         deliver a real SIGUSR1 to this process (the Slurm
                pre-timeout warning; exercises ft/signals.py + the
                save-and-resubmit exit policy)
sigterm         deliver a real SIGTERM (scancel; the no-save policy)
exception       raise the reference's simulated training error at the
                injection site in training/loop.py (``--raise-error`` is a
                thin alias for one of these entries)
ckpt_corrupt    raise a training error AND, after the exit handler's fault
                checkpoint commits, flip bytes in its newest step dir —
                the resume must detect it (integrity manifest,
                checkpoint/manager.py) and fall back to the previous
                passing checkpoint
loader_stall    sleep the data-prefetch worker before handing over the
                batch for the given step (arg = duration, default 2s)
kv_delay        sleep at a signal-sync boundary, simulating a slow
                multihost KV agreement round (arg = duration, default 1s)
kv_fail         raise PeerHostError at a sync boundary, simulating a
                failed agreement round / lost peer
publish_corrupt flip bytes in a just-published checkpoint's files AFTER
                the ``published.json`` pointer commits (deploy/publish.py)
                — the serving watcher's verify-before-load must reject the
                publish and keep serving on current weights
reload_signal   deliver a real SIGUSR1 in the middle of a hot weight swap
                (deploy/reload.py), keyed by reload ordinal (1 = first
                reload) — the swap must complete and the drain then run
                on the NEW weights
host_kill       SIGKILL this serving-fleet host mid-decode (keyed by fleet
                loop iteration) — no handler runs, no drain: the router's
                lease sweep must declare it dead and migrate its in-flight
                requests onto survivors (inference/fleet.py)
heartbeat_delay sleep inside the fleet host's lease-renewal path (arg =
                duration, default 2s) — a slow-but-alive host: shorter
                than the ttl it must NOT trip the dead verdict; longer, it
                must self-fence rather than double-commit
handoff_corrupt flip a payload byte in a just-exported block-shipment
                artifact (inference/kv_cache.py), keyed by export ordinal
                (0 = first handoff export) — the router/survivor CRC
                verify must reject the artifact and the migration must
                degrade to committed-prefix replay with nothing lost
spill_corrupt   flip a payload byte in a just-written KV spill artifact,
                keyed by spill ordinal — the restore's CRC verify must
                reject it and fall back to a replay re-admission
prefill_kill    SIGKILL a prefill-role fleet host between prefill chunk
                commits (keyed by completed-chunk ordinal, 0 = after the
                first chunk) — no drain, shipments stop mid-prompt: the
                router must re-prefill the request on a peer and the
                dead host's partial shipments must never be imported
ship_corrupt    flip a payload byte in the Nth block shipment a prefill
                host exports (keyed by ship ordinal, manifest spared) —
                the router's verify must CRC-reject exactly that shipment
                and hand the request to decode as a committed-prefix
                replay instead
store_corrupt   flip a payload byte in the Nth prefix train this host
                publishes to the fleet-global KV store
                (inference/kvstore.py, keyed by publish ordinal, manifest
                spared) — a fetching host's verify-before-import must
                CRC-reject exactly that train and degrade to local
                chunked prefill with nothing lost
mem_corrupt     poison the Nth block train pushed onto the in-memory KV
                transport lane (inference/transport.py, keyed by push
                ordinal): mutate the fabric-resident manifest METADATA
                without refreshing its push-time digest — the importer's
                mem-lane verify must catch the digest disagreement and
                degrade that train to the fs artifact (and, if that is
                also corrupt, to committed-prefix replay) with nothing
                lost; the on-disk artifact is untouched
==============  ============================================================

Steps are *global* training steps, so an entry in the past at resume time
never re-fires: a resumed job naturally continues clean. Durations accept
``5s``, ``250ms`` or a bare float (seconds).
"""

import dataclasses
import json
import os
import re
from typing import List, Optional, Sequence

# arg = None: no argument allowed; float: required/defaulted duration (s)
FAULTS = {
    "sigusr1": None,
    "sigterm": None,
    "exception": None,
    "ckpt_corrupt": None,
    "loader_stall": 2.0,
    "kv_delay": 1.0,
    "kv_fail": None,
    "publish_corrupt": None,
    "reload_signal": None,
    "host_kill": None,
    "heartbeat_delay": 2.0,
    "handoff_corrupt": None,
    "spill_corrupt": None,
    "prefill_kill": None,
    "ship_corrupt": None,
    "store_corrupt": None,
    "mem_corrupt": None,
}

# The serving loop has no training steps, prefetcher or KV agreement: only
# the signal faults (a mid-decode drain), the mid-swap reload signal and
# the spill-tier corruption make sense there.
SERVE_FAULTS = ("sigusr1", "sigterm", "reload_signal", "spill_corrupt")

# A fleet host adds the membership faults; "one rank" is expressed by
# giving only that host's process the entry (each host is a separate OS
# process with its own schedule, so @rank= is unnecessary there).
FLEET_FAULTS = ("sigusr1", "sigterm", "host_kill", "heartbeat_delay",
                "handoff_corrupt", "spill_corrupt", "prefill_kill",
                "ship_corrupt", "store_corrupt", "mem_corrupt")

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)?$")
_ENTRY_RE = re.compile(
    r"^(?P<trigger>step|t|p)=(?P<when>[^:]+):(?P<fault>[a-z_0-9]+)"
    r"(?:=(?P<arg>[^@]+))?(?:@rank=(?P<rank>-?\d+))?$")


@dataclasses.dataclass
class ChaosEntry:
    """One scheduled injection. ``fired`` latches after the injector acts:
    every entry fires exactly once per process lifetime. ``trigger``
    selects when: ``"step"`` compares the injection site's step to
    ``step``; ``"time"`` fires once ``when`` seconds have elapsed since
    the injector was built; ``"prob"`` fires with per-visit probability
    ``when`` from the injector's seeded rng."""

    step: int
    fault: str
    arg: Optional[float] = None  # seconds, for duration faults
    rank: int = -1  # -1 = every process; >=0 = that process index only
    fired: bool = False
    trigger: str = "step"  # "step" | "time" | "prob"
    when: float = 0.0  # time: seconds since start; prob: probability


def parse_duration(text: str) -> float:
    m = _DURATION_RE.match(str(text).strip())
    if not m:
        raise ValueError(
            f"bad chaos duration {text!r} (want e.g. '5s', '250ms' or a "
            f"bare seconds float)")
    value = float(m.group(1))
    return value / 1000.0 if m.group(2) == "ms" else value


def _validate(step, fault, arg, rank, trigger="step",
              when=0.0) -> ChaosEntry:
    if fault not in FAULTS:
        raise ValueError(
            f"unknown chaos fault {fault!r} (known: {sorted(FAULTS)})")
    step = int(step)
    if step < 0:
        raise ValueError(f"chaos step must be >= 0, got {step}")
    default = FAULTS[fault]
    if arg is not None and default is None:
        raise ValueError(f"chaos fault {fault!r} takes no argument, "
                         f"got {arg!r}")
    seconds = None
    if default is not None:
        seconds = parse_duration(arg) if arg is not None else float(default)
        if seconds < 0:
            raise ValueError(f"chaos duration must be >= 0, got {seconds}")
    return ChaosEntry(step=step, fault=fault, arg=seconds,
                      rank=int(rank if rank is not None else -1),
                      trigger=trigger, when=float(when))


def _trigger_fields(trigger: str, value) -> dict:
    """Map one (trigger, value) pair to _validate kwargs, failing fast on
    out-of-range values — a typo'd schedule must die at parse time, not
    silently never fire mid-campaign."""
    if trigger == "step":
        return {"step": value}
    if trigger == "t":
        seconds = parse_duration(value)
        return {"step": 0, "trigger": "time", "when": seconds}
    try:
        prob = float(value)
    except (TypeError, ValueError):
        raise ValueError(f"bad chaos probability {value!r} "
                         f"(want a float in (0, 1])")
    if not 0.0 < prob <= 1.0:
        raise ValueError(f"chaos probability must be in (0, 1], got {prob}")
    return {"step": 0, "trigger": "prob", "when": prob}


def _parse_entry(token: str) -> ChaosEntry:
    m = _ENTRY_RE.match(token.strip())
    if not m:
        raise ValueError(
            f"bad chaos entry {token!r} (want "
            f"'step=<N>:<fault>[=<arg>][@rank=<R>]', or 't=<dur>:' / "
            f"'p=<prob>:' in place of 'step=<N>:')")
    return _validate(fault=m.group("fault"), arg=m.group("arg"),
                     rank=m.group("rank"),
                     **_trigger_fields(m.group("trigger"), m.group("when")))


def _parse_json(path: str) -> List[ChaosEntry]:
    with open(path) as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        data = data.get("schedule", data.get("entries"))
    if not isinstance(data, list):
        raise ValueError(
            f"chaos JSON {path!r} must hold a list of entries (or a dict "
            f"with a 'schedule' list)")
    out = []
    for i, item in enumerate(data):
        triggers = ([k for k in ("step", "t", "p") if k in item]
                    if isinstance(item, dict) else [])
        if not isinstance(item, dict) or len(triggers) != 1 \
                or "fault" not in item:
            raise ValueError(
                f"chaos JSON {path!r} entry {i} needs 'step' and 'fault' "
                f"keys (or exactly one of 't'/'p' in place of 'step'), "
                f"got {item!r}")
        out.append(_validate(fault=item["fault"], arg=item.get("arg"),
                             rank=item.get("rank"),
                             **_trigger_fields(triggers[0],
                                               item[triggers[0]])))
    return out


def parse_schedule(spec: str,
                   allowed: Optional[Sequence[str]] = None
                   ) -> List[ChaosEntry]:
    """Parse ``--chaos`` (inline grammar or a JSON file path) into entries,
    sorted by step. ``allowed`` restricts the fault set for contexts that
    support only part of it (serving passes :data:`SERVE_FAULTS`)."""
    spec = (spec or "").strip()
    if not spec:
        return []
    if spec.startswith("@"):
        entries = _parse_json(spec[1:])
    elif os.path.isfile(spec):
        entries = _parse_json(spec)
    else:
        entries = [_parse_entry(tok) for tok in spec.split(";")
                   if tok.strip()]
        if not entries:
            raise ValueError(f"empty chaos schedule {spec!r}")
    if allowed is not None:
        bad = [e.fault for e in entries if e.fault not in allowed]
        if bad:
            raise ValueError(
                f"chaos fault(s) {sorted(set(bad))} not supported in this "
                f"context (allowed: {sorted(allowed)})")
    return sorted(entries, key=lambda e: (e.step, e.fault))
