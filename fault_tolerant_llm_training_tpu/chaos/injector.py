"""Seeded deterministic fault injectors.

One :class:`ChaosInjector` owns the parsed schedule (chaos/schedule.py) and
exposes one hook per injection site:

- :meth:`on_train_step` — the training loop's single injection point
  (training/loop.py; the legacy ``--raise-error`` block now lives here):
  signal delivery, simulated exceptions, checkpoint-corruption faults;
- :meth:`on_sync_boundary` — the multihost KV signal-agreement boundary:
  delayed (``kv_delay``) or failed (``kv_fail``) rounds;
- :meth:`on_batch` — the data-prefetch worker (data/prefetch.py), keyed by
  the batch's global step: ``loader_stall`` sleeps before handing over;
- :meth:`on_serve_step` — the serving loop (inference/serve.py), keyed by
  decode iteration: a mid-decode drain signal;
- :meth:`post_fault_save` — ft/handler.py, after the exit handler's fault
  checkpoint commits: ``ckpt_corrupt`` flips bytes in the newest step dir
  (AFTER its integrity manifest is written, so the next restore must catch
  it and fall back);
- :meth:`on_publish` — deploy/publish.py, after the ``published.json``
  pointer commits: ``publish_corrupt`` flips a byte in the published
  step's files, so the serving watcher's verify-before-load must reject
  the publish;
- :meth:`on_reload` — deploy/reload.py, keyed by reload ordinal (1 = the
  first swap): ``reload_signal`` delivers a real SIGUSR1 in the middle of
  a hot weight swap;
- :meth:`on_handoff` / :meth:`on_spill` / :meth:`on_ship` /
  :meth:`on_store_put` — the tiered-KV block artifacts
  (inference/scheduler.py spill tier and incremental prefill shipments,
  inference/fleet.py ``--handoff`` drain, inference/kvstore.py store
  publishes), keyed by export ordinal: ``handoff_corrupt`` /
  ``spill_corrupt`` / ``ship_corrupt`` / ``store_corrupt`` flip one
  payload byte AFTER the artifact's CRC manifest commits, so the
  verify-before-import must reject it and the request must degrade to
  committed-prefix replay (or, for store fetches, local chunked
  prefill);
- :meth:`on_mem_push` — the in-memory KV transport lane
  (inference/transport.py ``MemTransport``), keyed by push ordinal:
  ``mem_corrupt`` poisons the fabric-resident train's manifest METADATA
  without refreshing its push-time digest, so the importer's mem-lane
  verify must catch the disagreement and degrade that train to the fs
  artifact (the on-disk copy is untouched);
- :meth:`on_prefill_chunk` — the prefill-role scheduler's chunk-commit
  boundary, keyed by completed-chunk ordinal: ``prefill_kill`` SIGKILLs
  the prefill engine mid-prompt.

Trigger kinds beyond ``step=N`` (chaos/schedule.py): ``t=DUR`` entries
fire at the first injection-site visit after DUR has elapsed since this
injector was constructed, and ``p=PROB`` entries fire with seeded
per-visit probability — both latch exactly once like step entries.

Every firing is recorded three ways at once: the ``AUDIT_CHAOS_INJECT_FMT``
audit line, one flight-recorder event typed ``chaos_<fault>``
(obs/events.py), and the ``chaos_faults_injected_total{class=...}``
counter. Signals are delivered through the OS (:func:`ft.signals.inject`)
so the handler, the flag, and the cluster agreement run exactly as for a
scheduler-sent signal. The injector is seeded: which byte of which file a
``ckpt_corrupt`` flips is a deterministic function of ``--seed``.
"""

import os
import signal as _signal
import time
from typing import List, Optional

import numpy as np

from ..ft import signals as ft_signals
from ..obs import events
from ..obs.registry import REGISTRY
from ..utils.logging import AUDIT_CHAOS_INJECT_FMT, logger
from .schedule import ChaosEntry, parse_schedule

# The reference's injected-error shape (ref: train.py:112-113): args[1] == -1
# routes the exit policy down "save, no resubmit" (ft/handler.py).
_SIM_ERROR_MSG = "Simulated exception to test signal handler"

_M_INJECTED = REGISTRY.counter(
    "chaos_faults_injected_total",
    "Chaos faults injected by this process, by fault class")


class ChaosInjector:
    def __init__(self, entries: List[ChaosEntry], seed: int = 0):
        self.entries = entries
        self.rng = np.random.default_rng(seed)
        self._corrupt_armed: Optional[ChaosEntry] = None
        self._t0 = time.monotonic()  # epoch for t= (time-triggered) entries

    @classmethod
    def from_config(cls, cfg) -> Optional["ChaosInjector"]:
        """Build from TrainConfig: ``--chaos`` plus the legacy
        ``--raise-error`` alias (one ``exception`` entry at ``--error-step``
        carrying ``--error-local-rank``) — the injection site lives here in
        one place either way."""
        entries = parse_schedule(getattr(cfg, "chaos", ""))
        if getattr(cfg, "raise_error", False):
            entries.append(ChaosEntry(step=cfg.error_step, fault="exception",
                                      rank=cfg.error_local_rank))
        if not entries:
            return None
        return cls(sorted(entries, key=lambda e: (e.step, e.fault)),
                   seed=getattr(cfg, "seed", 0))

    def describe(self) -> str:
        parts = []
        for e in self.entries:
            if e.trigger == "time":
                tok = f"t={e.when:g}s:{e.fault}"
            elif e.trigger == "prob":
                tok = f"p={e.when:g}:{e.fault}"
            else:
                tok = f"step={e.step}:{e.fault}"
            if e.arg is not None:
                tok += f"={e.arg:g}s"
            if e.rank >= 0:
                tok += f"@rank={e.rank}"
            parts.append(tok)
        return "; ".join(parts)

    # ------------------------------------------------------------- internals
    def _due(self, entry: ChaosEntry, step: int) -> bool:
        if entry.trigger == "time":
            return time.monotonic() - self._t0 >= entry.when
        if entry.trigger == "prob":
            return float(self.rng.random()) < entry.when
        return entry.step == step

    def _pending(self, faults, step: int) -> List[ChaosEntry]:
        return [e for e in self.entries
                if not e.fired and e.fault in faults
                and self._due(e, step)]

    def _fire(self, entry: ChaosEntry, at_step: Optional[int] = None,
              **payload) -> None:
        """Latch the entry and record the injection everywhere at once —
        before the fault itself acts, so a fault that kills the process
        still leaves its own trail. ``at_step`` overrides the audited step
        for time/probability-triggered entries (their ``step`` field is a
        placeholder 0, the firing site's step is the informative one)."""
        entry.fired = True
        step = entry.step if at_step is None else at_step
        _M_INJECTED.labels(**{"class": entry.fault}).inc()
        events.emit_audit(
            logger,
            AUDIT_CHAOS_INJECT_FMT.format(fault=entry.fault, step=step),
            f"chaos_{entry.fault}", step=step, fault=entry.fault,
            **payload)
        events.flush()

    def _raise_error(self, trainer, entry: ChaosEntry) -> None:
        """The reference's simulated-error semantics, byte-identical to the
        old in-loop block: replicated (rank < 0) drains the dispatch
        pipeline and marks the error cluster-replicated so the exit handler
        may save coordinated; a rank-restricted fault raises on that host
        only, undrained — the shape that exercises the pod fault fence."""
        if entry.rank < 0:
            self._fire(entry, rank=-1)
            if trainer is not None:
                trainer._drain_inflight()
                trainer.error_is_replicated = True
            raise Exception(_SIM_ERROR_MSG, -1)
        import jax

        if entry.rank == jax.process_index():
            self._fire(entry, rank=entry.rank)
            raise Exception(_SIM_ERROR_MSG, -1)
        entry.fired = True  # not this host's fault to raise

    # ----------------------------------------------------------------- hooks
    def on_train_step(self, trainer, step: int) -> None:
        """Training-loop injection site: called once per loop iteration
        while ``training_step == step`` (after the step's dispatch, before
        the counter advances) — the exact point the legacy ``--raise-error``
        fired from."""
        for e in self._pending(("sigusr1", "sigterm"), step):
            if 0 <= e.rank != _process_index():
                e.fired = True
                continue
            signum = (_signal.SIGUSR1 if e.fault == "sigusr1"
                      else _signal.SIGTERM)
            self._fire(e, at_step=step, signum=int(signum))
            ft_signals.inject(signum)
        for e in self._pending(("ckpt_corrupt",), step):
            # Two-phase fault: die like a training error now (the exit
            # handler saves the fault checkpoint), corrupt that checkpoint
            # in post_fault_save once it has committed.
            self._fire(e, at_step=step, phase="raise")
            self._corrupt_armed = e
            if trainer is not None:
                trainer._drain_inflight()
                trainer.error_is_replicated = True
            raise Exception(_SIM_ERROR_MSG, -1)
        for e in self._pending(("exception",), step):
            self._raise_error(trainer, e)

    def on_sync_boundary(self, trainer, step: int) -> None:
        """Signal-sync boundary: delayed or failed KV agreement rounds."""
        from ..ft.multihost import PeerHostError

        for e in self._pending(("kv_delay",), step):
            self._fire(e, at_step=step, seconds=e.arg)
            time.sleep(e.arg or 0.0)
        for e in self._pending(("kv_fail",), step):
            self._fire(e, at_step=step)
            if trainer is not None:
                trainer.error_is_replicated = True
            raise PeerHostError()

    def on_batch(self, batch_step: int) -> None:
        """Prefetch-worker hook (data/prefetch.py), called with the global
        step the produced batch will feed, BEFORE it is handed to the
        consumer: ``loader_stall`` delays that batch's delivery."""
        for e in self._pending(("loader_stall",), batch_step):
            self._fire(e, at_step=batch_step, seconds=e.arg)
            time.sleep(e.arg or 0.0)

    def on_serve_step(self, iteration: int) -> None:
        """Serving-loop hook, keyed by decode iteration: deliver the drain
        signal mid-decode; the serve loop's next flag check begins the
        drain lifecycle."""
        for e in self._pending(("sigusr1", "sigterm"), iteration):
            signum = (_signal.SIGUSR1 if e.fault == "sigusr1"
                      else _signal.SIGTERM)
            self._fire(e, at_step=iteration, signum=int(signum), serve=True)
            ft_signals.inject(signum)

    def on_fleet_step(self, iteration: int) -> None:
        """Fleet-host loop hook (inference/fleet.py), keyed by the host's
        loop iteration: the drain signals work as in ``on_serve_step``, and
        ``host_kill`` SIGKILLs this host mid-decode — no handler, no drain,
        no journal flush beyond what already committed. ``_fire`` runs
        first, so the chaos audit line and its flight-recorder event are
        on disk before the process dies; everything after is the router's
        problem (dead verdict -> migrate), which is the point."""
        for e in self._pending(("sigusr1", "sigterm"), iteration):
            signum = (_signal.SIGUSR1 if e.fault == "sigusr1"
                      else _signal.SIGTERM)
            self._fire(e, at_step=iteration, signum=int(signum), fleet=True)
            ft_signals.inject(signum)
        for e in self._pending(("host_kill",), iteration):
            self._fire(e, at_step=iteration,
                       signum=int(_signal.SIGKILL), fleet=True)
            os.kill(os.getpid(), _signal.SIGKILL)

    def on_prefill_chunk(self, ordinal: int) -> None:
        """Prefill-chunk hook (inference/scheduler.py), keyed by the
        host's completed-prefill-chunk ordinal (0 = right after the first
        chunk commits): ``prefill_kill`` SIGKILLs a prefill-role host
        between chunk commits — shipments stop mid-prompt and the router
        must re-prefill the request on a peer. Same audit-before-death
        ordering as ``host_kill``."""
        for e in self._pending(("prefill_kill",), ordinal):
            self._fire(e, at_step=ordinal,
                       signum=int(_signal.SIGKILL), prefill=True)
            os.kill(os.getpid(), _signal.SIGKILL)

    def on_heartbeat(self, iteration: int) -> None:
        """Lease-renewal hook (inference/fleet.py), keyed by loop
        iteration: ``heartbeat_delay`` sleeps before the renewal write, so
        the router's sweep sees a stale lease on a live host — shorter
        than the ttl it must ride through, longer it must self-fence."""
        for e in self._pending(("heartbeat_delay",), iteration):
            self._fire(e, at_step=iteration, seconds=e.arg)
            time.sleep(e.arg or 0.0)

    def on_publish(self, step_dir: str, step: int, log) -> Optional[str]:
        """Publisher hook (deploy/publish.py), called AFTER the
        ``published.json`` pointer commit: ``publish_corrupt`` flips one
        seeded byte in the published step's files — the manifest stays
        intact, so the watcher's verify-before-load must catch the CRC
        mismatch and reject the publish. Returns the corrupted path."""
        corrupted = None
        for e in self._pending(("publish_corrupt",), step):
            self._fire(e, at_step=step, phase="corrupt")
            flipped = self._flip_byte(step_dir, log,
                                      what=f"published step {step}")
            if flipped is not None:
                corrupted, rel, offset = flipped
                events.emit(kind="chaos_publish_corrupt", step=int(step),
                            phase="corrupted", file=rel, offset=offset)
                events.flush()
        return corrupted

    def on_reload(self, ordinal: int) -> None:
        """Hot-reload hook (deploy/reload.py), called in the MIDDLE of a
        weight swap (new params restored, not yet installed), keyed by
        reload ordinal (1 = first swap): ``reload_signal`` delivers a real
        SIGUSR1 there — the swap must complete, and the serve loop's next
        flag check drains on the new weights."""
        for e in self._pending(("reload_signal",), ordinal):
            self._fire(e, at_step=ordinal, signum=int(_signal.SIGUSR1),
                       reload=True)
            ft_signals.inject(_signal.SIGUSR1)

    def _corrupt_artifact(self, fault: str, artifact_dir: str,
                          ordinal: int, what: str) -> Optional[str]:
        """Shared body for the block-artifact corruption hooks: flip one
        seeded payload byte in ``artifact_dir`` — ``_flip_byte`` spares
        ``integrity.json``, so the damage lands exactly where the CRC
        manifest must catch it. Keyed by export ordinal (0 = first)."""
        corrupted = None
        for e in self._pending((fault,), ordinal):
            self._fire(e, at_step=ordinal, phase="corrupt")
            flipped = self._flip_byte(artifact_dir, logger, what=what)
            if flipped is not None:
                corrupted, rel, offset = flipped
                events.emit(kind=f"chaos_{fault}", step=int(ordinal),
                            phase="corrupted", file=rel, offset=offset)
                events.flush()
        return corrupted

    def on_handoff(self, artifact_dir: str,
                   ordinal: int = 0) -> Optional[str]:
        """Drain-time block-shipment hook (inference/fleet.py
        ``--handoff``), called AFTER one request's artifact manifest
        commits: ``handoff_corrupt`` — the router/survivor CRC verify
        must reject the artifact and the migration must degrade to
        committed-prefix replay. Returns the corrupted path."""
        return self._corrupt_artifact(
            "handoff_corrupt", artifact_dir, ordinal,
            what=f"handoff artifact {ordinal}")

    def on_ship(self, artifact_dir: str, ordinal: int = 0) -> Optional[str]:
        """Block-shipment hook (disaggregated prefill, called AFTER one
        incremental shipment's manifest commits, keyed by this host's
        ship-export ordinal): ``ship_corrupt`` flips one payload byte with
        the manifest spared — the router's verify must CRC-reject exactly
        this shipment and the decode admission degrades to
        committed-prefix replay. Returns the corrupted path."""
        return self._corrupt_artifact(
            "ship_corrupt", artifact_dir, ordinal,
            what=f"block shipment {ordinal}")

    def on_store_put(self, artifact_dir: str,
                     ordinal: int = 0) -> Optional[str]:
        """Fleet-store publish hook (inference/kvstore.py, called AFTER a
        prefix train's manifest commits, keyed by this host's publish
        ordinal): ``store_corrupt`` flips one payload byte with the
        manifest spared — a fetching host's verify-before-import must
        CRC-reject exactly this train and fall back to local chunked
        prefill. Returns the corrupted path."""
        return self._corrupt_artifact(
            "store_corrupt", artifact_dir, ordinal,
            what=f"store artifact {ordinal}")

    def on_mem_push(self, fabric, handle: str,
                    ordinal: int = 0) -> Optional[str]:
        """In-memory transport push hook (inference/transport.py
        ``MemTransport``, called AFTER a train's device arrays land in
        the shared fabric, keyed by push ordinal): ``mem_corrupt``
        poisons the fabric-resident manifest's metadata WITHOUT
        refreshing the push-time digest — the mem-lane analogue of the
        payload byte flips, except the damage is metadata because the
        lane's whole verification contract IS the metadata digest. The
        importer must catch the disagreement and degrade exactly this
        train to the fs artifact. Returns the poisoned handle."""
        poisoned = None
        for e in self._pending(("mem_corrupt",), ordinal):
            self._fire(e, at_step=ordinal, phase="poison")
            detail = fabric.poison(handle)
            if detail:
                poisoned = str(handle)
                events.emit(kind="chaos_mem_corrupt", step=int(ordinal),
                            phase="poisoned", handle=str(handle),
                            detail=detail)
                events.flush()
        return poisoned

    def on_spill(self, artifact_dir: str, ordinal: int = 0) -> Optional[str]:
        """Spill-tier hook (inference/scheduler.py), called AFTER a
        preempted request's artifact manifest commits: ``spill_corrupt``
        — the restore's CRC verify must reject the artifact and fall
        back to a replay re-admission. Returns the corrupted path."""
        return self._corrupt_artifact(
            "spill_corrupt", artifact_dir, ordinal,
            what=f"spill artifact {ordinal}")

    def post_fault_save(self, checkpoint_dir: str, saved_step: int,
                        log) -> Optional[str]:
        """Corrupt the just-committed fault checkpoint (armed by a
        ``ckpt_corrupt`` raise). Flips one byte mid-file in a seeded-chosen
        state file of step ``saved_step`` — after the integrity manifest
        was written, so the corruption is exactly what the next restore's
        verification must catch. Returns the corrupted path (or None)."""
        if self._corrupt_armed is None or saved_step is None:
            return None
        entry, self._corrupt_armed = self._corrupt_armed, None
        step_dir = os.path.join(checkpoint_dir, str(saved_step))
        flipped = self._flip_byte(step_dir, log,
                                  what=f"checkpoint step {saved_step}")
        if flipped is None:
            return None
        target, rel, offset = flipped
        events.emit(kind="chaos_ckpt_corrupt", step=entry.step,
                    phase="corrupted", saved_step=int(saved_step),
                    file=rel, offset=offset)
        events.flush()
        return target

    def _flip_byte(self, step_dir: str, log, what: str):
        """Seeded single-byte XOR in one of a step dir's files (the
        integrity manifest itself is spared — the corruption must be the
        kind the manifest CATCHES). Returns ``(path, rel, offset)`` or
        None if the dir holds nothing flippable."""
        candidates = []
        for root, _dirs, names in os.walk(step_dir):
            for name in names:
                if name == "integrity.json" or name.startswith("."):
                    continue
                path = os.path.join(root, name)
                if os.path.getsize(path) > 0:
                    candidates.append(path)
        # Prefer real array payloads over small JSON metadata: corrupting
        # the largest-file cohort models a torn/bit-rotted shard write.
        state_files = sorted(c for c in candidates
                             if f"{os.sep}state{os.sep}" in c)
        pool = state_files or sorted(candidates)
        if not pool:
            log.warning(f"[CHAOS] corruption armed but no files found "
                        f"under {step_dir}")
            return None
        target = pool[int(self.rng.integers(len(pool)))]
        size = os.path.getsize(target)
        offset = int(self.rng.integers(size))
        with open(target, "r+b") as fh:
            fh.seek(offset)
            byte = fh.read(1)
            fh.seek(offset)
            fh.write(bytes([byte[0] ^ 0xFF]))
            fh.flush()
            os.fsync(fh.fileno())
        rel = os.path.relpath(target, os.path.dirname(step_dir))
        log.info(f"[CHAOS] Corrupted {what}: "
                 f"flipped byte {offset} of {rel}")
        return target, rel, offset


def _process_index() -> int:
    import jax

    return jax.process_index()
