"""Chaos subsystem: declarative fault-injection schedules.

The paper's product is the preempt -> checkpoint -> resubmit -> resume
loop; this package is how we *attack* it on purpose. A schedule string
(``--chaos "step=50:sigusr1;step=80:exception"``, utils/config.py) parses
into seeded deterministic injectors (injector.py) that hook the training
loop, the signal layer, the data prefetcher, the multihost KV agreement
and the serving loop. ``scripts/chaos_campaign.py`` drives whole
inject -> die -> resume -> verify scenarios end-to-end and writes a
survival report from the flight-recorder trail.
"""

from .schedule import (ChaosEntry, FAULTS, FLEET_FAULTS, SERVE_FAULTS,
                       parse_schedule)
from .injector import ChaosInjector

__all__ = ["ChaosEntry", "ChaosInjector", "FAULTS", "FLEET_FAULTS",
           "SERVE_FAULTS", "parse_schedule"]
