"""Structured JSONL flight recorder.

The reference's verification API is its log text: the README greps the Slurm
``.out`` files for the ``[EXIT HANDLER]`` audit trail (utils/logging.py keeps
those strings byte-identical). That trail is human-greppable but not
machine-accountable — nothing records how much compute a preempt →
checkpoint → resubmit → resume chain actually cost. The flight recorder
closes the gap without touching the text contract: every audit emission goes
through :func:`emit_audit`, which logs the byte-identical string AND appends
one typed event (``step``, ``ckpt_save``, ``ckpt_restore``, ``signal``,
``resume``, ``eval``, ``drain``, ...) with wall-clock, step, and duration.

Events are written through to a JSONL file (one JSON object per line, append
mode — a resumed job under the same id extends the same file) and mirrored
into an in-memory ring buffer of the last N events. ``ft/handler.py``
flushes the recorder on every exit path, so a crash leaves forensics on disk
even when stdout is lost with the node.

Event schema (all numbers host-local):

    {"t": <unix wall clock>, "kind": "...", "job": "...", "host": 0,
     "step": <int|null>, "dur": <seconds|null>, ...payload}

``obs/goodput.py`` stitches these files across restarts into goodput %,
MTTR, and per-failure-class lost time.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import hlc

# Event kinds with a fixed meaning across the chain (payloads are free-form):
#   start         AUDIT_START — fresh run entered the loop
#   resume        AUDIT_RESUME_FMT — resumed run entered the loop
#   step          one logged step window (payload: steps covered, loss, ...)
#   ckpt_save     checkpoint written (dur = blocking wall; payload: fault?)
#   ckpt_restore  checkpoint restored at setup (dur = restore wall)
#   signal        fault signal agreed/observed (payload: signum, class)
#   eval          held-out evaluation pass
#   drain         serving drain lifecycle (payload: phase=begin|end)
#   requeue       sbatch resubmission attempt (payload: ok)
#   exit          exit-handler verdict (payload: error_type, class, saved)
#   complete      AUDIT_COMPLETED / AUDIT_SERVE_COMPLETED
#   chaos_<fault> chaos injection fired (chaos/injector.py; one kind per
#                 fault class, e.g. chaos_sigusr1, chaos_ckpt_corrupt —
#                 the latter twice: phase=raise then phase=corrupted)
#   ckpt_verify_failed   a step dir failed its integrity manifest at
#                        restore (payload: step, detail)
#   ckpt_fallback        restore fell back to an older passing step
#                        (payload: step chosen, rejected steps)
#   ckpt_partial_skipped leftover non-finalized tmp dir seen (and never
#                        restored) during the finalize sweep


class FlightRecorder:
    """Append-only JSONL event log + ring buffer of the last ``capacity``."""

    def __init__(self, path: Optional[str] = None, capacity: int = 512,
                 job: str = "local", host: int = 0,
                 clock: Callable[[], float] = time.time):
        self.path = path
        self.job = job
        self.host = host
        self.clock = clock
        self.ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)  # line-buffered

    def emit(self, kind: str, step: Optional[int] = None,
             dur: Optional[float] = None, **payload) -> Dict:
        ev = {"t": self.clock(), "hlc": hlc.tick(), "kind": kind,
              "job": self.job, "host": self.host}
        if step is not None:
            ev["step"] = int(step)
        if dur is not None:
            ev["dur"] = float(dur)
        ev.update(payload)
        with self._lock:
            self.ring.append(ev)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(ev) + "\n")
                except (OSError, ValueError):
                    pass  # a full/dead disk must never take down training
        return ev

    def flush(self) -> None:
        """Push buffered lines to the OS and fsync — the exit-path call
        (ft/handler.py): after this, the events survive the process."""
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass

    def dump(self, path: str) -> None:
        """Write the ring buffer to ``path`` (forensics fallback for runs
        that never configured a write-through file)."""
        with self._lock:
            events = list(self.ring)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as fh:
            for ev in events:
                fh.write(json.dumps(ev) + "\n")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# --------------------------------------------------------- module singleton
# Memory-only until configure() points it at a file; ft/handler.py and the
# serving loop emit through the module functions so a partially-constructed
# Trainer (signal during setup) still leaves a trail.
_RECORDER = FlightRecorder()


def configure(path: Optional[str], job: str = "local", host: int = 0,
              capacity: int = 512) -> FlightRecorder:
    """Swap in a configured recorder; prior ring contents carry over so
    events emitted before configuration are not lost."""
    global _RECORDER
    old = _RECORDER
    rec = FlightRecorder(path, capacity=capacity, job=job, host=host)
    rec.ring.extend(old.ring)
    if rec._fh is not None:
        for ev in rec.ring:  # replay pre-configuration events into the file
            try:
                rec._fh.write(json.dumps(ev) + "\n")
            except (OSError, ValueError):
                break
    old.close()
    _RECORDER = rec
    return rec


def get() -> FlightRecorder:
    return _RECORDER


def emit(kind: str, step: Optional[int] = None,
         dur: Optional[float] = None, **payload) -> Dict:
    return _RECORDER.emit(kind, step=step, dur=dur, **payload)


def flush() -> None:
    _RECORDER.flush()


def emit_audit(log, text: str, kind: str, step: Optional[int] = None,
               dur: Optional[float] = None, **payload) -> Dict:
    """Log a byte-identical audit string AND emit exactly one typed event.

    This is the only sanctioned way to emit an ``AUDIT_*`` string
    (tests/test_audit_contract.py greps the source tree for raw
    ``logger.info(AUDIT_*`` call sites): the text contract and the
    machine-readable record can never drift apart.
    """
    log.info(text)
    return emit(kind, step=step, dur=dur, audit=True, **payload)


def read_events(path: str) -> List[Dict]:
    """Load one JSONL event file; tolerates a truncated final line (the
    crash case the ring-buffer flush exists for)."""
    events = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail write from a killed process
    return events
