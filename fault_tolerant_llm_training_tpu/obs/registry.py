"""Metric registry: counters, gauges, histograms → Prometheus text format.

Stdlib-only (the container must not need prometheus_client): a
:class:`MetricRegistry` holds named metric families, each family holds
labeled children, and :meth:`MetricRegistry.render` emits the Prometheus
text exposition format (version 0.0.4) that ``obs/prometheus.py`` serves at
``/metrics``. The ad-hoc meters in utils/metrics.py (Throughput, HBM
queries) remain the *measurement* layer; this module is the *export* layer
the training loop and the serving scheduler publish into.

Thread safety: one lock per registry guards family creation; each metric's
mutations are single-writer in practice (the training/serve driver thread)
but use atomic ops cheap enough to leave safe anyway.
"""

import bisect
import math
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

# Default duration buckets: spans 5 ms decode iterations to the 120 s USR1
# checkpoint lead the whole framework is built around.
DURATION_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# Integer-count buckets for the speculative-decoding tokens-per-verify-round
# histogram (scheduler.py ftl_spec_tokens_per_round): a round emits between
# 1 (first proposal rejected) and spec_k + 1 (full accept + bonus) tokens,
# and spec_k rarely exceeds 8 — 1..16 covers it with exact per-count bins.
SPEC_TOKEN_BUCKETS = tuple(float(i) for i in range(1, 17))

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (exposition format
    0.0.4): backslash, double-quote and newline must be escaped or a
    value like ``reason="bad \"token\""`` corrupts the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline (quotes are legal)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(key: _LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{escape_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    def __init__(self):
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    def __init__(self, buckets: Sequence[float] = DURATION_BUCKETS):
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.counts[bisect.bisect_left(self.bounds, v)] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the bucket holding the
        q-th observation) — coarse but dependency-free, for log lines."""
        if not self.count:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return (self.bounds[i] if i < len(self.bounds)
                        else self.bounds[-1])
        return self.bounds[-1]


class _Family:
    """One named metric family; labeled children created on demand. The
    family itself doubles as the unlabeled child (``registry.counter(n)
    .inc()`` and ``registry.counter(n).labels(x='y').inc()`` both work)."""

    def __init__(self, kind: str, name: str, help_text: str,
                 buckets: Optional[Sequence[float]] = None):
        self.kind = kind
        self.name = name
        self.help_text = help_text
        self.buckets = buckets
        self._children: Dict[_LabelKey, object] = {}
        self._lock = threading.Lock()

    def _make(self):
        if self.kind == "counter":
            return Counter()
        if self.kind == "gauge":
            return Gauge()
        return Histogram(self.buckets or DURATION_BUCKETS)

    def labels(self, **labels):
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    # -- unlabeled convenience (delegates to the () child) --
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    @property
    def value(self) -> float:
        return self.labels().value

    def children(self) -> Iterable[Tuple[_LabelKey, object]]:
        with self._lock:
            return list(self._children.items())

    def render(self) -> str:
        lines = [f"# HELP {self.name} {escape_help(self.help_text)}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, child in sorted(self.children()):
            if self.kind == "histogram":
                acc = 0
                for bound, c in zip(child.bounds, child.counts):
                    acc += c
                    le = 'le="%s"' % _fmt_value(bound)
                    lines.append(f"{self.name}_bucket"
                                 f"{_fmt_labels(key, le)} {acc}")
                inf = 'le="+Inf"'
                lines.append(f"{self.name}_bucket"
                             f"{_fmt_labels(key, inf)} {child.count}")
                lines.append(f"{self.name}_sum{_fmt_labels(key)}"
                             f" {_fmt_value(child.sum)}")
                lines.append(f"{self.name}_count{_fmt_labels(key)}"
                             f" {child.count}")
                # Summary-style quantile snapshots next to the buckets:
                # operators read p50/p95/p99 off one scrape instead of
                # integrating _bucket lines by hand. Bucket-resolution
                # (Histogram.quantile), good enough for SLO eyeballing.
                for q in (0.5, 0.95, 0.99):
                    quant = 'quantile="%s"' % _fmt_value(q)
                    lines.append(
                        f"{self.name}{_fmt_labels(key, quant)}"
                        f" {_fmt_value(child.quantile(q))}")
            else:
                lines.append(f"{self.name}{_fmt_labels(key)}"
                             f" {_fmt_value(child.value)}")
        return "\n".join(lines)


class MetricRegistry:
    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _family(self, kind: str, name: str, help_text: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, name, help_text,
                                                     buckets)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def counter(self, name: str, help_text: str = "") -> _Family:
        return self._family("counter", name, help_text)

    def gauge(self, name: str, help_text: str = "") -> _Family:
        return self._family("gauge", name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family("histogram", name, help_text, buckets)

    def render(self) -> str:
        """Prometheus text exposition format, trailing newline included."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        return "\n".join(f.render() for f in fams) + "\n"

    def snapshot(self) -> Dict[str, Dict]:
        """Plain-dict view for tests and log lines."""
        out: Dict[str, Dict] = {}
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            entry: Dict = {"kind": fam.kind, "series": {}}
            for key, child in fam.children():
                label = ",".join(f"{k}={v}" for k, v in key)
                if fam.kind == "histogram":
                    entry["series"][label] = {"sum": child.sum,
                                              "count": child.count,
                                              "p50": child.quantile(0.5),
                                              "p95": child.quantile(0.95),
                                              "p99": child.quantile(0.99)}
                else:
                    entry["series"][label] = child.value
            out[fam.name] = entry
        return out


# Default registry: the one the training loop, the serving scheduler, and
# the /metrics endpoint share within a process.
REGISTRY = MetricRegistry()


def default_registry() -> MetricRegistry:
    return REGISTRY
