"""Stdlib-only Prometheus ``/metrics`` endpoint + per-host heartbeats.

:class:`MetricsServer` serves the metric registry (obs/registry.py) in the
Prometheus text exposition format from a daemon-thread
``ThreadingHTTPServer`` — no prometheus_client dependency, nothing on the
training hot path (the scrape reads whatever the loop last published).
Both the training loop (``train.py --metrics-port``) and the serving driver
(``inference/serve.py --metrics-port``) mount one.

:class:`HeartbeatThread` closes the pod-scale blind spot: a wedged or
straggling host today is invisible until a collective times out (up to
``--peer-timeout-seconds`` later). Each host publishes ``(wall clock,
step)`` through the jax.distributed KV store (ft/multihost.py — the same
host-side gRPC channel the fault fence uses, so no device collectives), and
every host exports per-peer gauges:

    ftl_host_heartbeat_age_seconds{host="3"}  — staleness; alert on > 2-3x
                                                 the publish interval
    ftl_host_heartbeat_step{host="3"}         — per-host step; a flat or
                                                 lagging host is a straggler

so the straggler is visible on ANY surviving host's scrape before the
collective deadline fires.
"""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .registry import MetricRegistry, default_registry


class MetricsServer:
    """``GET /metrics`` → registry render; ``GET /healthz`` → ok."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 host: str = "0.0.0.0", port: int = 0):
        self.registry = registry or default_registry()
        self._host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        registry = self.registry

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] in ("/metrics", "/"):
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                elif self.path == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam the
                pass                       # audit-trail stdout

        self._httpd = ThreadingHTTPServer((self._host, self._requested_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ftl-metrics", daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._thread = None


class HeartbeatThread(threading.Thread):
    """Publish this host's heartbeat and export every peer's as gauges.

    ``step_fn`` returns the current training step (read without locking —
    an int read is atomic in CPython and staleness of one tick is fine).
    Single-process runs degrade to a self-heartbeat (age ~0), so the gauge
    surface is identical on a laptop and a pod.
    """

    def __init__(self, step_fn: Callable[[], int],
                 registry: Optional[MetricRegistry] = None,
                 interval_seconds: float = 10.0,
                 clock: Callable[[], float] = time.time):
        super().__init__(name="ftl-heartbeat", daemon=True)
        self.step_fn = step_fn
        self.registry = registry or default_registry()
        self.interval = interval_seconds
        self.clock = clock
        self._stop = threading.Event()
        self._age = self.registry.gauge(
            "ftl_host_heartbeat_age_seconds",
            "Seconds since each host last published a heartbeat")
        self._step = self.registry.gauge(
            "ftl_host_heartbeat_step",
            "Last training step each host reported in its heartbeat")

    def beat_once(self) -> None:
        """One publish + one peer sweep (also the test entry point)."""
        from ..ft import multihost

        multihost.publish_heartbeat(int(self.step_fn()))
        now = self.clock()
        for host, (t, step) in multihost.read_heartbeats().items():
            self._age.labels(host=str(host)).set(max(0.0, now - t))
            self._step.labels(host=str(host)).set(step)

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.beat_once()
            except Exception:
                pass  # observability must never take down training
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
