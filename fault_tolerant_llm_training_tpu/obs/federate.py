"""Metrics federation: one /metrics for the whole serving fleet.

PR 12 gave every process Dapper-style request traces; this module is the
Monarch-style aggregation layer above them. Each fleet host already
exports a per-process /metrics (obs/prometheus.py) and advertises its
bound port in its heartbeat lease value (ft/lease.py ``metrics_port``) —
so the aggregator needs no service discovery beyond the lease sweep it
already trusts for liveness. A :class:`Federator` scrapes every
live-leased host, re-exports each host's series with a ``host=`` label
(HELP/TYPE deduped to exactly once per family), and derives the fleet
rollups the ROADMAP's scheduler/autoscaling items consume:

- ``fleet_tokens_per_sec``                     sum of per-host throughput
- ``fleet_kv_blocks_free/total{role=}``        paged-pool capacity by
                                               engine role (prefill
                                               pacing reads decode free)
- ``fleet_kv_store_resident_bytes``/``_evicted_bytes``  folded straight
                                               from the block-store
                                               journal (sweeper budget)
- ``fleet_ttft_seconds``/``fleet_tpot_seconds``  cross-host histogram
                                               merges (bucket sums are
                                               exact: every host shares
                                               the registry's bounds)
                                               with p50/p95/p99 lines
- ``fleet_slo_attainment{slo=}``               fraction of requests under
                                               the --slo-*-ms bars, from
                                               the merged buckets
- ``fleet_<counter>``                          every scraped counter
                                               family summed fleet-wide
- ``fleet_hosts_live/stale``, ``fleet_lease_age_seconds{host=}``  a
                                               wedged (alive-but-not-
                                               renewing) host is visible
                                               here BEFORE the router's
                                               fence verdict fires

Run it: ``python -m fault_tolerant_llm_training_tpu.obs.federate
--store <fleet-store> --port 9200`` (or ``--once`` to print a single
federated scrape — what ci_nightly's federation drill diffs against the
per-host scrapes).
"""

import argparse
import json
import sys
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ..ft.lease import FileKVStore, LeaseRegistry
from . import events

__all__ = ["parse_metrics_text", "family_of", "Federator", "main"]

# Gauges whose fleet-wide SUM is meaningful (rates and occupancies that
# add across hosts). Everything else per-host only: averaging a ratio
# like kv_block_utilization across heterogeneous pools is a lie.
SUMMABLE_GAUGES = {
    "ftl_serve_tokens_per_sec": "fleet_tokens_per_sec",
    "ftl_serve_queue_depth": "fleet_queue_depth",
}

# Histogram families merged into fleet-wide quantiles. Exact, not an
# approximation: every host builds these from the same registry bucket
# bounds, so summing per-``le`` cumulative counts is the true fleet
# distribution at bucket resolution.
MERGED_HISTOGRAMS = {
    "ftl_serve_ttft_seconds": "fleet_ttft_seconds",
    "ftl_serve_tpot_seconds": "fleet_tpot_seconds",
}


def _unescape(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(v[i + 1],
                                                             v[i + 1]))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        name = body[i:eq].strip().rstrip(",")
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {body[eq:]!r}")
        j = eq + 2
        buf = []
        while body[j] != '"':
            if body[j] == "\\":
                buf.append(body[j:j + 2])
                j += 2
            else:
                buf.append(body[j])
                j += 1
        labels[name] = _unescape("".join(buf))
        i = j + 1
        while i < len(body) and body[i] in ", ":
            i += 1
    return labels


def parse_metrics_text(text: str) -> Tuple[Dict[str, Dict],
                                           List[Tuple[str, Dict[str, str],
                                                      float]]]:
    """Parse Prometheus text exposition into ``(meta, samples)``.

    ``meta``: family name -> {"kind", "help"} from # TYPE / # HELP lines.
    ``samples``: ``(sample_name, labels, value)`` in document order —
    sample_name keeps the ``_bucket``/``_sum``/``_count`` suffixes.
    Tolerant of torn/garbage lines (a half-written scrape parses as far
    as it goes), never raises on them."""
    meta: Dict[str, Dict] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            meta.setdefault(name, {"kind": "untyped", "help": ""})
            meta[name]["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            meta.setdefault(name, {"kind": "untyped", "help": ""})
            meta[name]["kind"] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        try:
            if "{" in line:
                name = line[:line.index("{")]
                body = line[line.index("{") + 1:line.rindex("}")]
                labels = _parse_labels(body)
                value = float(line[line.rindex("}") + 1:].strip()
                              .split()[0])
            else:
                name, _, rest = line.partition(" ")
                labels = {}
                value = float(rest.strip().split()[0])
        except (ValueError, IndexError):
            continue
        samples.append((name, labels, value))
    return meta, samples


def family_of(sample_name: str, meta: Dict[str, Dict]) -> str:
    """Map a sample back to its family: histogram samples carry
    ``_bucket``/``_sum``/``_count`` suffixes the headers don't."""
    if sample_name in meta:
        return sample_name
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[:-len(suffix)]
            if meta.get(base, {}).get("kind") == "histogram":
                return base
    return sample_name


def _default_fetch(host: str, port: int, timeout: float) -> str:
    # Fleet hosts are local OS processes (the FileKVStore fleet substrate
    # is a shared directory), so the scrape plane is loopback.
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


class _MergedHist:
    """Cross-host histogram merge: per-``le`` cumulative bucket sums."""

    def __init__(self):
        self.buckets: Dict[float, float] = {}
        self.sum = 0.0
        self.count = 0.0

    def add_bucket(self, le: float, cumulative: float) -> None:
        self.buckets[le] = self.buckets.get(le, 0.0) + cumulative

    def quantile(self, q: float) -> float:
        if not self.count:
            return 0.0
        rank = q * self.count
        finite = sorted(b for b in self.buckets if b != float("inf"))
        for le in finite:
            if self.buckets[le] >= rank:
                return le
        return finite[-1] if finite else 0.0

    def fraction_le(self, bound: float) -> float:
        """Fraction of observations <= ``bound`` at bucket resolution
        (smallest bucket bound >= the requested one — conservative)."""
        if not self.count:
            return 1.0
        finite = sorted(b for b in self.buckets if b != float("inf"))
        for le in finite:
            if le >= bound:
                return self.buckets[le] / self.count
        return 1.0


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: Dict[str, str]) -> str:
    from .registry import escape_label_value
    if not labels:
        return ""
    parts = [f'{k}="{escape_label_value(v)}"'
             for k, v in sorted(labels.items())]
    return "{" + ",".join(parts) + "}"


class Federator:
    """Scrape live-leased hosts, re-export + roll up. Duck-types the
    registry interface :class:`obs.prometheus.MetricsServer` expects
    (``render()``), so it mounts directly on the stock server."""

    def __init__(self, store_root: str, kv_store_dir: Optional[str] = None,
                 slo_ttft_ms: float = 0.0, slo_tpot_ms: float = 0.0,
                 stale_factor: float = 0.5, timeout: float = 2.0,
                 clock: Callable[[], float] = time.time,
                 fetch: Optional[Callable[[str, int], str]] = None):
        self.leases = LeaseRegistry(FileKVStore(store_root), host_id=None,
                                    clock=clock)
        self.kv_store_dir = kv_store_dir
        self.slo_ttft = slo_ttft_ms / 1e3
        self.slo_tpot = slo_tpot_ms / 1e3
        self.stale_factor = stale_factor
        self.timeout = timeout
        self.clock = clock
        self.fetch = fetch or (
            lambda host, port: _default_fetch(host, port, self.timeout))
        self.scrape_failures = 0
        # stats of the last render, for the audit line / CLI summary
        self.last: Dict[str, float] = {}

    # ------------------------------------------------------------ scrape
    def scrape(self):
        """One sweep: (leases, tombstones, per-host parsed scrapes)."""
        leases = self.leases.leases()
        tombs = set(self.leases.tombstones())
        per_host: Dict[str, Tuple[Dict, List]] = {}
        for host in sorted(leases):
            lease = leases[host]
            if host in tombs or not lease.live or not lease.metrics_port:
                continue
            try:
                text = self.fetch(host, lease.metrics_port)
            except (OSError, ValueError):
                self.scrape_failures += 1
                continue
            per_host[host] = parse_metrics_text(text)
        return leases, tombs, per_host

    # ------------------------------------------------------------ store fold
    def _store_bytes(self) -> Optional[Tuple[int, int]]:
        if not self.kv_store_dir:
            return None
        # Imported lazily: the aggregator must not drag jax in unless a
        # store dir was actually configured.
        from ..inference.kvstore import BlockStore
        try:
            store = BlockStore(self.kv_store_dir, writer="federator",
                               clock=self.clock)
            folded = store.fold()
        except (OSError, ValueError):
            return None
        resident = sum(st.bytes for st in folded.values()
                       if not st.evicted and store.has(st.key))
        evicted = sum(st.bytes for st in folded.values() if st.evicted)
        return resident, evicted

    # ------------------------------------------------------------ render
    def render(self) -> str:
        leases, tombs, per_host = self.scrape()
        lines: List[str] = []
        emitted_headers = set()

        def header(name: str, kind: str, help_text: str) -> None:
            # exactly once per family, however many hosts carry it
            if name in emitted_headers:
                return
            emitted_headers.add(name)
            from .registry import escape_help
            lines.append(f"# HELP {name} {escape_help(help_text)}")
            lines.append(f"# TYPE {name} {kind}")

        # ---- per-host re-export with host= label, headers deduped ----
        families: Dict[str, Dict] = {}
        for host, (meta, _samples) in per_host.items():
            for name, m in meta.items():
                if name not in families or (
                        families[name]["kind"] == "untyped"):
                    families[name] = m
        counter_sums: Dict[str, float] = {}
        gauge_sums: Dict[str, float] = {}
        merged: Dict[str, _MergedHist] = {}
        series = 0
        for fam_name in sorted(families):
            fam = families[fam_name]
            header(fam_name, fam["kind"], fam["help"])
            for host in sorted(per_host):
                meta, samples = per_host[host]
                for name, labels, value in samples:
                    if family_of(name, meta) != fam_name:
                        continue
                    out_labels = dict(labels, host=host)
                    lines.append(f"{name}{_fmt_labels(out_labels)} "
                                 f"{_fmt_value(value)}")
                    series += 1
                    kind = meta.get(fam_name, {}).get("kind")
                    if kind == "counter" and "quantile" not in labels:
                        counter_sums[fam_name] = (
                            counter_sums.get(fam_name, 0.0) + value)
                    elif kind == "gauge" and fam_name in SUMMABLE_GAUGES:
                        gauge_sums[fam_name] = (
                            gauge_sums.get(fam_name, 0.0) + value)
                    if fam_name in MERGED_HISTOGRAMS:
                        h = merged.setdefault(fam_name, _MergedHist())
                        if name.endswith("_bucket") and "le" in labels:
                            le = (float("inf")
                                  if labels["le"] == "+Inf"
                                  else float(labels["le"]))
                            h.add_bucket(le, value)
                        elif name.endswith("_sum"):
                            h.sum += value
                        elif name.endswith("_count"):
                            h.count += value

        # ---- fleet rollups ----
        rollups = 0
        now = self.clock()
        live = [h for h, l in leases.items()
                if l.live and h not in tombs]
        # a host is STALE when its lease age exceeds stale_factor * ttl
        # but the dead verdict (age > ttl) has not fired yet: alive by
        # the router's rules, wedged by the operator's
        stale = [h for h in live
                 if leases[h].age > self.stale_factor * leases[h].ttl]
        header("fleet_hosts_live", "gauge",
               "Live-leased, untombstoned fleet hosts at scrape time")
        lines.append(f"fleet_hosts_live {len(live)}")
        header("fleet_hosts_stale", "gauge",
               "Live hosts whose lease age exceeds stale_factor*ttl — "
               "wedged (alive-but-not-renewing), visible before the "
               "fence verdict")
        lines.append(f"fleet_hosts_stale {len(stale)}")
        rollups += 2
        header("fleet_lease_age_seconds", "gauge",
               "Per-host heartbeat lease age as seen by the aggregator")
        for host in sorted(leases):
            lines.append(
                f"fleet_lease_age_seconds{_fmt_labels({'host': host})} "
                f"{_fmt_value(round(leases[host].age, 6))}")
            rollups += 1
        # KV block capacity per engine role, straight off the lease
        # values (blocks_free) and the scraped total gauges
        role_free: Dict[str, int] = {}
        role_total: Dict[str, float] = {}
        for host in live:
            role = leases[host].role
            role_free[role] = (role_free.get(role, 0)
                               + leases[host].blocks_free)
            meta_samples = per_host.get(host)
            if meta_samples:
                for name, labels, value in meta_samples[1]:
                    if name == "ftl_serve_kv_blocks_total":
                        role_total[role] = (role_total.get(role, 0.0)
                                            + value)
        header("fleet_kv_blocks_free", "gauge",
               "Free paged-pool KV blocks summed over live hosts, by "
               "engine role (prefill pacing watches role=decode)")
        for role in sorted(role_free):
            lines.append(
                f"fleet_kv_blocks_free{_fmt_labels({'role': role})} "
                f"{role_free[role]}")
            rollups += 1
        header("fleet_kv_blocks_total", "gauge",
               "Paged-pool KV block capacity summed over live hosts, "
               "by engine role")
        for role in sorted(role_total):
            lines.append(
                f"fleet_kv_blocks_total{_fmt_labels({'role': role})} "
                f"{_fmt_value(role_total[role])}")
            rollups += 1
        # fleet-global block store residency (satellite of ROADMAP item
        # 3: the byte budget publish-backpressure will gate on)
        store_bytes = self._store_bytes()
        if store_bytes is not None:
            resident, evicted = store_bytes
            header("fleet_kv_store_resident_bytes", "gauge",
                   "Resident (fetchable) bytes in the fleet-global KV "
                   "block store, folded from its journal")
            lines.append(f"fleet_kv_store_resident_bytes {resident}")
            header("fleet_kv_store_evicted_bytes", "gauge",
                   "Bytes the store's LRU sweeper has evicted, folded "
                   "from its journal")
            lines.append(f"fleet_kv_store_evicted_bytes {evicted}")
            rollups += 2
        # summed gauges and counters
        for src, dst in sorted(SUMMABLE_GAUGES.items()):
            if src in gauge_sums:
                header(dst, "gauge",
                       f"Fleet-wide sum of per-host {src}")
                lines.append(f"{dst} {_fmt_value(gauge_sums[src])}")
                rollups += 1
        for src in sorted(counter_sums):
            dst = f"fleet_{src}"
            header(dst, "counter",
                   f"Fleet-wide sum of per-host {src}")
            lines.append(f"{dst} {_fmt_value(counter_sums[src])}")
            rollups += 1
        # merged latency histograms + SLO attainment
        for src, dst in sorted(MERGED_HISTOGRAMS.items()):
            h = merged.get(src)
            if h is None or not h.count:
                continue
            header(dst, "histogram",
                   f"Cross-host merge of {src} (exact bucket sums; "
                   f"shared bounds)")
            for le in sorted(h.buckets):
                le_lbl = {"le": "+Inf" if le == float("inf")
                          else _fmt_value(le)}
                lines.append(f"{dst}_bucket{_fmt_labels(le_lbl)} "
                             f"{_fmt_value(h.buckets[le])}")
            lines.append(f"{dst}_sum {_fmt_value(round(h.sum, 9))}")
            lines.append(f"{dst}_count {_fmt_value(h.count)}")
            for q in (0.5, 0.95, 0.99):
                lines.append(
                    f"{dst}{_fmt_labels({'quantile': _fmt_value(q)})} "
                    f"{_fmt_value(h.quantile(q))}")
            rollups += 1
        slo_pairs = [("ttft", self.slo_ttft,
                      merged.get("ftl_serve_ttft_seconds")),
                     ("tpot", self.slo_tpot,
                      merged.get("ftl_serve_tpot_seconds"))]
        for slo_name, bound, h in slo_pairs:
            if bound <= 0 or h is None or not h.count:
                continue
            header("fleet_slo_attainment", "gauge",
                   "Fraction of fleet requests meeting the --slo-*-ms "
                   "bars, from the merged latency buckets")
            lines.append(
                f"fleet_slo_attainment{_fmt_labels({'slo': slo_name})} "
                f"{_fmt_value(round(h.fraction_le(bound), 6))}")
            rollups += 1
        header("fleet_scrape_failures_total", "counter",
               "Scrapes of live-leased hosts that failed (cumulative)")
        lines.append(f"fleet_scrape_failures_total {self.scrape_failures}")
        header("fleet_hosts_scraped", "gauge",
               "Hosts successfully scraped this sweep")
        lines.append(f"fleet_hosts_scraped {len(per_host)}")
        rollups += 2

        self.last = {"hosts": len(per_host), "series": series,
                     "rollups": rollups, "stale": len(stale),
                     "live": len(live), "t": now,
                     "failures": self.scrape_failures}
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- CLI
def get_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m fault_tolerant_llm_training_tpu.obs.federate",
        description="Fleet /metrics federation aggregator: scrapes every "
                    "live-leased host (ports discovered from lease "
                    "values), re-exports per-host series with host= "
                    "labels and serves fleet rollups on its own "
                    "/metrics.")
    p.add_argument("--store", required=True,
                   help="fleet KV store root (the --store every fleet "
                        "host and the router share)")
    p.add_argument("--kv-store-dir", default=None,
                   help="fleet-global KV block store root; enables the "
                        "fleet_kv_store_resident/evicted_bytes rollups")
    p.add_argument("--port", type=int, default=0,
                   help="serve the federated /metrics here (0 = "
                        "ephemeral; printed at startup)")
    p.add_argument("--once", action="store_true",
                   help="print one federated scrape to stdout (or "
                        "--out) and exit — the ci_nightly drill mode")
    p.add_argument("--out", default="",
                   help="with --once: write the scrape here instead of "
                        "stdout")
    p.add_argument("--interval", type=float, default=2.0,
                   help="server mode: seconds between logged sweeps")
    p.add_argument("--slo-ttft-ms", type=float, default=0.0)
    p.add_argument("--slo-tpot-ms", type=float, default=0.0)
    p.add_argument("--stale-factor", type=float, default=0.5,
                   help="lease age > stale_factor*ttl counts as stale "
                        "(wedged-but-alive) in fleet_hosts_stale")
    p.add_argument("--scrape-timeout", type=float, default=2.0)
    p.add_argument("--event-log", default="",
                   help="flight-recorder JSONL for the federation audit "
                        "events")
    p.add_argument("--max-sweeps", type=int, default=0,
                   help="server mode: exit after N sweeps (0 = forever)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    from ..utils.logging import (AUDIT_FLEETSCOPE_FEDERATE_FMT,
                                 init_logger, logger)
    args = get_args(argv)
    init_logger()
    if args.event_log:
        events.configure(args.event_log, job="federate", host=0)
    fed = Federator(args.store, kv_store_dir=args.kv_store_dir or None,
                    slo_ttft_ms=args.slo_ttft_ms,
                    slo_tpot_ms=args.slo_tpot_ms,
                    stale_factor=args.stale_factor,
                    timeout=args.scrape_timeout)

    def audit_sweep():
        events.emit_audit(
            logger, AUDIT_FLEETSCOPE_FEDERATE_FMT.format(
                hosts=int(fed.last.get("hosts", 0)),
                series=int(fed.last.get("series", 0)),
                rollups=int(fed.last.get("rollups", 0)),
                stale=int(fed.last.get("stale", 0)),
                failures=int(fed.last.get("failures", 0))),
            "fleetscope_federate", hosts=int(fed.last.get("hosts", 0)),
            series=int(fed.last.get("series", 0)),
            rollups=int(fed.last.get("rollups", 0)),
            stale=int(fed.last.get("stale", 0)),
            failures=int(fed.last.get("failures", 0)))

    if args.once:
        text = fed.render()
        audit_sweep()
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        events.flush()
        return 0

    from .prometheus import MetricsServer
    server = MetricsServer(registry=fed, port=args.port)
    port = server.start()
    logger.info(f"Federation | serving fleet /metrics on port {port} "
                f"(store {args.store})")
    sweeps = 0
    try:
        while True:
            fed.render()  # refresh + audit even when nobody scrapes us
            audit_sweep()
            sweeps += 1
            if args.max_sweeps and sweeps >= args.max_sweeps:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        events.flush()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
