"""Goodput-accounted observability layer (SURVEY §5.5: the reference's only
observability is grepping Slurm ``.out`` files for the ``[EXIT HANDLER]``
audit trail).

- :mod:`.events`    — structured JSONL flight recorder; every audit string
  keeps its byte-identical text but also emits a typed event, and an
  in-memory ring buffer is flushed on any exit path (crash forensics).
- :mod:`.registry`  — counters / gauges / histograms behind the training and
  serving metrics, rendered in Prometheus text format.
- :mod:`.goodput`   — stitches event logs *across restarts* into goodput %,
  MTTR, replayed tokens, and time lost per failure class (the headline
  reliability metrics of MegaScale, arXiv:2402.15627, and Meta's cluster
  reliability study, arXiv:2410.21680).
- :mod:`.prometheus` — stdlib-only HTTP ``/metrics`` endpoint plus per-host
  heartbeat gauges over the ft/multihost.py KV store.
- :mod:`.trace`     — windowed ``jax.profiler`` capture (``--trace-steps
  A:B``) with ``StepTraceAnnotation``.
"""

from . import events, registry

__all__ = ["events", "registry"]
