"""Windowed ``jax.profiler`` capture (``--trace-steps A:B``).

``--profile-dir`` alone traces the whole run — fine for a 5-step probe,
useless for "step 400 regressed": a multi-hour trace is unloadably large.
The window form arms the profiler at step A and disarms it after step B
(inclusive), each captured step wrapped in a ``StepTraceAnnotation`` so
XenseCope/TensorBoard group device ops per step. scripts/profile_step.py
used to do this ad hoc with its own start/stop + parser; both now live
here (:func:`capture`, :func:`parse_trace`) so the CLI window, the script,
and the tests share one implementation.

:class:`AutoTraceWindow` (``--auto-trace``) is the reactive form: instead
of a pre-chosen window it arms itself, once per run, when a step's wall
time regresses past a multiple of the rolling median — capturing the
slowdown the operator didn't know to schedule a window for.
"""

import collections
import contextlib
import glob
import gzip
import json
import re
import statistics
from typing import Callable, Optional, Tuple


def parse_window(spec: str) -> Tuple[int, int]:
    """``"A:B"`` → (A, B) inclusive; ``"N"`` → (N, N). Raises ValueError on
    malformed or empty windows — a silently-ignored trace flag is worse
    than a failed launch."""
    parts = spec.split(":")
    try:
        if len(parts) == 1:
            a = b = int(parts[0])
        elif len(parts) == 2:
            a, b = int(parts[0]), int(parts[1])
        else:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"--trace-steps expects 'A:B' or 'N', got {spec!r}") from None
    if a < 0 or b < a:
        raise ValueError(f"--trace-steps window {spec!r} is empty "
                         f"(need 0 <= A <= B)")
    return a, b


class TraceWindow:
    """Arms ``jax.profiler`` for steps in [start, stop] (inclusive).

    The loop calls :meth:`on_step_start` before dispatching each step and
    :meth:`on_step_end` after the step counter advances; :meth:`annotate`
    wraps the dispatch in a ``StepTraceAnnotation``. ``drain`` (passed by
    the trainer) runs before ``stop_trace`` so the asynchronously
    dispatched device work of the window's final steps lands inside the
    capture instead of after it.
    """

    def __init__(self, spec: str, trace_dir: str,
                 drain: Optional[callable] = None):
        self.start_step, self.stop_step = parse_window(spec)
        self.trace_dir = trace_dir
        self.drain = drain
        self.active = False
        self.done = False

    def on_step_start(self, step: int) -> None:
        if (not self.active and not self.done
                and self.start_step <= step <= self.stop_step):
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self.active = True

    def annotate(self, step: int):
        if not self.active:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.StepTraceAnnotation("train", step_num=step)

    def on_step_end(self, step: int) -> None:
        if self.active and step >= self.stop_step:
            import jax

            if self.drain is not None:
                self.drain()
            jax.profiler.stop_trace()
            self.active = False
            self.done = True

    def close(self) -> None:
        """Stop a still-armed trace (loop exited inside the window)."""
        if self.active:
            import jax

            try:
                if self.drain is not None:
                    self.drain()
                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
            self.done = True


class AutoTraceWindow:
    """Self-arming profiler window on step-time regression (``--auto-trace``).

    ``--trace-steps`` needs the operator to know WHICH steps regressed —
    useless for the transient cliffs (a thermal-throttled chip, a slow
    storage burst, a noisy neighbor) that make long runs mysteriously
    slow after the fact. This watcher keeps a rolling window of recent
    step wall times and, when one step exceeds ``threshold`` times the
    rolling MEDIAN (robust against the very outliers it hunts), arms a
    bounded ``jax.profiler`` capture for the next ``capture_steps`` steps.
    It fires at most ONCE per run — the point is a post-mortem artifact,
    not a profiler left hot — and the trainer audits the arm
    (``[TRACE]``) so the receipt says exactly which step tripped it and
    where the trace landed.

    ``profiler_start``/``profiler_stop`` are injectable for tests; the
    defaults call ``jax.profiler`` lazily like :class:`TraceWindow`.
    """

    def __init__(self, trace_dir: str, threshold: float = 2.0,
                 history: int = 32, min_samples: int = 8,
                 capture_steps: int = 4,
                 profiler_start: Optional[Callable[[str], None]] = None,
                 profiler_stop: Optional[Callable[[], None]] = None):
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1, got {threshold}")
        self.trace_dir = trace_dir
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.capture_steps = int(capture_steps)
        self._times = collections.deque(maxlen=int(history))
        self._start = profiler_start
        self._stop = profiler_stop
        self.active = False
        self.done = False
        self.trigger_step: Optional[int] = None
        self.ratio = 0.0
        self._captured = 0

    def _profiler_start(self) -> None:
        if self._start is not None:
            self._start(self.trace_dir)
            return
        import jax

        jax.profiler.start_trace(self.trace_dir)

    def _profiler_stop(self) -> None:
        if self._stop is not None:
            self._stop()
            return
        import jax

        jax.profiler.stop_trace()

    def observe(self, step: int, seconds: float) -> Optional[float]:
        """Feed one finished step's wall time. Returns the regression
        ratio when THIS sample arms the capture, else None (the trainer
        audits on a non-None return)."""
        if self.active:
            self._captured += 1
            if self._captured >= self.capture_steps:
                self._profiler_stop()
                self.active = False
                self.done = True
            return None
        if self.done:
            return None
        if len(self._times) >= self.min_samples:
            med = statistics.median(self._times)
            if med > 0 and seconds > self.threshold * med:
                self.ratio = seconds / med
                self.trigger_step = int(step)
                self._profiler_start()
                self.active = True
                return self.ratio
        self._times.append(float(seconds))
        return None

    def close(self) -> None:
        """Stop a still-armed capture (loop exited inside the window)."""
        if self.active:
            try:
                self._profiler_stop()
            except Exception:
                pass
            self.active = False
            self.done = True


@contextlib.contextmanager
def capture(trace_dir: str):
    """Whole-scope trace (scripts/profile_step.py's form)."""
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def parse_trace(trace_dir: str, steps: int):
    """Aggregate device-side op durations from the newest Chrome-trace JSON
    under ``trace_dir``. Returns (per-category ms/step dict, total
    ms/step). This is how the kernel/copy/fusion breakdown in BASELINE.md
    was measured."""
    files = sorted(glob.glob(f"{trace_dir}/**/*.trace.json.gz",
                             recursive=True))
    if not files:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(files[-1]) as fh:
        data = json.load(fh)
    pids = {e["pid"]: e["args"].get("name", "")
            for e in data["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    cat = collections.Counter()
    for e in data["traceEvents"]:
        if e.get("ph") != "X":
            continue
        pname = pids.get(e["pid"], "")
        if "TPU" not in pname and "device" not in pname.lower():
            continue
        n = e["name"]
        # skip the whole-program span and the per-execution lane aggregates
        if n.startswith("jit_") or n.isdigit():
            continue
        cat[re.sub(r"\.\d+$", "", n)] += e.get("dur", 0)
    total = sum(cat.values())
    return ({k: v / steps / 1000 for k, v in cat.items()},
            total / steps / 1000)
