"""Windowed ``jax.profiler`` capture (``--trace-steps A:B``).

``--profile-dir`` alone traces the whole run — fine for a 5-step probe,
useless for "step 400 regressed": a multi-hour trace is unloadably large.
The window form arms the profiler at step A and disarms it after step B
(inclusive), each captured step wrapped in a ``StepTraceAnnotation`` so
XenseCope/TensorBoard group device ops per step. scripts/profile_step.py
used to do this ad hoc with its own start/stop + parser; both now live
here (:func:`capture`, :func:`parse_trace`) so the CLI window, the script,
and the tests share one implementation.
"""

import collections
import contextlib
import glob
import gzip
import json
import re
from typing import Optional, Tuple


def parse_window(spec: str) -> Tuple[int, int]:
    """``"A:B"`` → (A, B) inclusive; ``"N"`` → (N, N). Raises ValueError on
    malformed or empty windows — a silently-ignored trace flag is worse
    than a failed launch."""
    parts = spec.split(":")
    try:
        if len(parts) == 1:
            a = b = int(parts[0])
        elif len(parts) == 2:
            a, b = int(parts[0]), int(parts[1])
        else:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"--trace-steps expects 'A:B' or 'N', got {spec!r}") from None
    if a < 0 or b < a:
        raise ValueError(f"--trace-steps window {spec!r} is empty "
                         f"(need 0 <= A <= B)")
    return a, b


class TraceWindow:
    """Arms ``jax.profiler`` for steps in [start, stop] (inclusive).

    The loop calls :meth:`on_step_start` before dispatching each step and
    :meth:`on_step_end` after the step counter advances; :meth:`annotate`
    wraps the dispatch in a ``StepTraceAnnotation``. ``drain`` (passed by
    the trainer) runs before ``stop_trace`` so the asynchronously
    dispatched device work of the window's final steps lands inside the
    capture instead of after it.
    """

    def __init__(self, spec: str, trace_dir: str,
                 drain: Optional[callable] = None):
        self.start_step, self.stop_step = parse_window(spec)
        self.trace_dir = trace_dir
        self.drain = drain
        self.active = False
        self.done = False

    def on_step_start(self, step: int) -> None:
        if (not self.active and not self.done
                and self.start_step <= step <= self.stop_step):
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self.active = True

    def annotate(self, step: int):
        if not self.active:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.StepTraceAnnotation("train", step_num=step)

    def on_step_end(self, step: int) -> None:
        if self.active and step >= self.stop_step:
            import jax

            if self.drain is not None:
                self.drain()
            jax.profiler.stop_trace()
            self.active = False
            self.done = True

    def close(self) -> None:
        """Stop a still-armed trace (loop exited inside the window)."""
        if self.active:
            import jax

            try:
                if self.drain is not None:
                    self.drain()
                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
            self.done = True


@contextlib.contextmanager
def capture(trace_dir: str):
    """Whole-scope trace (scripts/profile_step.py's form)."""
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def parse_trace(trace_dir: str, steps: int):
    """Aggregate device-side op durations from the newest Chrome-trace JSON
    under ``trace_dir``. Returns (per-category ms/step dict, total
    ms/step). This is how the kernel/copy/fusion breakdown in BASELINE.md
    was measured."""
    files = sorted(glob.glob(f"{trace_dir}/**/*.trace.json.gz",
                             recursive=True))
    if not files:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(files[-1]) as fh:
        data = json.load(fh)
    pids = {e["pid"]: e["args"].get("name", "")
            for e in data["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    cat = collections.Counter()
    for e in data["traceEvents"]:
        if e.get("ph") != "X":
            continue
        pname = pids.get(e["pid"], "")
        if "TPU" not in pname and "device" not in pname.lower():
            continue
        n = e["name"]
        # skip the whole-program span and the per-execution lane aggregates
        if n.startswith("jit_") or n.isdigit():
            continue
        cat[re.sub(r"\.\d+$", "", n)] += e.get("dur", 0)
    total = sum(cat.values())
    return ({k: v / steps / 1000 for k, v in cat.items()},
            total / steps / 1000)
