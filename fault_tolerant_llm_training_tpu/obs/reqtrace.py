"""Crash-surviving per-request span trail — the serving twin of the
flight recorder.

The flight recorder (obs/events.py) made *training* restarts
machine-accountable; the serving stack built since (paged KV, spec/tree
decode, hot reload, the multi-host fleet) exposed only counters and
gauges — nobody could say what a single request's TTFT or per-token
latency was, or where a migrated request spent its time. This module
closes that gap with a Dapper-style span model: one trace per request,
keyed by a ``trace_id`` minted at intake and carried through the
journal, so a request migrated between fleet hosts leaves one joinable
trail across every process that touched it.

Spans are appended to a line-buffered JSONL file (append mode, flushed +
fsynced on every exit path) and mirrored into a ring buffer, exactly
like the flight recorder: a host SIGKILLed mid-decode still leaves every
span it committed on disk, and the stitcher tolerates the torn tail.

Span schema (``t`` is the span END on the unix wall clock — wall, not
monotonic, because traces are joined ACROSS hosts):

    {"t": <unix wall clock>, "trace_id": "...", "id": "<request id>",
     "span": "<stage>", "job": "...", "host": "...",
     "dur": <seconds|null>, ...payload}

Span names with a fixed meaning across the fleet (payloads free-form):

    intake        request accepted/minted at intake (router or serve)
    queue         placement/admission wait (dur = seconds queued)
    placement     router chose a host (payload: host, gen)
    assign        a fleet host picked the assignment up (payload: gen,
                  committed tokens to replay)
    prefill       prompt prefill finished (dur; payload: prompt_tokens,
                  chunks, packed, replayed)
    first_token   first token available — the TTFT reference point
                  (payload: ttft as measured by the serving clock)
    decode_round  one decode/spec round that committed tokens to this
                  request (payload: tokens, mode=token|burst|spec|tree)
    reload_pause  hot weight reload stalled this in-flight request
                  (dur = swap seconds; payload: old, new)
    migration     router fenced the dead src and re-admitted on dst
                  (payload: src, dst, gen, replayed = committed prefix
                  length the survivor must replay bit-exactly)
    block_ship    a prefill-role engine exported one incremental block
                  shipment as a chunk committed (dur = export seconds;
                  payload: seq, blocks, bytes, length) — emitted on the
                  PREFILL host, so the stitched trace crosses the
                  prefill-host -> decode-host boundary
    decode_placement  router transferred ownership prefill -> decode
                  host after prefill_done (payload: src, dst, gen,
                  shipments = verified artifacts named in the record)
    shipment_import   the decode engine imported the shipped blocks at
                  admission (dur = verify+import seconds; payload:
                  shipments, blocks, deduped = prefix-cache-hit blocks
                  NOT re-imported)
    store_publish a host published this request's committed prefix train
                  to the fleet-global KV store (dur = export seconds;
                  payload: key, blocks, bytes)
    store_fetch   admission landed a fleet-store train instead of
                  prefilling it (dur = verify+import seconds; payload:
                  key, depth = imported blocks, prompt_tokens)
    requeue       drain persisted this request back to the journal
    done          request finished (payload: reason, tokens, ttft, tpot)

TTFT = first_token.t - intake/submit; TPOT = (done.t - first_token.t) /
(tokens - 1) — the first token is prefill's, so only the remaining
tokens price the decode loop (the DistServe/Splitwise framing).
``scripts/latency_report.py`` stitches trace files from every host into
per-request critical paths and an SLO-attainment table, the way
``goodput_report.py`` stitches flight-recorder files into goodput.
"""

import json
import math
import os
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from . import hlc

# Spans that mark decode progress: used by the stitcher to find the last
# token-committing event when a `done` span is missing (crashed host).
_PROGRESS_SPANS = ("decode_round", "first_token", "prefill")


def derive_trace_path(event_log: str) -> str:
    """Default trace-file path next to a flight-recorder event log
    (``events_router.jsonl`` -> ``trace_router.jsonl``), so one directory
    holds both trails and the stitchers can consume it whole."""
    d, b = os.path.split(event_log)
    if b.startswith("events_"):
        b = b[len("events_"):]
    return os.path.join(d, f"trace_{b}")


def mint_trace_id(request_id: str = "") -> str:
    """Mint a trace id at intake. Prefixed with the request id so trace
    files stay human-greppable; suffixed with enough randomness that two
    fleets sharing a journal directory can never collide."""
    suffix = uuid.uuid4().hex[:12]
    return f"{request_id}-{suffix}" if request_id else suffix


class SpanRecorder:
    """Append-only JSONL span log + ring buffer of the last ``capacity``."""

    def __init__(self, path: Optional[str] = None, capacity: int = 1024,
                 job: str = "local", host: str = "0",
                 clock: Callable[[], float] = time.time):
        self.path = path
        self.job = job
        self.host = str(host)
        self.clock = clock
        self.ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)  # line-buffered

    def emit(self, trace_id: str, request_id: str, span: str,
             dur: Optional[float] = None, **payload) -> Dict:
        rec = {"t": self.clock(), "hlc": hlc.tick(),
               "trace_id": str(trace_id),
               "id": str(request_id), "span": span, "job": self.job,
               "host": self.host}
        if dur is not None:
            rec["dur"] = float(dur)
        rec.update(payload)
        with self._lock:
            self.ring.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                except (OSError, ValueError):
                    pass  # a full/dead disk must never take down serving
        return rec

    def flush(self) -> None:
        """Push buffered lines to the OS and fsync — the exit-path call:
        after this, the spans survive the process."""
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass

    def dump(self, path: str) -> None:
        """Write the ring buffer to ``path`` (forensics fallback for runs
        that never configured a write-through file)."""
        with self._lock:
            spans = list(self.ring)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as fh:
            for rec in spans:
                fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# --------------------------------------------------------- module singleton
# Memory-only until configure() points it at a file; the router, fleet
# hosts, and serve.py emit through the module functions so spans recorded
# before setup finishes are not lost.
_RECORDER = SpanRecorder()


def configure(path: Optional[str], job: str = "local", host: str = "0",
              capacity: int = 1024) -> SpanRecorder:
    """Swap in a configured recorder; prior ring contents carry over so
    spans emitted before configuration are not lost."""
    global _RECORDER
    old = _RECORDER
    rec = SpanRecorder(path, capacity=capacity, job=job, host=host)
    rec.ring.extend(old.ring)
    if rec._fh is not None:
        for span in rec.ring:  # replay pre-configuration spans into the file
            try:
                rec._fh.write(json.dumps(span) + "\n")
            except (OSError, ValueError):
                break
    old.close()
    _RECORDER = rec
    return rec


def get() -> SpanRecorder:
    return _RECORDER


def emit(trace_id: str, request_id: str, span: str,
         dur: Optional[float] = None, **payload) -> Dict:
    return _RECORDER.emit(trace_id, request_id, span, dur=dur, **payload)


def flush() -> None:
    _RECORDER.flush()


def read_spans(path: str) -> List[Dict]:
    """Load one JSONL trace file; tolerates a truncated final line (the
    crash case the line-buffered flush exists for)."""
    spans = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail write from a killed process
            if isinstance(rec, dict) and "trace_id" in rec:
                spans.append(rec)
    return spans


# ------------------------------------------------------------- stitching

def _trace_files(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            names = sorted(n for n in os.listdir(p)
                           if n.endswith(".jsonl") and n.startswith("trace"))
            files.extend(os.path.join(p, n) for n in names)
        elif os.path.isfile(p):
            files.append(p)
    return files


def load_traces(paths: Iterable[str]) -> Dict[str, List[Dict]]:
    """Read span files (or directories of ``trace*.jsonl``) from every
    host and group them by trace_id, each trace time-sorted — the
    cross-host join a migrated request's forensics depend on."""
    traces: Dict[str, List[Dict]] = {}
    for path in _trace_files(paths):
        for rec in read_spans(path):
            traces.setdefault(rec["trace_id"], []).append(rec)
    for spans in traces.values():
        spans.sort(key=lambda r: (r.get("t", 0.0), r.get("span", "")))
    return traces


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile; 0.0 on an empty population."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = max(0, min(len(vs) - 1, int(math.ceil(q * len(vs))) - 1))
    return vs[idx]


def derive(spans: List[Dict]) -> Dict:
    """Per-request summary of one stitched trace: TTFT/TPOT, hosts
    visited, migration/replay evidence, and the wall-clock critical path.

    Prefers the serving clock's own measurements (the ``done`` span's
    ttft/tpot payload, monotonic-clock durations) and falls back to
    wall-clock span deltas when the request never finished (crashed
    host) — coarser, but still attributable.
    """
    by_name: Dict[str, List[Dict]] = {}
    for rec in spans:
        by_name.setdefault(rec.get("span", ""), []).append(rec)

    def first(name):
        recs = by_name.get(name)
        return recs[0] if recs else None

    def last(name):
        recs = by_name.get(name)
        return recs[-1] if recs else None

    intake, ft, done = first("intake"), first("first_token"), last("done")
    hosts: List[str] = []
    for rec in spans:
        h = str(rec.get("host", ""))
        if h and h not in hosts:
            hosts.append(h)
    migrations = by_name.get("migration", [])
    replayed = sum(int(m.get("replayed", 0)) for m in migrations)

    ttft = tpot = None
    tokens = done.get("tokens") if done else None
    if done is not None and done.get("ttft") is not None:
        ttft = float(done["ttft"])
    elif ft is not None and intake is not None:
        ttft = max(0.0, ft["t"] - intake["t"])
    if done is not None and done.get("tpot") is not None:
        tpot = float(done["tpot"])
    elif ft is not None and done is not None and tokens and tokens > 1:
        tpot = max(0.0, done["t"] - ft["t"]) / (tokens - 1)

    queue_wait = sum(float(r.get("dur", 0.0)) for r in by_name.get("queue", ()))
    prefill_s = sum(float(r.get("dur", 0.0)) for r in by_name.get("prefill", ()))
    stall_s = sum(float(r.get("dur", 0.0))
                  for r in by_name.get("reload_pause", ()))
    decode_rounds = len(by_name.get("decode_round", ()))
    # Disaggregated pipeline legs: export time on the prefill host plus
    # verify+import time on the decode host — the price of the split,
    # sitting right on the stitched critical path between them.
    ship_s = sum(float(r.get("dur", 0.0))
                 for r in by_name.get("block_ship", ()))
    import_s = sum(float(r.get("dur", 0.0))
                   for r in by_name.get("shipment_import", ()))

    # Wall-clock critical path: every span in time order with the host
    # that emitted it — the "where did this request spend its time" view.
    path = [{"span": r.get("span"), "host": str(r.get("host", "")),
             "t": r.get("t"), "dur": r.get("dur")} for r in spans]

    t0 = spans[0]["t"] if spans else None
    t1 = spans[-1]["t"] if spans else None
    return {
        "trace_id": spans[0]["trace_id"] if spans else "",
        "request_id": spans[0].get("id", "") if spans else "",
        "hosts": hosts,
        "migrated": bool(migrations),
        "migrations": len(migrations),
        "replayed": replayed,
        "spans": len(spans),
        "ttft": ttft,
        "tpot": tpot,
        "tokens": tokens,
        "reason": done.get("reason") if done else None,
        "done": done is not None,
        "queue_wait": queue_wait,
        "prefill_seconds": prefill_s,
        "reload_stall_seconds": stall_s,
        "decode_rounds": decode_rounds,
        "ship_seconds": ship_s,
        "shipment_import_seconds": import_s,
        "disaggregated": bool(by_name.get("decode_placement")
                              or by_name.get("block_ship")),
        "wall_seconds": (t1 - t0) if (t0 is not None and t1 is not None)
                        else None,
        "critical_path": path,
    }


def stitch(paths: Iterable[str]) -> List[Dict]:
    """load_traces + derive, sorted by request id: the machine-readable
    product of ``scripts/latency_report.py``."""
    traces = load_traces(paths)
    reqs = [derive(spans) for spans in traces.values()]
    reqs.sort(key=lambda r: (r["request_id"], r["trace_id"]))
    return reqs


def format_report(reqs: List[Dict], slo_ttft: Optional[float] = None,
                  slo_tpot: Optional[float] = None) -> str:
    """Human latency report: per-request critical-path table, TTFT/TPOT
    percentiles, and SLO attainment when targets are given."""
    lines = ["Request latency report"]
    lines.append(f"requests {len(reqs)} | "
                 f"migrated {sum(1 for r in reqs if r['migrated'])} | "
                 f"driver scripts/latency_report.py")
    lines.append("")
    lines.append(f"{'request':<10} {'hosts':<12} {'ttft_ms':>9} "
                 f"{'tpot_ms':>9} {'tokens':>7} {'rounds':>7} "
                 f"{'replayed':>9} {'stall_ms':>9} {'reason':<10}")
    lines.append("-" * 88)
    for r in reqs:
        ttft = f"{r['ttft'] * 1e3:.1f}" if r["ttft"] is not None else "-"
        tpot = f"{r['tpot'] * 1e3:.2f}" if r["tpot"] is not None else "-"
        stall = f"{r['reload_stall_seconds'] * 1e3:.0f}"
        lines.append(
            f"{r['request_id']:<10} {'>'.join(r['hosts']):<12} {ttft:>9} "
            f"{tpot:>9} {str(r['tokens'] if r['tokens'] is not None else '-'):>7} "
            f"{r['decode_rounds']:>7} {r['replayed']:>9} {stall:>9} "
            f"{str(r['reason'] or ('-' if r['done'] else 'UNFINISHED')):<10}")
    lines.append("")
    ttfts = [r["ttft"] for r in reqs if r["ttft"] is not None]
    tpots = [r["tpot"] for r in reqs if r["tpot"] is not None]
    for name, vals in (("ttft", ttfts), ("tpot", tpots)):
        if vals:
            lines.append(
                f"{name}: p50 {percentile(vals, 0.5) * 1e3:.1f} ms | "
                f"p95 {percentile(vals, 0.95) * 1e3:.1f} ms | "
                f"p99 {percentile(vals, 0.99) * 1e3:.1f} ms "
                f"(n={len(vals)})")
        else:
            lines.append(f"{name}: no finished requests")
    if slo_ttft is not None or slo_tpot is not None:
        ok = total = 0
        for r in reqs:
            if r["ttft"] is None and r["tpot"] is None:
                continue
            total += 1
            good = True
            if slo_ttft is not None and (r["ttft"] is None
                                         or r["ttft"] > slo_ttft):
                good = False
            if slo_tpot is not None and (r["tpot"] is None
                                         or r["tpot"] > slo_tpot):
                good = False
            ok += 1 if good else 0
        pct = 100.0 * ok / total if total else 0.0
        slo_bits = []
        if slo_ttft is not None:
            slo_bits.append(f"ttft <= {slo_ttft * 1e3:.0f} ms")
        if slo_tpot is not None:
            slo_bits.append(f"tpot <= {slo_tpot * 1e3:.1f} ms")
        lines.append(f"SLO ({' and '.join(slo_bits)}): "
                     f"{ok}/{total} attained ({pct:.1f}%)")
    return "\n".join(lines)
