"""Goodput accounting: stitch flight-recorder logs across restarts.

The reference proves its fault-tolerance chain with three Slurm ``.out``
files (timeout → resume → injected error → resume → scancel) that a human
reads side by side. This module reads the same chain from the structured
event logs (obs/events.py) and computes what production fault-tolerant
trainers treat as the headline reliability metrics (MegaScale,
arXiv:2402.15627; Meta's cluster reliability study, arXiv:2410.21680):

- **goodput %** — wall time spent on *net-new* training steps divided by the
  chain's total wall time. Step windows that re-train steps already reached
  by an earlier job (replay after a lossy restart) count as lost, not good.
- **MTTR** — per restart, the gap between the failing job's fault instant
  (its ``signal``/``exit`` event, else its last event) and the next job's
  first completed step window.
- **replayed tokens** — per restart, (previous job's max step − restored
  step) × tokens/step: the compute re-bought after each resume. Zero in
  this framework's no-lost-steps design for save-bearing exits; non-zero
  after a no-save exit (scancel) or a periodic-checkpoint gap.
- **time lost per failure class** — restart downtime + replay wall,
  attributed to the failing job's class (``timeout``/``error``/``cancel``).

Input is one or more JSONL event files (typically
``<ckpt-path>/events/events_<jobid>.jsonl``, one per Slurm job in the
chain); jobs are ordered by first event time. ``scripts/goodput_report.py``
is the CLI.
"""

import dataclasses
import glob as _glob
import os
from typing import Dict, List, Optional, Sequence

from .events import read_events

FAILURE_CLASSES = {10: "timeout", 15: "cancel", -1: "error"}


def failure_class(error_type: Optional[int]) -> str:
    if error_type is None:
        return "unknown"
    return FAILURE_CLASSES.get(int(error_type), "unknown")


@dataclasses.dataclass
class Restart:
    from_job: str
    to_job: str
    failure: str               # timeout | error | cancel | unknown
    fault_t: float             # fault instant in the failing job
    recovered_t: float         # first completed step window in the next job
    restored_step: Optional[int]
    prev_max_step: Optional[int]
    replayed_steps: int
    replayed_tokens: int
    replay_seconds: float      # wall re-spent re-training replayed steps
    restart_seconds: float     # recovered_t - fault_t (scheduler + setup)

    @property
    def mttr_seconds(self) -> float:
        return self.restart_seconds

    @property
    def lost_seconds(self) -> float:
        return self.restart_seconds + self.replay_seconds


@dataclasses.dataclass
class GoodputReport:
    jobs: List[str]
    wall_seconds: float
    productive_seconds: float
    replay_seconds: float
    restarts: List[Restart]
    steps_reached: Optional[int]
    tokens_trained: int        # net-new tokens (replays not double-counted)
    tokens_replayed: int

    @property
    def goodput_pct(self) -> float:
        if self.wall_seconds <= 0:
            return 0.0
        return 100.0 * self.productive_seconds / self.wall_seconds

    @property
    def mttr_seconds(self) -> float:
        if not self.restarts:
            return 0.0
        return sum(r.mttr_seconds for r in self.restarts) / len(self.restarts)

    @property
    def lost_by_class(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.restarts:
            out[r.failure] = out.get(r.failure, 0.0) + r.lost_seconds
        return out


def _group_jobs(events: Sequence[dict]) -> List[List[dict]]:
    """Split a flat event list into per-job runs ordered by first event."""
    by_job: Dict[str, List[dict]] = {}
    for ev in events:
        by_job.setdefault(str(ev.get("job", "local")), []).append(ev)
    jobs = []
    for evs in by_job.values():
        evs.sort(key=lambda e: e["t"])
        jobs.append(evs)
    jobs.sort(key=lambda evs: evs[0]["t"])
    return jobs


def _window_steps(ev: dict) -> int:
    return int(ev.get("steps", 1))


def _fault_event(evs: Sequence[dict]) -> Optional[dict]:
    """The fault instant of one job: the first signal event if any, else the
    exit verdict, else None (the job simply stopped — SIGKILL/node loss)."""
    for ev in evs:
        if ev["kind"] == "signal":
            return ev
    for ev in evs:
        if ev["kind"] == "exit":
            return ev
    return None


def stitch(events: Sequence[dict]) -> GoodputReport:
    """Fold a (possibly multi-job) event list into a :class:`GoodputReport`.

    Step accounting walks each job's ``step`` windows (payload ``steps`` =
    steps covered, ``dur`` = window wall, ``step`` = last step in the
    window). A window whose steps were already reached by an earlier job in
    the chain is replay: its wall time moves from the productive to the
    replay bucket and its tokens count as re-trained.
    """
    jobs = _group_jobs(events)
    if not jobs:
        return GoodputReport(jobs=[], wall_seconds=0.0,
                             productive_seconds=0.0, replay_seconds=0.0,
                             restarts=[], steps_reached=None,
                             tokens_trained=0, tokens_replayed=0)

    wall = jobs[-1][-1]["t"] - jobs[0][0]["t"]
    productive = 0.0
    replay_total = 0.0
    tokens_new = 0
    tokens_replayed_total = 0
    restarts: List[Restart] = []
    high_water: Optional[int] = None  # max step reached by earlier jobs
    max_step: Optional[int] = None

    for i, evs in enumerate(jobs):
        job_id = str(evs[0].get("job", "local"))
        job_max: Optional[int] = None
        job_replay_seconds = 0.0
        job_replayed_steps = 0
        job_replayed_tokens = 0
        first_step_t: Optional[float] = None
        restored: Optional[int] = None
        for ev in evs:
            if ev["kind"] == "ckpt_restore" and restored is None:
                restored = ev.get("step")
            if ev["kind"] == "resume" and restored is None:
                restored = ev.get("step")
            if ev["kind"] != "step" or "step" not in ev:
                continue
            if first_step_t is None:
                first_step_t = ev["t"]
            last = int(ev["step"])
            n = _window_steps(ev)
            dur = float(ev.get("dur") or 0.0)
            tokens = int(ev.get("tokens", 0))
            job_max = last if job_max is None else max(job_max, last)
            if high_water is not None and last <= high_water:
                # whole window re-trains already-reached steps
                job_replay_seconds += dur
                job_replayed_steps += n
                job_replayed_tokens += tokens
            elif high_water is not None and last - n + 1 <= high_water:
                # window straddles the high-water mark: pro-rate
                replayed = high_water - (last - n)
                frac = replayed / max(n, 1)
                job_replay_seconds += dur * frac
                job_replayed_steps += replayed
                job_replayed_tokens += int(tokens * frac)
                productive += dur * (1 - frac)
                tokens_new += tokens - int(tokens * frac)
            else:
                productive += dur
                tokens_new += tokens
        replay_total += job_replay_seconds
        tokens_replayed_total += job_replayed_tokens

        if i > 0:
            prev = jobs[i - 1]
            fault = _fault_event(prev)
            fault_t = fault["t"] if fault is not None else prev[-1]["t"]
            error_type = None
            if fault is not None:
                error_type = fault.get("error_type", fault.get("signum"))
            recovered_t = (first_step_t if first_step_t is not None
                           else evs[-1]["t"])
            restarts.append(Restart(
                from_job=str(prev[0].get("job", "local")), to_job=job_id,
                failure=failure_class(error_type), fault_t=fault_t,
                recovered_t=recovered_t, restored_step=restored,
                prev_max_step=high_water,
                replayed_steps=job_replayed_steps,
                replayed_tokens=job_replayed_tokens,
                replay_seconds=job_replay_seconds,
                restart_seconds=max(0.0, recovered_t - fault_t)))

        if job_max is not None:
            high_water = (job_max if high_water is None
                          else max(high_water, job_max))
            max_step = high_water

    return GoodputReport(
        jobs=[str(evs[0].get("job", "local")) for evs in jobs],
        wall_seconds=wall, productive_seconds=productive,
        replay_seconds=replay_total, restarts=restarts,
        steps_reached=max_step, tokens_trained=tokens_new,
        tokens_replayed=tokens_replayed_total)


def load_chain(paths: Sequence[str]) -> List[dict]:
    """Read events from files, directories, or globs, flattened."""
    events: List[dict] = []
    for p in paths:
        if os.path.isdir(p):
            files = sorted(_glob.glob(os.path.join(p, "*.jsonl")))
        else:
            files = sorted(_glob.glob(p)) or [p]
        for f in files:
            events.extend(read_events(f))
    return events


def format_report(report: GoodputReport) -> str:
    """Human-readable goodput report (the CLI's output)."""
    lines = []
    lines.append("Goodput report")
    lines.append("=" * 64)
    lines.append(f"jobs in chain     : {len(report.jobs)} "
                 f"({', '.join(report.jobs) or '-'})")
    lines.append(f"steps reached     : "
                 f"{report.steps_reached if report.steps_reached is not None else '-'}")
    lines.append(f"chain wall        : {report.wall_seconds:,.1f} s")
    lines.append(f"productive        : {report.productive_seconds:,.1f} s")
    lines.append(f"replayed          : {report.replay_seconds:,.1f} s "
                 f"({report.tokens_replayed:,} tokens re-trained)")
    lines.append(f"tokens trained    : {report.tokens_trained:,} (net new)")
    lines.append(f"goodput           : {report.goodput_pct:.1f} %")
    lines.append(f"restarts          : {len(report.restarts)} | "
                 f"MTTR {report.mttr_seconds:,.1f} s")
    if report.restarts:
        lines.append("")
        lines.append(f"{'from -> to':<22} {'class':<8} {'MTTR s':>8} "
                     f"{'replay s':>9} {'replayed steps':>14} "
                     f"{'restored@':>10}")
        for r in report.restarts:
            restored = r.restored_step if r.restored_step is not None else "-"
            lines.append(
                f"{r.from_job + ' -> ' + r.to_job:<22} {r.failure:<8} "
                f"{r.mttr_seconds:>8.1f} {r.replay_seconds:>9.1f} "
                f"{r.replayed_steps:>14} {str(restored):>10}")
        lines.append("")
        lines.append("time lost by failure class:")
        for cls, secs in sorted(report.lost_by_class.items()):
            lines.append(f"  {cls:<8} {secs:>10.1f} s")
    return "\n".join(lines)
